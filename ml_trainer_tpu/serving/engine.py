"""Slot-based continuous-batching decode engine.

``generate()`` is one-shot: a whole batch prefills together, decodes in
lockstep, and every row waits for the slowest (the convoy effect); a new
batch shape means a new compile.  This engine serves requests that
arrive at arbitrary times through ONE preallocated KV-cache block and
ONE compiled per-token decode program:

* **Slots.**  The cache is the flax ``decode``-mode cache built at batch
  ``max_batch`` — per attention layer ``[max_batch, H, max_len, D]`` —
  with the scalar ``cache_index``/``pos_index`` leaves widened to
  per-row ``[max_batch]`` vectors (models/layers.py's slot-indexed
  path), so every row sits at its OWN sequence position.  A request owns
  one row (slot) for its lifetime.

* **Prefill.**  A new request prefills OUT OF BAND at batch 1: its
  prompt is right-padded to the next power-of-two bucket (at most
  log2(max_len) compiled prefill programs — ``generate_ragged``'s
  bucketing trick applied to length instead of batch), one batched
  causal forward fills a fresh batch-1 cache, the true-length logits
  sample token 0, and the rows are inserted into the slot cache with the
  index vectors set to the TRUE prompt length.  Padding garbage beyond
  the true length is never attended: the decode mask is
  ``arange(max_len) <= index[slot]`` and later tokens overwrite it.

* **Decode.**  All slots advance through a single compiled step —
  ``[max_batch, 1]`` tokens in, one forward, per-row sampling out.
  Requests join (prefill + insert) and leave (EOS / budget / deadline)
  at token boundaries with NO recompilation: shapes are static, inactive
  slots just compute masked garbage that nobody reads.

Sampling matches ``generate()`` token-for-token per request: greedy is
``argmax``; ``temperature > 0`` draws
``categorical(fold_in(rng, t), logits / temperature)`` with the
request's own rng and per-token counter ``t`` — byte-identical to a
standalone batch-1 ``generate()`` call for the same request.

Compiled programs (prefill buckets, the decode step, the slot insert)
live in the process-wide LRU shared with ``generate._COMPILED``, so one
bound covers every decode executable in the process.

* **Speculative mode** (``spec_k > 0``, see speculative.py and
  docs/serving.md): each step drafts ``spec_k`` tokens per slot (n-gram
  lookup over the request's own history, or a vocab-compatible draft
  model with its own slot cache) and ONE verify forward over a
  ``[max_batch, spec_k+1]`` window commits a variable 1..spec_k+1
  tokens per slot — still one static-shaped executable at fixed K, so
  join/leave semantics and the no-recompilation guarantee carry over
  unchanged.  Greedy slots stay byte-identical to ``generate()``.

* **Paged mode** (``kv_page_size > 0``, see kv_pool.py,
  prefix_cache.py and docs/serving.md): the per-slot contiguous
  ``[max_batch, H, max_len, D]`` regions become ONE pool of fixed-size
  pages addressed through host-owned per-slot page tables
  (models/layers.py's paged gather/scatter path — still one static
  executable, the table is an ordinary input).  What that buys:

  - memory tracks LIVE tokens, not ``max_batch × max_len`` worst case;
  - a radix prefix cache maps shared prompt prefixes to already-filled
    refcounted pages, so a prefix hit skips their prefill entirely —
    only the unshared suffix runs (a ``serve_prefill_paged``
    continuation window at the slot's dynamic offset).  The cache is
    NAMESPACED BY TENANT by default (``prefix_scope="tenant"``): cache
    residency is observable (TTFT, hit-rate metrics), so a shared trie
    would let one tenant probe another's prompt/generated content
    block-by-block; ``prefix_scope="global"`` opts trusted deployments
    back into cross-tenant sharing;
  - under page pressure the engine evicts cold prefix pages first, then
    PREEMPTS a victim request: its written pages are donated to the
    prefix cache, the rest freed, and the request re-queues with its
    generated tokens as a resumable prefix (flight-recorder ``preempt``
    event; a structured client error after ``max_preemptions``).

  Requests with no prefix hit still prefill through the SAME contiguous
  batch-1 program as the contiguous engine and are scatter-inserted
  into their pages bit-for-bit, which is what keeps greedy and
  speculative output byte-identical to the contiguous path.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.generate import _COMPILED, _cache_shapes, _empty_cache
from ml_trainer_tpu.serving.kv_pool import KVPagePool
from ml_trainer_tpu.serving.metrics import ServingMetrics
from ml_trainer_tpu.serving.prefix_cache import PrefixCache
from ml_trainer_tpu.serving.scheduler import Request
from ml_trainer_tpu.telemetry.flight import get_recorder
from ml_trainer_tpu.telemetry.spans import StepProfiler, span
from ml_trainer_tpu.speculative import (
    DraftModelDrafter,
    NgramDrafter,
    build_draft_scan,
    build_verify,
)


def _as_key(rng) -> np.ndarray:
    """Normalize a request rng (None | int seed | PRNG key) to raw
    uint32[2] key data.  None matches ``generate()``'s PRNGKey(0)
    default so an rng-less sampled request reproduces the rng-less
    ``generate()`` call."""
    if rng is None:
        rng = 0
    if isinstance(rng, (int, np.integer)):
        rng = jax.random.PRNGKey(int(rng))
    key = np.asarray(rng, np.uint32).reshape(-1)
    if key.shape != (2,):
        raise ValueError(f"rng must be an int seed or a PRNG key, got {rng!r}")
    return key


def _sample_rows(last, temps, rngs, steps):
    """Per-row sampling: greedy argmax where ``temps == 0``, else
    ``categorical(fold_in(rng_row, t_row), last_row / temp_row)`` — the
    same draw ``generate()`` makes for that request at token ``t``."""
    greedy_tok = jnp.argmax(last, axis=-1)
    keys = jax.vmap(jax.random.fold_in)(rngs, steps)
    safe = jnp.where(temps > 0, temps, 1.0)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, last / safe)
    return jnp.where(temps > 0, sampled, greedy_tok)


def _leaf_name(path) -> Optional[str]:
    """Last dict key of a tree path (None for non-dict paths)."""
    return getattr(path[-1], "key", None) if path else None


class SlotDecodeEngine:
    """The slot cache plus its compiled programs.  Single-threaded by
    design: one worker (serving/api.py's loop) calls ``admit`` and
    ``step``; thread-safe admission lives in the scheduler."""

    def __init__(self, model, variables: dict, max_batch: int = 8,
                 metrics: Optional[ServingMetrics] = None,
                 spec_k: int = 0, drafter="ngram",
                 draft_variables: Optional[dict] = None,
                 ngram_n: int = 3,
                 kv_page_size: int = 0, kv_pages: int = 0,
                 paged_kernel: bool = False,
                 quant_int8: bool = False,
                 prefix_cache: bool = True,
                 prefix_scope: str = "tenant",
                 max_preemptions: int = 8,
                 adapters=None,
                 prefill_chunk: int = 0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if not getattr(model, "max_len", 0):
            raise ValueError(
                "serving needs a causal LM exposing decode/max_len "
                f"(got {type(model).__name__})"
            )
        if spec_k < 0 or spec_k >= int(model.max_len):
            raise ValueError(
                f"spec_k must be in [0, max_len={model.max_len}), "
                f"got {spec_k}"
            )
        self.model = model
        self.max_batch = max_batch
        self.max_len = int(model.max_len)
        self.vocab_size = int(model.vocab_size)
        self.metrics = metrics if metrics is not None else ServingMetrics()

        # -- paged KV mode (opt-in) -------------------------------------
        self.kv_page_size = int(kv_page_size)
        self.paged = self.kv_page_size > 0
        self.pool: Optional[KVPagePool] = None
        self._prefix: Optional[PrefixCache] = None
        self.max_preemptions = int(max_preemptions)
        if prefix_scope not in ("tenant", "global"):
            raise ValueError(
                f"prefix_scope must be 'tenant' or 'global', got "
                f"{prefix_scope!r}"
            )
        self.prefix_scope = prefix_scope
        self._preempted: List[Request] = []
        if self.paged:
            if self.max_len % self.kv_page_size:
                raise ValueError(
                    f"kv_page_size ({kv_page_size}) must divide max_len "
                    f"({self.max_len})"
                )
            pages_per_slot = self.max_len // self.kv_page_size
            # Default pool: full contiguous capacity + the trash page —
            # no oversubscription until the caller asks for it.
            self.kv_pages = int(kv_pages) or max_batch * pages_per_slot + 1
            self.pool = KVPagePool(
                self.kv_pages, self.kv_page_size, self.max_len, max_batch
            )
            if prefix_cache:
                self._prefix = PrefixCache(self.pool)
            # The model whose decode cache is paged: compiled decode /
            # verify / continuation programs key on THIS clone, so a
            # paged and a contiguous engine in one process never collide
            # in the compile cache.
            self._key_model = model.clone(
                kv_page_size=self.kv_page_size, kv_pages=self.kv_pages
            )
        else:
            if kv_pages:
                raise ValueError("kv_pages needs kv_page_size > 0")
            self.kv_pages = 0
            self._key_model = model

        # -- Pallas kernel knobs (ops/kernels/; docs/kernels.md) --------
        # paged_kernel fuses the page-table gather into the S == 1
        # decode attention; quant_int8 swaps the decode projections to
        # int8 weights + per-column scales (prefill and verify stay
        # fp32).  Both dispatch to lax references off-TPU, so CPU bytes
        # never change when a knob flips.
        self.paged_kernel = bool(paged_kernel)
        if self.paged_kernel:
            if not self.paged:
                raise ValueError(
                    "paged_kernel needs paged KV (kv_page_size > 0): "
                    "the kernel fuses the page-table gather into the "
                    "decode attention step"
                )
            try:
                self._key_model = self._key_model.clone(paged_kernel=True)
            except TypeError as e:
                raise ValueError(
                    f"{type(model).__name__} does not carry the "
                    "paged_kernel knob (only the GPT-2 family)"
                ) from e
        self.quant_int8 = bool(quant_int8)
        if self.quant_int8:
            if spec_k:
                raise ValueError(
                    "quant_int8 with spec_k > 0 is not supported: the "
                    "verify window runs the fp32 program, so acceptance "
                    "would compare int8 drafts against fp32 verify "
                    "(serve quantized with spec_k=0)"
                )
            if adapters is not None:
                raise ValueError(
                    "quant_int8 with adapters is not supported: LoRA "
                    "deltas attach to the fp32 projections the "
                    "quantized program does not read (serve quantized "
                    "without adapters)"
                )

        # -- chunked prefill (opt-in; page-aligned windows) --------------
        # Long prompts prefill in ``prefill_chunk``-token windows through
        # the paged continuation program, with decode ticks interleaved
        # between windows (serving/api.py advances one window per loop
        # iteration) — one long prompt can no longer head-of-line-block
        # every short request's TTFT.
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk:
            if not self.paged:
                raise ValueError(
                    "prefill_chunk needs paged KV (kv_page_size > 0): "
                    "chunk windows are continuation-window prefills at "
                    "the slot's page-aligned offset"
                )
            if self.prefill_chunk % self.kv_page_size:
                raise ValueError(
                    f"prefill_chunk ({prefill_chunk}) must be a multiple "
                    f"of kv_page_size ({kv_page_size}): every window "
                    "boundary must land on a page boundary"
                )
            if spec_k:
                raise ValueError(
                    "prefill_chunk with spec_k > 0 is not supported yet: "
                    "the draft cache has no continuation-window prefill "
                    "(serve chunked prefill with spec_k=0)"
                )
        # Chunk-in-progress slots: slot -> dispatch state.  These hold
        # their slot (free_capacity counts them) but are not yet in
        # ``_active`` — decode steps skip them until the final window.
        self._chunked: Dict[int, dict] = {}

        # -- batched LoRA adapter pool (opt-in; docs/serving.md) --------
        # The model clones with ``lora_slots > 0``: every targeted Dense
        # gains pool stacks in the "lora" collection and a per-row
        # gathered delta — ONE program for any adapter mix, slot 0 the
        # all-zero trash adapter, so adapter=None rows stay
        # bit-identical to a LoRA-free engine.
        self.adapters = None
        self._lora_on = False
        self._prefill_model = model
        if adapters is not None:
            from ml_trainer_tpu.serving.adapter_pool import (
                AdapterConfig,
                AdapterPool,
            )

            if isinstance(adapters, dict):
                adapters = AdapterConfig(**adapters)
            if not isinstance(adapters, AdapterConfig):
                raise ValueError(
                    "adapters must be an AdapterConfig (or its kwargs "
                    f"dict), got {type(adapters).__name__}"
                )
            if spec_k:
                raise ValueError(
                    "adapters with spec_k > 0 is not supported yet: the "
                    "speculative verify window does not thread the "
                    "adapter gather (serve adapters with spec_k=0)"
                )
            lora_kw = dict(
                lora_rank=int(adapters.rank),
                lora_slots=int(adapters.slots),
                lora_targets=tuple(adapters.targets),
            )
            try:
                self._key_model = self._key_model.clone(**lora_kw)
                self._prefill_model = model.clone(**lora_kw)
            except TypeError as e:
                raise ValueError(
                    f"{type(model).__name__} does not carry the lora_* "
                    "knobs (only the GPT-2 family serves adapters)"
                ) from e
            self.adapters = AdapterPool(adapters)  # registers sources
            self._lora_on = True
        self.dm = self._key_model.clone(decode=True)
        # Prefill ALWAYS runs the contiguous batch-1 program (shared
        # with contiguous engines — and the anchor that keeps paged
        # output byte-identical): its cache is scatter-inserted into the
        # pages afterwards.  (With adapters the prefill model is the
        # lora clone: the adapter shapes the cached K/V, so the prefill
        # program gathers the request's adapter too.)
        self._dm_prefill = self._prefill_model.clone(decode=True)
        self.params = (
            variables["params"] if "params" in variables else variables
        )
        # Identity of the weights this engine serves — KV migrated
        # between engines is only portable when the fingerprints match
        # (transfer.import_kv_slot refuses with WeightsMismatch
        # otherwise); a deploy's generation boundary is keyed on it.
        from ml_trainer_tpu.checkpoint import weights_fingerprint

        self.weights_fp = weights_fingerprint({"params": self.params})
        # Decode-only int8 clone + the host-built "quant" collection
        # (ops/kernels/quantize_tree): prefill / verify / continuation
        # windows keep running the fp32 ``self.dm`` programs — only the
        # S == 1 decode program reads the quantized weights.
        self._dm_quant = None
        self._quant = None
        if self.quant_int8:
            try:
                self._dm_quant = self.dm.clone(quant_int8=True)
            except TypeError as e:
                raise ValueError(
                    f"{type(model).__name__} does not carry the "
                    "quant_int8 knob (only the GPT-2 family)"
                ) from e
            from ml_trainer_tpu.ops.kernels.int8_matmul import quantize_tree

            self._quant = quantize_tree(self.params)
            if not self._quant:
                raise ValueError(
                    "quant_int8 found no quantizable projections in the "
                    "params tree (expected qkv/proj/fc_in/fc_out Dense "
                    "kernels)"
                )

        # Batch-1 cache shapes for prefill; slot cache at max_batch with
        # the scalar index leaves widened to [max_batch] vectors.
        self._shapes_b1 = _cache_shapes(self._dm_prefill, 1, jnp.int32)
        shapes_mb = _cache_shapes(self.dm, max_batch, jnp.int32)
        self.cache = jax.tree.map(
            lambda s: jnp.zeros(
                (max_batch,) if s.ndim == 0 else s.shape, s.dtype
            ),
            shapes_mb,
        )
        self.tok = jnp.zeros((max_batch, 1), jnp.int32)
        self._temps = np.zeros((max_batch,), np.float32)
        self._rngs = np.zeros((max_batch, 2), np.uint32)
        self._steps = np.zeros((max_batch,), np.int32)
        # Per-slot adapter index (0 = trash = base model) + the device
        # stacks the rows gather from.  Stacks are ordinary program
        # inputs: uploading an adapter into a slot row (the one compiled
        # scatter below) or repointing a row never recompiles.
        self._adapter_rows = np.zeros((max_batch,), np.int32)
        self._lora_stacks = None
        if self._lora_on:
            full_shapes = jax.eval_shape(
                lambda p: self.dm.init(
                    {"params": p}, jnp.zeros((max_batch, 1), jnp.int32),
                    train=False,
                ),
                jax.random.PRNGKey(0),
            )
            stack_shapes = {
                k: v for k, v in full_shapes["lora"].items()
                if k != "adapter_idx"
            }
            self._lora_stacks = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), stack_shapes
            )
            from jax import tree_util as _tu

            flat = _tu.tree_flatten_with_path(self._lora_stacks)
            self._stack_treedef = flat[1]
            self._stack_paths = [
                "/".join(str(getattr(k, "key", k)) for k in p)
                for p, _ in flat[0]
            ]
            self._stack_shapes = {
                path: tuple(leaf.shape)
                for path, (_, leaf) in zip(self._stack_paths, flat[0])
            }
            self._upload = self._program(
                ("adapter_upload", self._key_model, max_batch),
                self._build_adapter_upload,
            )
            # Warm the upload program NOW (zeros over the trash slot's
            # zeros — a no-op write), so the first real hot-load under
            # live traffic mints no compile.
            zero_rows = _tu.tree_unflatten(
                self._stack_treedef,
                [np.zeros(self._stack_shapes[p][1:], np.float32)
                 for p in self._stack_paths],
            )
            self._lora_stacks = self._upload(
                self._lora_stacks, zero_rows, np.int32(0)
            )
        self._active: Dict[int, Request] = {}
        self._step_seq = 0  # decode steps run (the decode_wedge fault clock)
        # Overload control (serving/overload.py, set via
        # Server.set_degradation): the active degradation-ladder rung
        # (0 = full service), the retry_after a shed client is told,
        # and whether speculative decode is enabled (rung 2 turns it
        # off WITHOUT recompiling — the vanilla decode program always
        # exists, and greedy streams are byte-identical either way).
        self.degradation_level = 0
        self.shed_retry_after = 2.0
        self.spec_enabled = True
        # Telemetry: flight ring for crash forensics (the watchdog dumps
        # it when the loop wedges) and the on-demand profile window the
        # admin endpoint arms (POST /admin/profile).
        self._flight = get_recorder()
        self._profiler = StepProfiler("serve")

        self._decode = self._program(
            ("serve_decode_int8" if self.quant_int8 else "serve_decode",
             self._key_model, max_batch),
            self._build_decode,
        )
        if self.paged:
            self._insert = self._program(
                ("serve_insert_paged", self._key_model, max_batch),
                self._build_insert_paged,
            )
        else:
            self._insert = self._program(
                ("serve_insert", model, max_batch), self._build_insert
            )
        # Host mirror of each slot's consumed-token count (device
        # ``cache_index``): spec mode always needs it for the verify
        # window; paged mode needs it for page allocation.
        self._pos = np.zeros((max_batch,), np.int32)

        # -- speculative decoding (opt-in; see speculative.py) ----------
        # Slots advance a variable 1..spec_k+1 tokens per verify step;
        # all shapes stay static at fixed spec_k, so ragged join/leave
        # traffic still never recompiles.
        self.spec_k = int(spec_k)
        self._ngram: Optional[NgramDrafter] = None
        self._draft: Optional[DraftModelDrafter] = None
        if self.spec_k:
            if drafter == "ngram":
                self._ngram = NgramDrafter(k=self.spec_k, n=ngram_n)
            elif isinstance(drafter, DraftModelDrafter):
                self._draft = drafter
            elif hasattr(drafter, "max_len"):
                if draft_variables is None:
                    raise ValueError(
                        "a draft model needs draft_variables (its params)"
                    )
                self._draft = DraftModelDrafter(drafter, draft_variables)
            else:
                raise ValueError(
                    "drafter must be 'ngram', a DraftModelDrafter or a "
                    f"registry model, got {drafter!r}"
                )
            self._verify = self._program(
                ("spec_verify", self._key_model, max_batch, self.spec_k + 1),
                lambda: build_verify(self._key_model, max_batch,
                                     self.spec_k + 1),
            )
            # Write caps per slot (the verify window writes spec_k+1
            # positions at pos, so pos is clamped to keep every write
            # inside max_len).
            self._caps = np.full(
                (max_batch,), self.max_len - self.spec_k - 1, np.int32
            )
            if self._draft is not None:
                self._draft.check_compatible(model)
                d_model = self._draft.model
                if int(d_model.max_len) < self.max_len:
                    raise ValueError(
                        f"draft model max_len ({d_model.max_len}) must "
                        f"cover the target's ({self.max_len})"
                    )
                # The draft model keeps the CONTIGUOUS slot cache: it is
                # sized tiny by design (gpt2_nano-class), so paging its
                # K/V buys nothing and would double the page machinery.
                self._draft_dm = d_model.clone(decode=True)
                self._draft_shapes_b1 = _cache_shapes(
                    self._draft_dm, 1, jnp.int32
                )
                d_shapes = _cache_shapes(self._draft_dm, max_batch, jnp.int32)
                self._draft_cache = jax.tree.map(
                    lambda s: jnp.zeros(
                        (max_batch,) if s.ndim == 0 else s.shape, s.dtype
                    ),
                    d_shapes,
                )
                self._draft_tok = jnp.zeros((max_batch, 1), jnp.int32)
                self._draft_scan = self._program(
                    ("spec_draft", d_model, max_batch, self.spec_k),
                    lambda: build_draft_scan(
                        d_model, max_batch, self.spec_k
                    ),
                )
                self._draft_insert = self._program(
                    ("serve_insert", d_model, max_batch), self._build_insert
                )

    # -- compiled programs ----------------------------------------------

    def _program(self, key, build):
        run = _COMPILED.get(key)
        if run is None:
            run = build()
            _COMPILED[key] = run
        return run

    def _build_decode(self):
        dm = self.dm

        if self.quant_int8:
            qdm = self._dm_quant

            def step_quant(params, cache, tok, temps, rngs, steps, quant):
                # ``quant`` rides as an ordinary (non-donated) program
                # input, like the LoRA stacks: re-quantizing after a
                # weight hot-swap never recompiles.
                logits, mut = qdm.apply(
                    {"params": params, "cache": cache, "quant": quant},
                    tok, train=False, mutable=["cache"],
                )
                nxt = _sample_rows(logits[:, -1], temps, rngs, steps)
                return mut["cache"], nxt[:, None].astype(jnp.int32)

            return jax.jit(step_quant, donate_argnums=(1, 2))

        if self._lora_on:
            def step_lora(params, cache, tok, temps, rngs, steps, lora):
                logits, mut = dm.apply(
                    {"params": params, "cache": cache, "lora": lora},
                    tok, train=False, mutable=["cache"],
                )
                nxt = _sample_rows(logits[:, -1], temps, rngs, steps)
                return mut["cache"], nxt[:, None].astype(jnp.int32)

            return jax.jit(step_lora, donate_argnums=(1, 2))

        def step(params, cache, tok, temps, rngs, steps):
            logits, mut = dm.apply(
                {"params": params, "cache": cache}, tok,
                train=False, mutable=["cache"],
            )
            nxt = _sample_rows(logits[:, -1], temps, rngs, steps)
            return mut["cache"], nxt[:, None].astype(jnp.int32)

        return jax.jit(step, donate_argnums=(1, 2))

    # -- batched LoRA adapters (serving/adapter_pool.py) -----------------

    def _build_adapter_upload(self):
        """The one compiled hot-load program: scatter a prepared A/B row
        set into slot ``slot`` of every stack leaf.  Stacks are donated
        (updated in place); static shapes, so loading adapter #1000
        reuses the program minted at warmup."""
        def upload(stacks, rows, slot):
            return jax.tree.map(
                lambda s, r: s.at[slot].set(jnp.asarray(r, s.dtype)),
                stacks, rows,
            )

        return jax.jit(upload, donate_argnums=(0,))

    def _lora_vars(self, idx) -> dict:
        """The "lora" collection for one dispatch: the shared stacks
        plus the caller's per-row adapter index vector."""
        return {
            **self._lora_stacks,
            "adapter_idx": jnp.asarray(idx, jnp.int32),
        }

    def _bind_adapter(self, req: Request, slot: int) -> None:
        """Pin ``req``'s adapter for its slot lifetime: residency hit
        repoints the row; a miss uploads the registered artifact into a
        (possibly LRU-evicted) slot through the warm upload program.
        Raises ``UnknownAdapter`` / ``AdapterPoolExhausted`` (structured
        — the caller maps them to a client error, never a hang)."""
        if not req.adapter:
            self._adapter_rows[slot] = 0
            return
        aslot, upload = self.adapters.acquire(req.adapter)
        if upload is not None:
            from jax import tree_util as _tu

            from ml_trainer_tpu.serving.adapter_pool import prepare_upload

            meta, leaves = upload
            rows = prepare_upload(
                meta, leaves, self._stack_shapes, self.adapters.rank
            )
            rows_tree = _tu.tree_unflatten(
                self._stack_treedef,
                [rows[p] for p in self._stack_paths],
            )
            self._lora_stacks = self._upload(
                self._lora_stacks, rows_tree, np.int32(aslot)
            )
            req.mark("adapter_loaded", adapter=req.adapter, slot=aslot)
        self._adapter_rows[slot] = aslot
        self._push_adapter_metrics()

    def _release_adapter(self, slot: int) -> None:
        """Drop the slot's adapter pin (idempotent — the row zeroes on
        release, and row 0 is the unpinned trash adapter)."""
        if self.adapters is None:
            return
        idx = int(self._adapter_rows[slot])
        if idx:
            self._adapter_rows[slot] = 0
            self.adapters.release(idx)
            self._push_adapter_metrics()

    def _adapter_bytes_per_slot(self) -> int:
        """Device bytes ONE adapter slot occupies across every stack
        leaf (A and B, all layers/targets) — the pricing behind
        ``serving_adapter_pool_bytes{state=}``."""
        cached = getattr(self, "_bytes_per_adapter_slot", None)
        if cached is not None:
            return cached
        total = sum(
            int(l.nbytes) for l in jax.tree.leaves(self._lora_stacks)
        )
        self._bytes_per_adapter_slot = total // max(self.adapters.slots, 1)
        return self._bytes_per_adapter_slot

    def _push_adapter_metrics(self) -> None:
        if self.adapters is None:
            return
        pool = self.adapters
        counters = pool.counters()
        self.metrics.record_adapters(
            free=pool.free_count(), used=pool.used_count(),
            total=pool.slots - 1, resident=pool.resident(),
            hits=counters["hits"], loads=counters["loads"],
            evictions=counters["evictions"],
            bytes_per_slot=self._adapter_bytes_per_slot(),
        )

    def _build_insert(self):
        def insert(cache_big, tok_big, cache1, tok0, slot, true_len):
            def leaf(big, small):
                if big.ndim == small.ndim:
                    # K/V row replace: [1, H, L, D] into row ``slot``.
                    start = (slot,) + (0,) * (big.ndim - 1)
                    return jax.lax.dynamic_update_slice(
                        big, small.astype(big.dtype), start
                    )
                # Index vector vs the prefill's scalar: the slot's
                # position is the TRUE prompt length, not the padded
                # bucket the scalar advanced to.
                return big.at[slot].set(jnp.asarray(true_len, big.dtype))

            cache_big = jax.tree.map(leaf, cache_big, cache1)
            tok_big = jax.lax.dynamic_update_slice(
                tok_big, tok0[:, None], (slot, 0)
            )
            return cache_big, tok_big

        return jax.jit(insert, donate_argnums=(0, 1))

    def _build_insert_paged(self):
        """Scatter a contiguous batch-1 prefill cache into a slot's
        pages: position ``j`` of the b1 cache lands in page
        ``page_row[j // page_size]`` at offset ``j % page_size`` — a pure
        data movement, so the paged slot holds bit-for-bit the K/V the
        contiguous engine would.  ``page_row`` is the slot's full table
        row (trash-0 past its chain, where the bucket's padding garbage
        harmlessly lands)."""
        ps, L = self.kv_page_size, self.max_len
        from jax import tree_util

        def insert(cache_big, tok_big, cache1, tok0, slot, true_len,
                   page_row):
            page_of_pos = jnp.repeat(page_row, ps)          # [L]
            offs = jnp.arange(L) % ps
            big_flat, treedef = tree_util.tree_flatten_with_path(cache_big)
            small = {
                tuple(getattr(k, "key", str(k)) for k in p): leaf
                for p, leaf in tree_util.tree_flatten_with_path(cache1)[0]
            }
            out = []
            for path, big in big_flat:
                if _leaf_name(path) == "page_table":
                    out.append(big.at[slot].set(page_row.astype(big.dtype)))
                    continue
                sm = small[tuple(getattr(k, "key", str(k)) for k in path)]
                if big.ndim == 4:
                    rows = sm[0].transpose(1, 0, 2).astype(big.dtype)  # [L,H,D]
                    out.append(big.at[page_of_pos, :, offs, :].set(rows))
                else:
                    out.append(
                        big.at[slot].set(jnp.asarray(true_len, big.dtype))
                    )
            cache_big = tree_util.tree_unflatten(treedef, out)
            tok_big = jax.lax.dynamic_update_slice(
                tok_big, tok0[:, None], (slot, 0)
            )
            return cache_big, tok_big

        return jax.jit(insert, donate_argnums=(0, 1))

    def _build_prefill(self, bucket: int, dm=None, shapes=None,
                       lora: bool = False):
        dm = dm if dm is not None else self._dm_prefill
        shapes = shapes if shapes is not None else self._shapes_b1

        if lora:
            def prefill_lora(params, prompt_pad, true_len, temp, rng,
                             step0, lora_vars):
                cache = _empty_cache(shapes)
                logits, mut = dm.apply(
                    {"params": params, "cache": cache, "lora": lora_vars},
                    prompt_pad, train=False, mutable=["cache"],
                )
                last = jax.lax.dynamic_index_in_dim(
                    logits, true_len - 1, axis=1, keepdims=False
                )
                tok = _sample_rows(last, temp[None], rng[None], step0[None])
                return mut["cache"], tok.astype(jnp.int32)

            return jax.jit(prefill_lora)

        def prefill(params, prompt_pad, true_len, temp, rng, step0):
            cache = _empty_cache(shapes)
            logits, mut = dm.apply(
                {"params": params, "cache": cache}, prompt_pad,
                train=False, mutable=["cache"],
            )
            # Causal prefill: the padded tail cannot influence position
            # true_len-1, whose logits sample token 0 (fold counter
            # ``step0`` — 0 for fresh requests, the committed-token
            # count for a preempt-resume, so the sampled stream
            # continues generate()'s per-token fold sequence).
            last = jax.lax.dynamic_index_in_dim(
                logits, true_len - 1, axis=1, keepdims=False
            )
            tok = _sample_rows(last, temp[None], rng[None], step0[None])
            return mut["cache"], tok.astype(jnp.int32)

        return jax.jit(prefill)

    def _build_prefill_paged(self, bucket: int):
        """Continuation prefill for a PREFIX-CACHE hit: run only the
        unshared suffix (padded to ``bucket``) through the paged decode
        path at the slot's dynamic offset ``start`` — the suffix window
        attends the shared pages like a verify window attends committed
        tokens, writes its own K/V into the slot's fresh pages, and the
        true last position's logits sample the first new token.  The
        shared prefix's prefill is skipped entirely."""
        dm = self.dm
        lora_on = self._lora_on
        from jax import tree_util

        def run(cache_big, tok_big, params, window, true_len, start,
                page_row, temp, rng, step0, slot, *lora_rest):
            big_flat, treedef = tree_util.tree_flatten_with_path(cache_big)
            # Batch-1 view: shared pools as-is, this slot's table row and
            # start offset as the [1]-row metadata.
            view = []
            for path, leaf in big_flat:
                if leaf.ndim == 4:
                    view.append(leaf)
                elif _leaf_name(path) == "page_table":
                    view.append(page_row[None, :])
                else:
                    view.append(jnp.full((1,), start, leaf.dtype))
            cache1 = tree_util.tree_unflatten(treedef, view)
            variables = {"params": params, "cache": cache1}
            if lora_on:
                variables["lora"] = lora_rest[0]
            logits, mut = dm.apply(
                variables, window,
                train=False, mutable=["cache"],
            )
            last = jax.lax.dynamic_index_in_dim(
                logits, true_len - 1, axis=1, keepdims=False
            )
            tok = _sample_rows(
                last, temp[None], rng[None], step0[None]
            ).astype(jnp.int32)
            # Write back: pools carry the suffix K/V; slot metadata
            # advances to the full consumed length.
            mut_flat = tree_util.tree_flatten_with_path(mut["cache"])[0]
            out = []
            for (path, big), (_, new) in zip(big_flat, mut_flat):
                if big.ndim == 4:
                    out.append(new)
                elif _leaf_name(path) == "page_table":
                    out.append(big.at[slot].set(page_row.astype(big.dtype)))
                else:
                    out.append(
                        big.at[slot].set((start + true_len).astype(big.dtype))
                    )
            cache_big = tree_util.tree_unflatten(treedef, out)
            tok_big = jax.lax.dynamic_update_slice(
                tok_big, tok[:, None], (slot, 0)
            )
            return cache_big, tok_big, tok

        return jax.jit(run, donate_argnums=(0, 1))

    # -- paged memory management ----------------------------------------

    def _sync_table(self) -> None:
        """Upload the host page table into every layer's table leaf when
        it changed (slot freed / pages appended): a compiled step must
        never write through a stale device table into a recycled page.
        Each leaf gets its OWN device copy — donation-safe."""
        if not self.paged or not self.pool.dirty:
            return
        host = self.pool.page_table

        def leaf(l):
            if l.ndim == 2 and l.dtype == jnp.int32:
                return jnp.asarray(host)
            return l

        self.cache = jax.tree.map(leaf, self.cache)
        self.pool.dirty = False

    def _prefix_ns(self, req: Request) -> str:
        """Prefix-cache namespace for ``req``: its tenant by default, so
        whether a block is cached (observable via TTFT and the hit-rate
        metrics) never leaks one tenant's prompt or generated content to
        another; ``prefix_scope="global"`` opts a trusted deployment
        back into one shared trie.

        With adapters enabled the namespace ALWAYS also carries the
        request's adapter (even under prefix_scope="global"): cached
        K/V is a function of the adapter that prefilled it, so a hit
        under adapter X serving adapter Y would be silently-wrong
        logits, not just a side channel."""
        ns = req.tenant if self.prefix_scope == "tenant" else ""
        if self.adapters is not None:
            ns = f"{ns}\x1fadapter={req.adapter or ''}"
        return ns

    def _page_row(self, slot: int) -> np.ndarray:
        row = np.zeros((self.pool.pages_per_slot,), np.int32)
        chain = self.pool.slot_pages[slot]
        row[: len(chain)] = chain
        return row

    def _release_slot_pages(self, slot: int, req: Optional[Request] = None,
                            donate: bool = True) -> None:
        """Return a slot's pages to the pool (idempotent).  With
        ``donate``, its WRITTEN full blocks are first registered in the
        prefix cache — a finished request's prompt stays hot for the
        next user, and a preempted victim can re-pin its own pages on
        resume.  Also drops the slot's adapter pin (every slot-free
        path funnels through here, paged or contiguous)."""
        self._release_adapter(slot)
        if not self.paged:
            return
        chain = self.pool.slot_pages[slot]
        if chain and donate and self._prefix is not None and req is not None:
            blocks = int(self._pos[slot]) // self.kv_page_size
            if blocks:
                seq = np.concatenate([
                    np.asarray(req.prompt, np.int32).reshape(-1),
                    np.asarray(req.tokens, np.int32),
                ])
                self._prefix.insert(
                    seq, chain[:blocks], namespace=self._prefix_ns(req)
                )
        self.pool.reset_slot(slot)
        self._push_kv_metrics()

    def _kv_bytes_per_page(self) -> int:
        """Device bytes of ONE pool page across every layer's K and V:
        the page-geometry × dtype pricing behind
        ``serving_kv_pool_bytes{state=}`` (cached; the pool leaves are
        the cache entries whose leading dim is the page count)."""
        cached = getattr(self, "_bytes_per_page", None)
        if cached is not None:
            return cached
        pool_bytes = sum(
            int(l.nbytes)
            for l in jax.tree.leaves(self.cache)
            if getattr(l, "ndim", 0) >= 1 and l.shape[0] == self.kv_pages
        )
        self._bytes_per_page = pool_bytes // max(self.kv_pages, 1)
        return self._bytes_per_page

    def _push_kv_metrics(self) -> None:
        if not self.paged:
            return
        self.metrics.record_kv(
            self.pool.free_count(), self.pool.used_count(),
            self.kv_pages - 1,
            len(self._prefix) if self._prefix is not None else 0,
            bytes_per_page=self._kv_bytes_per_page(),
        )
        if self._prefix is not None:
            self.metrics.record_prefix_stats(
                self._prefix.hits, self._prefix.misses,
                self._prefix.hit_tokens, self._prefix.lookup_tokens,
            )

    def _pick_victim(self, exclude: int) -> Optional[int]:
        """Preemption victim: lowest priority first, youngest admission
        within a priority (losing the least completed work)."""
        candidates = [
            (req.priority, -(req.admitted_at or 0.0), slot)
            for slot, req in self._active.items()
            if slot != exclude
        ]
        if not candidates:
            return None
        candidates.sort()
        return candidates[0][2]

    def _preempt(self, slot: int, cause: str) -> None:
        """Evict ``slot``'s request under page pressure: donate its
        written blocks to the prefix cache, free the rest, and re-queue
        it (via ``drain_preempted``) with its generated tokens as a
        resumable prefix — or fail it with a structured error once it
        has been preempted ``max_preemptions`` times."""
        req = self._active.pop(slot)
        req.preemptions += 1
        req.mark("preempt", slot=slot, cause=cause)
        self._flight.record(
            "preempt", request=req.id, tenant=req.tenant, slot=slot,
            committed_tokens=len(req.tokens),
            preemptions=req.preemptions, cause=cause,
        )
        self.metrics.record_preemption(req.tenant)
        self._release_slot_pages(slot, req, donate=True)
        if req.preemptions > self.max_preemptions:
            req.finish(
                "error",
                f"request {req.id} (tenant '{req.tenant}') preempted "
                f"{req.preemptions}x under page pressure ({cause}); "
                f"giving up after max_preemptions={self.max_preemptions}",
            )
        else:
            self._preempted.append(req)

    def drain_preempted(self) -> List[Request]:
        """Preempted-but-resumable requests since the last call — the
        serving loop re-queues them (scheduler.requeue)."""
        out, self._preempted = self._preempted, []
        return out

    def _ensure_pages(self, window: int) -> List[int]:
        """Grow every active slot's page chain to cover its next
        ``window`` writes.  Under pressure: evict cold prefix pages
        first, then preempt victims (newest, lowest-priority first).
        Returns the slots freed by preemption."""
        freed: List[int] = []
        pool = self.pool
        for slot in sorted(self._active):
            if slot not in self._active:
                continue
            need_tokens = min(int(self._pos[slot]) + window, self.max_len)
            need = min(pool.pages_for(need_tokens), pool.pages_per_slot)
            short = need - pool.slot_page_count(slot)
            if short <= 0:
                continue
            pages = None
            while slot in self._active:
                pages = pool.allocate(short)
                if pages is not None:
                    break
                want = short - pool.free_count()
                cause = (
                    f"page_pressure: slot {slot} needs {short} page(s), "
                    f"{pool.free_count()} free of {self.kv_pages - 1}"
                )
                if (
                    self._prefix is not None
                    and self._prefix.evict(want) > 0
                ):
                    continue
                victim = self._pick_victim(exclude=slot)
                if victim is None:
                    # Nothing left to shed but this slot itself.
                    self._preempt(slot, cause)
                    freed.append(slot)
                    break
                self._preempt(victim, cause)
                freed.append(victim)
            if pages is not None and slot in self._active:
                pool.extend_slot(slot, pages)
        if freed:
            self._push_kv_metrics()
        return freed

    # -- KV migration (serving/transfer.py; disaggregated serving) -------

    def export_slot(self, slot: int):
        """Export ``slot``'s page chain + continuation state (the
        migration unit the router ships to a decode replica).  Read-only
        — the caller releases the slot afterwards if it migrates."""
        from ml_trainer_tpu.serving.transfer import export_kv_slot

        return export_kv_slot(self, slot)

    def import_slot(self, req: Request, slot: int, export) -> str:
        """Scatter an exported chain into ``slot`` bit-for-bit and
        register ``req`` as active; returns ``"active"`` or
        ``"no_memory"`` (target pool full — caller requeues ``req``,
        which resumes via the ordinary preempt-resume prefill)."""
        from ml_trainer_tpu.serving.transfer import import_kv_slot

        return import_kv_slot(self, req, slot, export)

    # -- serving ---------------------------------------------------------

    def free_capacity(self) -> int:
        return self.max_batch - len(self._active) - len(self._chunked)

    def active_count(self) -> int:
        return len(self._active)

    def chunking_count(self) -> int:
        return len(self._chunked)

    def admit(self, req: Request, slot: int) -> str:
        """Prefill ``req`` into ``slot`` and emit its first token.
        Returns ``"active"`` (decoding), ``"finished"`` (EOS on token 0
        or a one-token budget — the caller recycles the slot),
        ``"no_memory"`` (paged mode: the pool cannot hold the prompt
        right now — the caller re-queues the request and retries once
        running requests free pages), or ``"chunking"`` (chunked
        prefill engaged: the slot is held and ``advance_chunks`` runs
        one window per serving-loop iteration until the request
        activates)."""
        if slot in self._active:
            raise ValueError(f"slot {slot} is already occupied")
        if req.adapter and self.adapters is None:
            # A pool-less engine silently serving an adapter-named
            # request with BASE weights would be wrong output, not a
            # capacity problem — structured refusal instead.
            req.finish(
                "error",
                f"request {req.id} names adapter '{req.adapter}' but "
                "this engine has no adapter pool "
                "(Server(adapters=AdapterConfig(...)))",
            )
            return "finished"
        # Effective prompt: original prompt plus any tokens committed
        # before a preemption — resume is just admission with a longer
        # prompt (and the fold counter picking up where it left off).
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        done_tokens = len(req.tokens)
        if done_tokens:
            prompt = np.concatenate(
                [prompt, np.asarray(req.tokens, np.int32)]
            )
        p = prompt.shape[0]
        key = _as_key(req.rng)

        shared: List[int] = []
        c = 0
        if self.paged:
            if self._prefix is not None:
                # A retry of a previously blocked ("no_memory") admission
                # re-walks the trie but must not re-count stats or
                # re-heat this request's prefix pages' LRU stamps — the
                # serve loop retries every iteration under exactly the
                # page pressure that makes eviction order matter.
                shared, c = self._prefix.lookup(
                    prompt, (p - 1) // self.kv_page_size,
                    namespace=self._prefix_ns(req),
                    record=not req.kv_blocked,
                )
                req.prefix_hit_tokens = c
            if (
                self.degradation_level >= 3 and c == 0
                and done_tokens == 0 and self._prefix is not None
            ):
                # Rung 3 (hits_only): a FRESH prefix-cache miss is shed
                # with a structured 503 instead of spending a full
                # prefill the fleet cannot afford.  Resumes/preempted
                # requests (committed tokens) are never shed — the
                # byte-identity contract for running streams.
                if shared:
                    self.pool.release(shared)
                req.retry_after = self.shed_retry_after
                req.finish(
                    "shed",
                    f"request {req.id} (tenant '{req.tenant}') shed: "
                    "degradation rung hits_only admits prefix-cache "
                    f"hits only; retry after {self.shed_retry_after}s",
                )
                self.metrics.record_shed(req.tenant)
                return "finished"
            # Cover the prompt plus the first decode window so a fresh
            # admission cannot immediately trigger preemption.
            total_need = self.pool.pages_for(
                min(p + 1 + self.spec_k, self.max_len)
            )
            n_new = total_need - len(shared)
            pages = self.pool.allocate(n_new)
            if pages is None and self._prefix is not None:
                self._prefix.evict(n_new - self.pool.free_count())
                pages = self.pool.allocate(n_new)
            if pages is None:
                if shared:
                    self.pool.release(shared)
                if not self._active:
                    # Nothing running will ever free pages: the pool is
                    # simply too small for this request.  Structured
                    # error instead of an unserveable queue entry.
                    req.finish(
                        "error",
                        f"kv pool exhausted: request {req.id} (tenant "
                        f"'{req.tenant}') needs {n_new} page(s) beyond "
                        f"its prefix hit, pool has "
                        f"{self.pool.free_count()} of {self.kv_pages - 1}",
                    )
                    return "finished"
                self.metrics.record_admission_blocked()
                req.kv_blocked = True
                return "no_memory"
            self.pool.bind_slot(slot, shared + pages)
            req.kv_blocked = False

        if self.adapters is not None:
            from ml_trainer_tpu.serving.adapter_pool import (
                AdapterPoolExhausted,
                UnknownAdapter,
            )

            try:
                self._bind_adapter(req, slot)
            except (UnknownAdapter, AdapterPoolExhausted) as e:
                # Structured error naming the adapter — never a hang;
                # any KV pages bound above unwind with the slot.
                if self.paged:
                    self.pool.reset_slot(slot)
                    self._push_kv_metrics()
                req.finish("error", str(e))
                return "finished"

        req.slot = slot
        req.state = "active"
        if self.prefill_chunk and (p - c) > self.prefill_chunk:
            return self._admit_chunked(req, slot, prompt, c, key,
                                       done_tokens)
        req.mark(
            "prefill_start", slot=slot,
            kind="continuation" if (self.paged and c > 0) else "full",
            prefix_hit_tokens=c, resumed_tokens=done_tokens,
        )
        t0 = time.perf_counter()
        if self.paged and c > 0:
            tok0 = self._admit_paged_continuation(
                req, slot, prompt, c, key, done_tokens
            )
        else:
            tok0 = self._admit_full_prefill(
                req, slot, prompt, key, done_tokens
            )
        if self.spec_k:
            self._caps[slot] = min(
                p + (req.max_new_tokens - done_tokens) - 1,
                self.max_len - self.spec_k - 1,
            )
            if self._draft is not None:
                self._admit_draft(prompt, slot, key, req.temperature)
        self._pos[slot] = p
        tok0 = np.asarray(tok0)  # blocks until prefill + insert land
        prefill_dt = time.perf_counter() - t0
        req.prefill_secs += prefill_dt
        req.mark("prefill_done", ms=round(prefill_dt * 1e3, 3))
        self.metrics.record_prefill(prefill_dt)
        self._temps[slot] = req.temperature
        self._rngs[slot] = key
        self._steps[slot] = done_tokens + 1
        if self.paged:
            if self._prefix is not None:
                # Register the prompt's full blocks NOW (the prefill
                # that fills them is already dispatched, and the device
                # stream serializes) so the next same-prefix request —
                # even one admitted this very batch — hits.
                self._prefix.insert(
                    prompt,
                    self.pool.slot_pages[slot][: p // self.kv_page_size],
                    namespace=self._prefix_ns(req),
                )
            self._push_kv_metrics()
        token = int(tok0.reshape(-1)[0])
        req.push_token(token)
        if done_tokens == 0:
            self.metrics.record_ttft(
                time.monotonic() - req.submitted_at, tenant=req.tenant
            )
            if req.first_admitted_at is not None:
                # The queueing half of TTFT (the prefill-compute half is
                # record_prefill above), per-request, so a saturated
                # queue and a slow prefill are attributable apart.
                self.metrics.record_queue_wait(
                    req.first_admitted_at - req.submitted_at,
                    tenant=req.tenant,
                )
        self._active[slot] = req
        if self._finished(req, token):
            return "finished"
        return "active"

    def _admit_full_prefill(self, req, slot, prompt, key, done_tokens):
        """The contiguous batch-1 prefill + slot insert (paged mode
        scatter-inserts the SAME program's cache into pages — the
        byte-identity anchor)."""
        p = prompt.shape[0]
        bucket = min(1 << (p - 1).bit_length(), self.max_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = prompt
        run = self._program(
            ("serve_prefill", self._prefill_model, bucket),
            lambda: self._build_prefill(bucket, lora=self._lora_on),
        )
        extra = (
            (self._lora_vars(self._adapter_rows[slot: slot + 1]),)
            if self._lora_on else ()
        )
        with span("serve_prefill", prompt_len=p, bucket=bucket, slot=slot,
                  request=req.id, tenant=req.tenant):
            cache1, tok0 = run(
                self.params, padded, np.int32(p),
                jnp.asarray(req.temperature, jnp.float32), key,
                np.int32(done_tokens), *extra,
            )
            if self.paged:
                self.cache, self.tok = self._insert(
                    self.cache, self.tok, cache1, tok0, np.int32(slot),
                    np.int32(p), jnp.asarray(self._page_row(slot)),
                )
            else:
                self.cache, self.tok = self._insert(
                    self.cache, self.tok, cache1, tok0, np.int32(slot),
                    np.int32(p)
                )
        return tok0

    # Continuation windows bucket to powers of two like prefill, but
    # floored: suffix lengths collapse from log2(max_len) buckets to a
    # handful (8, 16, 32, ...), so steady-state traffic — where a repeat
    # prompt can self-hit down to a 1-token suffix — stops minting new
    # compiles for every tiny suffix length.  Padding cost is at most 7
    # wasted window positions.
    _MIN_SUFFIX_BUCKET = 8

    def _admit_paged_continuation(self, req, slot, prompt, c, key,
                                  done_tokens):
        """Prefix hit: skip the shared ``c`` tokens entirely; run only
        the suffix window through the paged continuation program."""
        p = prompt.shape[0]
        su = p - c
        bucket = min(
            max(self._MIN_SUFFIX_BUCKET, 1 << (su - 1).bit_length()),
            self.max_len,
        )
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :su] = prompt[c:]
        run = self._program(
            ("serve_prefill_paged", self._key_model, bucket),
            lambda: self._build_prefill_paged(bucket),
        )
        extra = (
            (self._lora_vars(self._adapter_rows[slot: slot + 1]),)
            if self._lora_on else ()
        )
        with span("serve_prefill_paged", prompt_len=p, prefix_hit=c,
                  bucket=bucket, slot=slot, request=req.id,
                  tenant=req.tenant):
            self.cache, self.tok, tok0 = run(
                self.cache, self.tok, self.params, padded, np.int32(su),
                np.int32(c), jnp.asarray(self._page_row(slot)),
                jnp.asarray(req.temperature, jnp.float32), key,
                np.int32(done_tokens), np.int32(slot), *extra,
            )
        return tok0

    # -- chunked prefill (prefill_chunk mode) -----------------------------

    def _admit_chunked(self, req, slot, prompt, c, key, done_tokens):
        """Admit a long prompt through page-aligned prefill windows:
        dispatch the first window now (async — nothing blocks) and park
        the slot in ``_chunked``; the serving loop advances one window
        per iteration via ``advance_chunks``, decoding between windows.
        Byte identity holds because every window is the SAME paged
        continuation program a prefix-cache hit runs (at the slot's
        dynamic offset), and the sampling fold-in counter is
        non-consuming — intermediate windows' discarded samples cannot
        perturb the final window's draw."""
        p = prompt.shape[0]
        req.mark(
            "prefill_start", slot=slot, kind="chunked",
            prefix_hit_tokens=c, resumed_tokens=done_tokens,
            window=self.prefill_chunk,
        )
        self.metrics.record_chunked_admission()
        self._chunked[slot] = {
            "req": req, "prompt": prompt, "p": p, "key": key,
            "done_tokens": done_tokens, "next": c, "secs": 0.0,
        }
        self._dispatch_chunk(slot)
        return "chunking"

    def _dispatch_chunk(self, slot: int):
        """Run ONE prefill window for a chunk-in-progress slot.  The
        window start is always page-aligned (prefix hits are
        block-granular and ``prefill_chunk`` is a page multiple).
        Non-final windows return None WITHOUT blocking on the device —
        the interleaving win; the final window blocks and returns the
        request's first sampled token."""
        st = self._chunked[slot]
        req, prompt, p = st["req"], st["prompt"], st["p"]
        start = st["next"]
        w = min(self.prefill_chunk, p - start)
        final = start + w >= p
        t0 = time.perf_counter()
        bucket = min(
            max(self._MIN_SUFFIX_BUCKET, 1 << (w - 1).bit_length()),
            self.max_len,
        )
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :w] = prompt[start: start + w]
        run = self._program(
            ("serve_prefill_paged", self._key_model, bucket),
            lambda: self._build_prefill_paged(bucket),
        )
        extra = (
            (self._lora_vars(self._adapter_rows[slot: slot + 1]),)
            if self._lora_on else ()
        )
        with span("serve_prefill_chunk", prompt_len=p, start=start,
                  window=w, bucket=bucket, slot=slot, request=req.id,
                  tenant=req.tenant):
            self.cache, self.tok, tok0 = run(
                self.cache, self.tok, self.params, padded, np.int32(w),
                np.int32(start), jnp.asarray(self._page_row(slot)),
                jnp.asarray(req.temperature, jnp.float32), st["key"],
                np.int32(st["done_tokens"]), np.int32(slot), *extra,
            )
        st["next"] = start + w
        req.prefill_chunks += 1
        self.metrics.record_prefill_chunk()
        if not final:
            st["secs"] += time.perf_counter() - t0
            req.mark("prefill_chunk", start=start, window=w)
            return None
        tok0 = np.asarray(tok0)  # blocks until the last window lands
        st["secs"] += time.perf_counter() - t0
        return tok0

    def advance_chunks(self) -> List[tuple]:
        """Advance every chunk-in-progress slot by ONE window (the
        serving loop calls this once per iteration, AFTER admissions and
        before decode — short requests admit and decode between a long
        prompt's windows).  Returns ``(slot, req, status)`` tuples:
        ``"chunking"`` (more windows pending), ``"active"`` (final
        window landed, request now decoding), or ``"finished"``
        (completed/cancelled/expired on its first token — the caller
        recycles the slot)."""
        out: List[tuple] = []
        now = time.monotonic()
        for slot in sorted(self._chunked):
            st = self._chunked.get(slot)
            if st is None:
                continue
            req = st["req"]
            if req.cancel_requested:
                del self._chunked[slot]
                req.finish("error", "cancelled: hedge superseded")
                self.metrics.record_cancellation()
                self._release_slot_pages(slot, None, donate=False)
                out.append((slot, req, "finished"))
                continue
            if req.expired(now):
                del self._chunked[slot]
                req.finish("expired")
                self.metrics.record_expiry()
                self._release_slot_pages(slot, None, donate=False)
                out.append((slot, req, "finished"))
                continue
            tok0 = self._dispatch_chunk(slot)
            if tok0 is None:
                out.append((slot, req, "chunking"))
                continue
            out.append((slot, req, self._finalize_chunked(slot, req, tok0)))
        return out

    def _finalize_chunked(self, slot: int, req: Request, tok0) -> str:
        """The admit tail for a chunked admission: the last window
        landed, so the slot activates exactly as an unchunked admission
        would — position, sampler state, prefix registration, first
        token, TTFT."""
        st = self._chunked.pop(slot)
        prompt, p = st["prompt"], st["p"]
        done_tokens = st["done_tokens"]
        self._pos[slot] = p
        req.prefill_secs += st["secs"]
        req.mark("prefill_done", ms=round(st["secs"] * 1e3, 3),
                 chunks=req.prefill_chunks)
        self.metrics.record_prefill(st["secs"])
        self._temps[slot] = req.temperature
        self._rngs[slot] = st["key"]
        self._steps[slot] = done_tokens + 1
        if self._prefix is not None:
            self._prefix.insert(
                prompt,
                self.pool.slot_pages[slot][: p // self.kv_page_size],
                namespace=self._prefix_ns(req),
            )
        self._push_kv_metrics()
        token = int(tok0.reshape(-1)[0])
        req.push_token(token)
        if done_tokens == 0:
            self.metrics.record_ttft(
                time.monotonic() - req.submitted_at, tenant=req.tenant
            )
            if req.first_admitted_at is not None:
                self.metrics.record_queue_wait(
                    req.first_admitted_at - req.submitted_at,
                    tenant=req.tenant,
                )
        self._active[slot] = req
        if self._finished(req, token):
            return "finished"
        return "active"

    def abort_chunked(self, msg: str) -> List[int]:
        """Fail every chunk-in-progress request with a structured error
        (teardown/evacuation: their page chains are only partially
        written, so pages release WITHOUT prefix donation).  Returns the
        freed slots for the caller to recycle."""
        freed: List[int] = []
        for slot in list(self._chunked):
            st = self._chunked.pop(slot)
            st["req"].finish("error", msg)
            self._release_slot_pages(slot, None, donate=False)
            freed.append(slot)
        return freed

    def _admit_draft(self, prompt, slot, key, temperature):
        """Prefill the draft model's own (contiguous) slot cache with
        the same effective prompt; its sampled token is discarded — only
        the K/V state matters for drafting."""
        p = prompt.shape[0]
        bucket = min(1 << (p - 1).bit_length(), self.max_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = prompt
        d_run = self._program(
            ("serve_prefill", self._draft.model, bucket),
            lambda: self._build_prefill(
                bucket, self._draft_dm, self._draft_shapes_b1
            ),
        )
        d_cache1, d_tok0 = d_run(
            self._draft.params, padded, np.int32(p),
            jnp.asarray(temperature, jnp.float32), key, np.int32(0),
        )
        self._draft_cache, self._draft_tok = self._draft_insert(
            self._draft_cache, self._draft_tok, d_cache1, d_tok0,
            np.int32(slot), np.int32(p),
        )

    def _finished(self, req: Request, token: int) -> bool:
        """Finish-and-unbind if ``req`` just completed; True if so."""
        done = (
            req.eos_token_id is not None and token == req.eos_token_id
        ) or len(req.tokens) >= req.max_new_tokens
        if done:
            req.finish("done")
            self.metrics.record_completion()
            self._release_slot_pages(req.slot, req, donate=True)
            del self._active[req.slot]
        return done

    def _sweep_cancelled(self) -> List[int]:
        """Release slots whose request was cancelled (a hedging loser,
        serving/router.py): the router already stopped reading the
        stream and cleared the SLO observer, so the finish is purely a
        release — pages donated (the prefill work stays useful in the
        prefix cache), slot freed before the next dispatch wastes a
        step on it."""
        freed: List[int] = []
        for slot in [
            s for s, r in self._active.items() if r.cancel_requested
        ]:
            req = self._active.pop(slot)
            req.finish("error", "cancelled: hedge superseded")
            self.metrics.record_cancellation()
            self._release_slot_pages(slot, req, donate=True)
            freed.append(slot)
        return freed

    def step(self) -> List[int]:
        """One compiled decode step over all slots; distributes each
        active slot's token(s) and returns the slots freed this step
        (finished, expired, cancelled, or preempted).  In spec mode
        each slot advances 1..spec_k+1 tokens."""
        if not self._active:
            return []
        cancel_freed = self._sweep_cancelled()
        if not self._active:
            return cancel_freed
        self._step_seq += 1
        # Flight record BEFORE the dispatch: when this step wedges, the
        # ring's newest decode_step record names the step — and the
        # REQUESTS riding it — that the watchdog dump blames.
        step_requests = [req.id for _, req in sorted(self._active.items())]
        self._flight.record(
            "decode_step", engine_step=self._step_seq,
            active=len(self._active), spec=bool(self.spec_k),
            requests=step_requests,
        )
        self._profiler.on_step(self._step_seq)
        # decode_wedge injection hook (resilience/faults.py): block like a
        # wedged device program would — the serving watchdog's job is to
        # fail the waiting clients while this thread is stuck here.
        from ml_trainer_tpu.resilience.faults import active_plan

        plan = active_plan()
        if plan is not None:
            fault = plan.fire("decode_wedge", step=self._step_seq)
            if fault is not None:
                plan.hold_wedge(fault)
        spec_now = bool(self.spec_k and self.spec_enabled)
        preempt_freed: List[int] = cancel_freed
        if self.paged:
            preempt_freed = preempt_freed + self._ensure_pages(
                self.spec_k + 1 if spec_now else 1
            )
            self._sync_table()
            if not self._active:
                return preempt_freed
        if spec_now:
            return preempt_freed + self._step_spec()
        active_before = len(self._active)
        t0 = time.perf_counter()
        extra = (
            (self._lora_vars(self._adapter_rows),) if self._lora_on
            else (self._quant,) if self.quant_int8
            else ()
        )
        with span("serve_decode", engine_step=self._step_seq,
                  active=active_before, requests=step_requests):
            self.cache, self.tok = self._decode(
                self.params, self.cache, self.tok,
                self._temps, self._rngs, self._steps, *extra,
            )
            # The step's ONE fence: every later read this iteration is
            # host data.  # graft-lint: sync-ok
            toks = np.asarray(self.tok[:, 0])  # blocks: the step landed
        dt = time.perf_counter() - t0
        # Host mirror of the device's idx += 1 (every row advances).
        self._pos = np.minimum(self._pos + 1, self.max_len).astype(np.int32)
        freed: List[int] = []
        emitted = 0
        now = time.monotonic()
        for slot in sorted(self._active):
            req = self._active[slot]
            if req.expired(now):
                req.finish(
                    "expired",
                    f"deadline ({req.deadline}s) passed mid-decode "
                    f"after {len(req.tokens)} token(s)",
                )
                self.metrics.record_expiry()
                self._release_slot_pages(slot, req, donate=True)
                del self._active[slot]
                freed.append(slot)
                continue
            self._steps[slot] += 1
            token = int(toks[slot])
            req.push_token(token)
            emitted += 1
            if self._finished(req, token):
                freed.append(slot)
        self.metrics.record_step(dt, active_before, self.max_batch, emitted)
        return preempt_freed + freed

    def _step_spec(self) -> List[int]:
        """One speculative verify step over all slots: draft spec_k
        tokens per slot (lookup or draft model), score the whole
        [max_batch, spec_k+1] window in ONE target forward, commit each
        slot's accepted prefix + 1.  Greedy slots reproduce the vanilla
        path byte-for-byte (longest-accepted-prefix); sampled slots use
        rejection sampling (same distribution, different draw stream
        than the vanilla per-token fold)."""
        active_before = len(self._active)
        k = self.spec_k
        step_requests = [req.id for _, req in sorted(self._active.items())]
        t0 = time.perf_counter()
        with span("serve_decode_spec", engine_step=self._step_seq,
                  active=active_before, k=k, requests=step_requests):
            if self._draft is not None:
                self._draft_cache, drafts_dev = self._draft_scan(
                    self._draft.params, self._draft_cache, self.tok,
                    jnp.asarray(self._pos),
                )
                # Draft fence: the verify window needs the drafted ids
                # on the host.  # graft-lint: sync-ok
                drafts = np.asarray(drafts_dev)
            else:
                # Per-slot draft state: the lookup history is the
                # request's own prompt + committed tokens.  Inactive
                # slots draft zeros — their rows compute masked garbage
                # nobody reads.
                drafts = np.zeros((self.max_batch, k), np.int32)
                for slot, req in self._active.items():
                    hist = np.concatenate([
                        np.asarray(req.prompt, np.int32).reshape(-1),
                        np.asarray(req.tokens, np.int32),
                    ])
                    drafts[slot] = self._ngram.draft_one(hist)
            window = jnp.concatenate(
                [self.tok, jnp.asarray(drafts, jnp.int32)], axis=1
            )
            self.cache, accepted, self.tok, _ = self._verify(
                self.params, self.cache, window, jnp.asarray(self._pos),
                jnp.asarray(self._caps), self._temps, self._rngs,
                self._steps,
            )
            acc = np.asarray(accepted)  # graft-lint: sync-ok
            # graft-lint: sync-ok (the verify step's one fence)
            toks = np.asarray(self.tok[:, 0])  # blocks: the step landed
        dt = time.perf_counter() - t0
        freed: List[int] = []
        emitted = 0
        acc_active: List[int] = []
        now = time.monotonic()
        for slot in sorted(self._active):
            req = self._active[slot]
            if req.expired(now):
                req.finish(
                    "expired",
                    f"deadline ({req.deadline}s) passed mid-decode "
                    f"after {len(req.tokens)} token(s)",
                )
                self.metrics.record_expiry()
                self._release_slot_pages(slot, req, donate=True)
                del self._active[slot]
                freed.append(slot)
                continue
            n_acc = int(acc[slot])
            acc_active.append(n_acc)
            req.spec_steps += 1
            req.spec_accepted_tokens += n_acc
            committed = [int(t) for t in drafts[slot][:n_acc]]
            committed.append(int(toks[slot]))
            for token in committed:
                self._steps[slot] += 1
                req.push_token(token)
                emitted += 1
                if self._finished(req, token):
                    freed.append(slot)
                    break
        # Host mirrors the device's new_pos formula exactly.
        self._pos = np.minimum(
            self._pos + acc.astype(np.int32) + 1, self._caps
        ).astype(np.int32)
        self.metrics.record_step(dt, active_before, self.max_batch, emitted)
        self.metrics.record_spec(acc_active, k)
        return freed
