"""Slot-based continuous-batching decode engine.

``generate()`` is one-shot: a whole batch prefills together, decodes in
lockstep, and every row waits for the slowest (the convoy effect); a new
batch shape means a new compile.  This engine serves requests that
arrive at arbitrary times through ONE preallocated KV-cache block and
ONE compiled per-token decode program:

* **Slots.**  The cache is the flax ``decode``-mode cache built at batch
  ``max_batch`` — per attention layer ``[max_batch, H, max_len, D]`` —
  with the scalar ``cache_index``/``pos_index`` leaves widened to
  per-row ``[max_batch]`` vectors (models/layers.py's slot-indexed
  path), so every row sits at its OWN sequence position.  A request owns
  one row (slot) for its lifetime.

* **Prefill.**  A new request prefills OUT OF BAND at batch 1: its
  prompt is right-padded to the next power-of-two bucket (at most
  log2(max_len) compiled prefill programs — ``generate_ragged``'s
  bucketing trick applied to length instead of batch), one batched
  causal forward fills a fresh batch-1 cache, the true-length logits
  sample token 0, and the rows are inserted into the slot cache with the
  index vectors set to the TRUE prompt length.  Padding garbage beyond
  the true length is never attended: the decode mask is
  ``arange(max_len) <= index[slot]`` and later tokens overwrite it.

* **Decode.**  All slots advance through a single compiled step —
  ``[max_batch, 1]`` tokens in, one forward, per-row sampling out.
  Requests join (prefill + insert) and leave (EOS / budget / deadline)
  at token boundaries with NO recompilation: shapes are static, inactive
  slots just compute masked garbage that nobody reads.

Sampling matches ``generate()`` token-for-token per request: greedy is
``argmax``; ``temperature > 0`` draws
``categorical(fold_in(rng, t), logits / temperature)`` with the
request's own rng and per-token counter ``t`` — byte-identical to a
standalone batch-1 ``generate()`` call for the same request.

Compiled programs (prefill buckets, the decode step, the slot insert)
live in the process-wide LRU shared with ``generate._COMPILED``, so one
bound covers every decode executable in the process.

* **Speculative mode** (``spec_k > 0``, see speculative.py and
  docs/serving.md): each step drafts ``spec_k`` tokens per slot (n-gram
  lookup over the request's own history, or a vocab-compatible draft
  model with its own slot cache) and ONE verify forward over a
  ``[max_batch, spec_k+1]`` window commits a variable 1..spec_k+1
  tokens per slot — still one static-shaped executable at fixed K, so
  join/leave semantics and the no-recompilation guarantee carry over
  unchanged.  Greedy slots stay byte-identical to ``generate()``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.generate import _COMPILED, _cache_shapes, _empty_cache
from ml_trainer_tpu.serving.metrics import ServingMetrics
from ml_trainer_tpu.serving.scheduler import Request
from ml_trainer_tpu.telemetry.flight import get_recorder
from ml_trainer_tpu.telemetry.spans import StepProfiler, span
from ml_trainer_tpu.speculative import (
    DraftModelDrafter,
    NgramDrafter,
    build_draft_scan,
    build_verify,
)


def _as_key(rng) -> np.ndarray:
    """Normalize a request rng (None | int seed | PRNG key) to raw
    uint32[2] key data.  None matches ``generate()``'s PRNGKey(0)
    default so an rng-less sampled request reproduces the rng-less
    ``generate()`` call."""
    if rng is None:
        rng = 0
    if isinstance(rng, (int, np.integer)):
        rng = jax.random.PRNGKey(int(rng))
    key = np.asarray(rng, np.uint32).reshape(-1)
    if key.shape != (2,):
        raise ValueError(f"rng must be an int seed or a PRNG key, got {rng!r}")
    return key


def _sample_rows(last, temps, rngs, steps):
    """Per-row sampling: greedy argmax where ``temps == 0``, else
    ``categorical(fold_in(rng_row, t_row), last_row / temp_row)`` — the
    same draw ``generate()`` makes for that request at token ``t``."""
    greedy_tok = jnp.argmax(last, axis=-1)
    keys = jax.vmap(jax.random.fold_in)(rngs, steps)
    safe = jnp.where(temps > 0, temps, 1.0)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, last / safe)
    return jnp.where(temps > 0, sampled, greedy_tok)


class SlotDecodeEngine:
    """The slot cache plus its three compiled programs.  Single-threaded
    by design: one worker (serving/api.py's loop) calls ``admit`` and
    ``step``; thread-safe admission lives in the scheduler."""

    def __init__(self, model, variables: dict, max_batch: int = 8,
                 metrics: Optional[ServingMetrics] = None,
                 spec_k: int = 0, drafter="ngram",
                 draft_variables: Optional[dict] = None,
                 ngram_n: int = 3):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if not getattr(model, "max_len", 0):
            raise ValueError(
                "serving needs a causal LM exposing decode/max_len "
                f"(got {type(model).__name__})"
            )
        if spec_k < 0 or spec_k >= int(model.max_len):
            raise ValueError(
                f"spec_k must be in [0, max_len={model.max_len}), "
                f"got {spec_k}"
            )
        self.model = model
        self.dm = model.clone(decode=True)
        self.params = (
            variables["params"] if "params" in variables else variables
        )
        self.max_batch = max_batch
        self.max_len = int(model.max_len)
        self.vocab_size = int(model.vocab_size)
        self.metrics = metrics if metrics is not None else ServingMetrics()

        # Batch-1 cache shapes for prefill; slot cache at max_batch with
        # the scalar index leaves widened to [max_batch] vectors.
        self._shapes_b1 = _cache_shapes(self.dm, 1, jnp.int32)
        shapes_mb = _cache_shapes(self.dm, max_batch, jnp.int32)
        self.cache = jax.tree.map(
            lambda s: jnp.zeros(
                (max_batch,) if s.ndim == 0 else s.shape, s.dtype
            ),
            shapes_mb,
        )
        self.tok = jnp.zeros((max_batch, 1), jnp.int32)
        self._temps = np.zeros((max_batch,), np.float32)
        self._rngs = np.zeros((max_batch, 2), np.uint32)
        self._steps = np.zeros((max_batch,), np.int32)
        self._active: Dict[int, Request] = {}
        self._step_seq = 0  # decode steps run (the decode_wedge fault clock)
        # Telemetry: flight ring for crash forensics (the watchdog dumps
        # it when the loop wedges) and the on-demand profile window the
        # admin endpoint arms (POST /admin/profile).
        self._flight = get_recorder()
        self._profiler = StepProfiler("serve")

        self._decode = self._program(
            ("serve_decode", model, max_batch), self._build_decode
        )
        self._insert = self._program(
            ("serve_insert", model, max_batch), self._build_insert
        )

        # -- speculative decoding (opt-in; see speculative.py) ----------
        # Slots advance a variable 1..spec_k+1 tokens per verify step;
        # all shapes stay static at fixed spec_k, so ragged join/leave
        # traffic still never recompiles.
        self.spec_k = int(spec_k)
        self._ngram: Optional[NgramDrafter] = None
        self._draft: Optional[DraftModelDrafter] = None
        if self.spec_k:
            if drafter == "ngram":
                self._ngram = NgramDrafter(k=self.spec_k, n=ngram_n)
            elif isinstance(drafter, DraftModelDrafter):
                self._draft = drafter
            elif hasattr(drafter, "max_len"):
                if draft_variables is None:
                    raise ValueError(
                        "a draft model needs draft_variables (its params)"
                    )
                self._draft = DraftModelDrafter(drafter, draft_variables)
            else:
                raise ValueError(
                    "drafter must be 'ngram', a DraftModelDrafter or a "
                    f"registry model, got {drafter!r}"
                )
            self._verify = self._program(
                ("spec_verify", model, max_batch, self.spec_k + 1),
                lambda: build_verify(model, max_batch, self.spec_k + 1),
            )
            # Host-owned consumed-token counts and write caps per slot
            # (the verify window writes spec_k+1 positions at pos, so
            # pos is clamped to keep every write inside max_len).
            self._pos = np.zeros((max_batch,), np.int32)
            self._caps = np.full(
                (max_batch,), self.max_len - self.spec_k - 1, np.int32
            )
            if self._draft is not None:
                self._draft.check_compatible(model)
                d_model = self._draft.model
                if int(d_model.max_len) < self.max_len:
                    raise ValueError(
                        f"draft model max_len ({d_model.max_len}) must "
                        f"cover the target's ({self.max_len})"
                    )
                self._draft_dm = d_model.clone(decode=True)
                self._draft_shapes_b1 = _cache_shapes(
                    self._draft_dm, 1, jnp.int32
                )
                d_shapes = _cache_shapes(self._draft_dm, max_batch, jnp.int32)
                self._draft_cache = jax.tree.map(
                    lambda s: jnp.zeros(
                        (max_batch,) if s.ndim == 0 else s.shape, s.dtype
                    ),
                    d_shapes,
                )
                self._draft_tok = jnp.zeros((max_batch, 1), jnp.int32)
                self._draft_scan = self._program(
                    ("spec_draft", d_model, max_batch, self.spec_k),
                    lambda: build_draft_scan(
                        d_model, max_batch, self.spec_k
                    ),
                )
                self._draft_insert = self._program(
                    ("serve_insert", d_model, max_batch), self._build_insert
                )

    # -- compiled programs ----------------------------------------------

    def _program(self, key, build):
        run = _COMPILED.get(key)
        if run is None:
            run = build()
            _COMPILED[key] = run
        return run

    def _build_decode(self):
        dm = self.dm

        def step(params, cache, tok, temps, rngs, steps):
            logits, mut = dm.apply(
                {"params": params, "cache": cache}, tok,
                train=False, mutable=["cache"],
            )
            nxt = _sample_rows(logits[:, -1], temps, rngs, steps)
            return mut["cache"], nxt[:, None].astype(jnp.int32)

        return jax.jit(step, donate_argnums=(1, 2))

    def _build_insert(self):
        def insert(cache_big, tok_big, cache1, tok0, slot, true_len):
            def leaf(big, small):
                if big.ndim == small.ndim:
                    # K/V row replace: [1, H, L, D] into row ``slot``.
                    start = (slot,) + (0,) * (big.ndim - 1)
                    return jax.lax.dynamic_update_slice(
                        big, small.astype(big.dtype), start
                    )
                # Index vector vs the prefill's scalar: the slot's
                # position is the TRUE prompt length, not the padded
                # bucket the scalar advanced to.
                return big.at[slot].set(jnp.asarray(true_len, big.dtype))

            cache_big = jax.tree.map(leaf, cache_big, cache1)
            tok_big = jax.lax.dynamic_update_slice(
                tok_big, tok0[:, None], (slot, 0)
            )
            return cache_big, tok_big

        return jax.jit(insert, donate_argnums=(0, 1))

    def _build_prefill(self, bucket: int, dm=None, shapes=None):
        dm = dm if dm is not None else self.dm
        shapes = shapes if shapes is not None else self._shapes_b1

        def prefill(params, prompt_pad, true_len, temp, rng):
            cache = _empty_cache(shapes)
            logits, mut = dm.apply(
                {"params": params, "cache": cache}, prompt_pad,
                train=False, mutable=["cache"],
            )
            # Causal prefill: the padded tail cannot influence position
            # true_len-1, whose logits sample token 0 (fold counter 0 —
            # generate()'s t=0 draw).
            last = jax.lax.dynamic_index_in_dim(
                logits, true_len - 1, axis=1, keepdims=False
            )
            tok = _sample_rows(
                last, temp[None], rng[None], jnp.zeros((1,), jnp.int32)
            )
            return mut["cache"], tok.astype(jnp.int32)

        return jax.jit(prefill)

    # -- serving ---------------------------------------------------------

    def free_capacity(self) -> int:
        return self.max_batch - len(self._active)

    def active_count(self) -> int:
        return len(self._active)

    def admit(self, req: Request, slot: int) -> bool:
        """Prefill ``req`` into ``slot`` and emit its first token.
        Returns False when the request finished immediately (EOS on
        token 0, or a one-token budget) — the caller recycles the slot."""
        if slot in self._active:
            raise ValueError(f"slot {slot} is already occupied")
        req.slot = slot
        req.state = "active"
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        bucket = min(1 << (p - 1).bit_length(), self.max_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = prompt
        key = _as_key(req.rng)
        run = self._program(
            ("serve_prefill", self.model, bucket),
            lambda: self._build_prefill(bucket),
        )
        t0 = time.perf_counter()
        with span("serve_prefill", prompt_len=p, bucket=bucket, slot=slot):
            cache1, tok0 = run(
                self.params, padded, np.int32(p),
                jnp.asarray(req.temperature, jnp.float32), key,
            )
            self.cache, self.tok = self._insert(
                self.cache, self.tok, cache1, tok0, np.int32(slot),
                np.int32(p)
            )
        if self.spec_k:
            self._pos[slot] = p
            self._caps[slot] = min(
                p + req.max_new_tokens - 1, self.max_len - self.spec_k - 1
            )
            if self._draft is not None:
                # The draft model prefills the same padded prompt into
                # ITS slot cache (its own bucketed programs); the draft
                # prefill's sampled token is discarded — only the K/V
                # state matters for drafting.
                d_run = self._program(
                    ("serve_prefill", self._draft.model, bucket),
                    lambda: self._build_prefill(
                        bucket, self._draft_dm, self._draft_shapes_b1
                    ),
                )
                d_cache1, d_tok0 = d_run(
                    self._draft.params, padded, np.int32(p),
                    jnp.asarray(req.temperature, jnp.float32), key,
                )
                self._draft_cache, self._draft_tok = self._draft_insert(
                    self._draft_cache, self._draft_tok, d_cache1, d_tok0,
                    np.int32(slot), np.int32(p),
                )
        tok0 = np.asarray(tok0)  # blocks until prefill + insert land
        self.metrics.record_prefill(time.perf_counter() - t0)
        self._temps[slot] = req.temperature
        self._rngs[slot] = key
        self._steps[slot] = 1
        token = int(tok0[0])
        req.push_token(token)
        self.metrics.record_ttft(time.monotonic() - req.submitted_at)
        self._active[slot] = req
        if self._finished(req, token):
            return False
        return True

    def _finished(self, req: Request, token: int) -> bool:
        """Finish-and-unbind if ``req`` just completed; True if so."""
        done = (
            req.eos_token_id is not None and token == req.eos_token_id
        ) or len(req.tokens) >= req.max_new_tokens
        if done:
            req.finish("done")
            self.metrics.record_completion()
            del self._active[req.slot]
        return done

    def step(self) -> List[int]:
        """One compiled decode step over all slots; distributes each
        active slot's token(s) and returns the slots freed this step.
        In spec mode each slot advances 1..spec_k+1 tokens."""
        if not self._active:
            return []
        self._step_seq += 1
        # Flight record BEFORE the dispatch: when this step wedges, the
        # ring's newest decode_step record names the step the watchdog
        # dump blames.
        self._flight.record(
            "decode_step", engine_step=self._step_seq,
            active=len(self._active), spec=bool(self.spec_k),
        )
        self._profiler.on_step(self._step_seq)
        # decode_wedge injection hook (resilience/faults.py): block like a
        # wedged device program would — the serving watchdog's job is to
        # fail the waiting clients while this thread is stuck here.
        from ml_trainer_tpu.resilience.faults import active_plan

        plan = active_plan()
        if plan is not None:
            fault = plan.fire("decode_wedge", step=self._step_seq)
            if fault is not None:
                plan.hold_wedge(fault)
        if self.spec_k:
            return self._step_spec()
        active_before = len(self._active)
        t0 = time.perf_counter()
        with span("serve_decode", engine_step=self._step_seq,
                  active=active_before):
            self.cache, self.tok = self._decode(
                self.params, self.cache, self.tok,
                self._temps, self._rngs, self._steps,
            )
            toks = np.asarray(self.tok[:, 0])  # blocks: the step landed
        dt = time.perf_counter() - t0
        freed: List[int] = []
        emitted = 0
        now = time.monotonic()
        for slot in sorted(self._active):
            req = self._active[slot]
            if req.expired(now):
                req.finish(
                    "expired",
                    f"deadline ({req.deadline}s) passed mid-decode "
                    f"after {len(req.tokens)} token(s)",
                )
                self.metrics.record_expiry()
                del self._active[slot]
                freed.append(slot)
                continue
            self._steps[slot] += 1
            token = int(toks[slot])
            req.push_token(token)
            emitted += 1
            if self._finished(req, token):
                freed.append(slot)
        self.metrics.record_step(dt, active_before, self.max_batch, emitted)
        return freed

    def _step_spec(self) -> List[int]:
        """One speculative verify step over all slots: draft spec_k
        tokens per slot (lookup or draft model), score the whole
        [max_batch, spec_k+1] window in ONE target forward, commit each
        slot's accepted prefix + 1.  Greedy slots reproduce the vanilla
        path byte-for-byte (longest-accepted-prefix); sampled slots use
        rejection sampling (same distribution, different draw stream
        than the vanilla per-token fold)."""
        active_before = len(self._active)
        k = self.spec_k
        t0 = time.perf_counter()
        with span("serve_decode_spec", engine_step=self._step_seq,
                  active=active_before, k=k):
            if self._draft is not None:
                self._draft_cache, drafts_dev = self._draft_scan(
                    self._draft.params, self._draft_cache, self.tok,
                    jnp.asarray(self._pos),
                )
                drafts = np.asarray(drafts_dev)
            else:
                # Per-slot draft state: the lookup history is the
                # request's own prompt + committed tokens.  Inactive
                # slots draft zeros — their rows compute masked garbage
                # nobody reads.
                drafts = np.zeros((self.max_batch, k), np.int32)
                for slot, req in self._active.items():
                    hist = np.concatenate([
                        np.asarray(req.prompt, np.int32).reshape(-1),
                        np.asarray(req.tokens, np.int32),
                    ])
                    drafts[slot] = self._ngram.draft_one(hist)
            window = jnp.concatenate(
                [self.tok, jnp.asarray(drafts, jnp.int32)], axis=1
            )
            self.cache, accepted, self.tok, _ = self._verify(
                self.params, self.cache, window, jnp.asarray(self._pos),
                jnp.asarray(self._caps), self._temps, self._rngs,
                self._steps,
            )
            acc = np.asarray(accepted)
            toks = np.asarray(self.tok[:, 0])  # blocks: the step landed
        dt = time.perf_counter() - t0
        freed: List[int] = []
        emitted = 0
        acc_active: List[int] = []
        now = time.monotonic()
        for slot in sorted(self._active):
            req = self._active[slot]
            if req.expired(now):
                req.finish(
                    "expired",
                    f"deadline ({req.deadline}s) passed mid-decode "
                    f"after {len(req.tokens)} token(s)",
                )
                self.metrics.record_expiry()
                del self._active[slot]
                freed.append(slot)
                continue
            n_acc = int(acc[slot])
            acc_active.append(n_acc)
            req.spec_steps += 1
            req.spec_accepted_tokens += n_acc
            committed = [int(t) for t in drafts[slot][:n_acc]]
            committed.append(int(toks[slot]))
            for token in committed:
                self._steps[slot] += 1
                req.push_token(token)
                emitted += 1
                if self._finished(req, token):
                    freed.append(slot)
                    break
        # Host mirrors the device's new_pos formula exactly.
        self._pos = np.minimum(
            self._pos + acc.astype(np.int32) + 1, self._caps
        ).astype(np.int32)
        self.metrics.record_step(dt, active_before, self.max_batch, emitted)
        self.metrics.record_spec(acc_active, k)
        return freed
