"""SLO attainment accounting over request-lifecycle timelines.

The serving capacity question — "how much load can this deployment take
before it stops being good?" — is answered in SLO terms, not mean
throughput: at each offered load, what fraction of requests met their
time-to-first-token (TTFT) and time-per-output-token (TPOT) targets
(the Gemma-on-TPU serving paper's framing, PAPERS.md arXiv 2605.25645).
This module is the consumer side of the request-lifecycle tracing:

* every :class:`~ml_trainer_tpu.serving.scheduler.Request` records its
  lifecycle (submit -> queue -> admit -> prefill -> per-token stream ->
  finish/preempt) as monotonic timestamps and ``mark()`` events, and
  ``Request.timeline()`` renders that as one structured JSON record;
* the :class:`SloTracker` is installed as the request's finish observer
  (``Server`` wires it at submit): it aggregates per-tenant attainment
  against an :class:`SloPolicy`, feeds the completion-side latency
  histograms (TPOT, end-to-end) through ``ServingMetrics`` into the
  registry's real ``Histogram`` type, keeps a bounded ring of the last
  N timelines as a flight-recorder context provider (a watchdog/preempt
  dump names the requests it hurt WITH their lifecycles), and emits the
  finished request onto the span trace as nested retrospective events
  (``request N`` -> queue_wait / prefill / decode children);
* ``publish()`` mirrors attainment and **burn rate** into the registry:
  ``burn_rate = (1 - attainment) / (1 - target)`` — 1.0 means the error
  budget is being spent exactly at the rate the target allows, >1 means
  the deployment is burning budget faster than sustainable (the
  standard SRE alerting quantity).

Host-only module: no jax — timelines are plain host data.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, Optional

from ml_trainer_tpu.serving.metrics import ServingMetrics


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """Latency targets one deployment promises its callers.

    ``ttft_ms``: submit -> first token budget per request.
    ``tpot_ms``: per-request MEAN inter-token latency budget (requests
    with fewer than two tokens have no inter-token gap and trivially
    attain it).  ``target``: the attainment objective (fraction of
    requests that must meet each budget) the burn rate is relative to.
    Requests that finish in an error/expired state missed both SLOs by
    definition — a failed request is not a fast request."""

    ttft_ms: float = 250.0
    tpot_ms: float = 50.0
    target: float = 0.99

    def __post_init__(self):
        if self.ttft_ms <= 0 or self.tpot_ms <= 0:
            raise ValueError(
                f"SLO budgets must be positive, got ttft_ms={self.ttft_ms} "
                f"tpot_ms={self.tpot_ms}"
            )
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"target must be in (0, 1), got {self.target}"
            )


def _attained(tl: dict, policy: SloPolicy) -> Dict[str, bool]:
    """Per-SLO verdict for one finished timeline."""
    ok_state = tl["state"] == "done"
    ttft = tl.get("ttft_ms")
    tpot_mean = (tl.get("tpot_ms") or {}).get("mean")
    return {
        "ttft": bool(
            ok_state and ttft is not None and ttft <= policy.ttft_ms
        ),
        "tpot": bool(
            ok_state and (tpot_mean is None or tpot_mean <= policy.tpot_ms)
        ),
    }


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return round(sorted_vals[i], 3)


def aggregate_timelines(timelines, policy: SloPolicy) -> dict:
    """Offline aggregation of one measurement window's finished
    timelines — latency percentiles (ms) + attainment + burn rate —
    independent of any server-lifetime accounting.  This is what the
    load harness reports per offered rate: it filters the tracker's
    timeline ring to the timed window and aggregates only that.  TPOT
    percentiles are over per-request MEAN inter-token latency (the
    per-request SLO quantity the attainment judges)."""
    tls = list(timelines)
    budget = 1.0 - policy.target

    def _dist(values):
        vals = sorted(v for v in values if v is not None)
        return {
            "p50": _pct(vals, 0.5),
            "p99": _pct(vals, 0.99),
            "n": len(vals),
        }

    ok = {"ttft": 0, "tpot": 0}
    for tl in tls:
        v = _attained(tl, policy)
        for k in ok:
            ok[k] += int(v[k])
    n = len(tls)
    attainment = {k: round(ok[k] / n, 4) if n else 1.0 for k in ok}
    return {
        "n_requests": n,
        "n_failed": sum(1 for tl in tls if tl["state"] != "done"),
        "ttft_ms": _dist(tl["ttft_ms"] for tl in tls),
        "tpot_ms": _dist((tl["tpot_ms"] or {}).get("mean") for tl in tls),
        "queue_wait_ms": _dist(tl["queue_wait_ms"] for tl in tls),
        "prefill_ms": _dist(tl["prefill_ms"] for tl in tls),
        "e2e_ms": _dist(tl["e2e_ms"] for tl in tls),
        "attainment": attainment,
        "burn_rate": {
            k: round((1.0 - attainment[k]) / budget, 4) if budget > 0
            else 0.0
            for k in attainment
        },
    }


class _TenantLedger:
    __slots__ = ("requests", "ok", "failed")

    def __init__(self):
        self.requests = 0
        self.ok = {"ttft": 0, "tpot": 0}
        self.failed = 0


class SloTracker:
    """Thread-safe per-tenant SLO attainment over finished requests.

    ``track()`` registers an in-flight request (the forensics surface),
    ``observe()`` consumes it at finish (installed as
    ``Request.observer``, so every terminal path — EOS, budget,
    deadline, preemption give-up, engine death — lands here exactly
    once), ``snapshot()``/``publish()`` read the accounting."""

    def __init__(self, policy: Optional[SloPolicy] = None,
                 metrics: Optional[ServingMetrics] = None,
                 keep_timelines: int = 64, trace: bool = True):
        if keep_timelines < 1:
            raise ValueError(
                f"keep_timelines must be >= 1, got {keep_timelines}"
            )
        self.policy = policy if policy is not None else SloPolicy()
        self._metrics = metrics
        self._trace = trace
        self._lock = threading.Lock()
        self._active: Dict[int, object] = {}       # id -> live Request
        self._timelines: collections.deque = collections.deque(
            maxlen=keep_timelines
        )
        self._tenants: Dict[str, _TenantLedger] = {}

    # -- recording -------------------------------------------------------

    def track(self, req) -> None:
        """Register an in-flight request (forensics: a flight dump taken
        while it runs includes its timeline-so-far).  A request that
        already finished (queued-expiry can race the submit path) is
        not re-registered — ``observe`` popped it already."""
        with self._lock:
            if not req._observed:
                self._active[req.id] = req

    def forget(self, req) -> None:
        """Drop an in-flight registration WITHOUT consuming it — the
        request is migrating to another replica whose tracker takes
        over (``Server.adopt`` re-tracks it there), so this replica's
        accounting must neither leak the active entry nor claim the
        finished timeline."""
        with self._lock:
            self._active.pop(req.id, None)

    def observe(self, req) -> None:
        """Consume one FINISHED request's timeline into the accounting.
        Called from ``Request.finish`` (any thread, exactly once)."""
        tl = req.timeline()
        verdict = _attained(tl, self.policy)
        with self._lock:
            self._active.pop(tl["id"], None)
            self._timelines.append(tl)
            t = self._tenants.get(tl["tenant"])
            if t is None:
                t = self._tenants[tl["tenant"]] = _TenantLedger()
            t.requests += 1
            if tl["state"] != "done":
                t.failed += 1
            for k, ok in verdict.items():
                if ok:
                    t.ok[k] += 1
        # Completion-side latency feeds (the admission side — TTFT and
        # queue wait — is recorded by the engine at first token) and the
        # trace emission run OUTSIDE the tracker lock.
        if self._metrics is not None:
            deltas = req.tpot_deltas()
            if deltas:
                self._metrics.record_tpot(deltas, tenant=tl["tenant"])
            if tl["e2e_ms"] is not None:
                self._metrics.record_e2e(
                    tl["e2e_ms"] / 1e3, tenant=tl["tenant"]
                )
        if self._trace:
            self._emit_trace(req, tl)

    @staticmethod
    def _trace_args(req, tl: dict) -> dict:
        """The per-span args correlating this process's fragment with
        the fleet-wide request (docs/observability.md "Fleet plane"):
        ``trace_id`` is the ORIGIN request id when a trace context rode
        the wire (shadows/adoptions mint fresh local ids), else the
        local id — single-process traces are unchanged."""
        ctx = getattr(req, "trace_ctx", None) or {}
        args = {
            "request": tl["id"],
            "trace_id": ctx.get("trace_id", tl["id"]),
        }
        if ctx.get("origin_pid") is not None:
            args["origin_pid"] = ctx["origin_pid"]
        if ctx.get("parent"):
            args["parent"] = ctx["parent"]
        return args

    def _emit_trace(self, req, tl: dict) -> None:
        """Render the finished request as nested retrospective spans on
        the process trace: one ``request N`` complete event spanning
        submit -> finish with queue_wait / prefill / decode children
        (time containment = nesting in Perfetto)."""
        from ml_trainer_tpu.telemetry import spans

        sub = req.submitted_at
        fin = req.finished_at
        if fin is None or fin <= sub:
            return
        targs = self._trace_args(req, tl)
        spans.complete_event(
            f"request {targs['trace_id']}", sub, fin, category="request",
            tenant=tl["tenant"], state=tl["state"],
            prompt_tokens=tl["prompt_tokens"],
            new_tokens=tl["new_tokens"],
            preemptions=tl["preemptions"], **targs,
        )
        admit = req.first_admitted_at
        first_tok = req.first_token_at
        if admit is not None and admit > sub:
            spans.complete_event(
                "queue_wait", sub, min(admit, fin), category="request",
                **targs,
            )
        if admit is not None and first_tok is not None \
                and first_tok > admit:
            spans.complete_event(
                "prefill", admit, min(first_tok, fin),
                category="request",
                prefix_hit_tokens=tl["prefix_hit_tokens"], **targs,
            )
        if first_tok is not None and fin > first_tok:
            spans.complete_event(
                "decode", first_tok, fin, category="request",
                new_tokens=tl["new_tokens"], **targs,
            )

    def observe_export(self, req) -> None:
        """Emit the PREFILL-SIDE spans for a request migrating away
        (``Server._export_for_migration``): the request never finishes
        on this replica — ``forget()`` drops it without a timeline — so
        without this call the fleet trace would have a hole where the
        prefill happened.  Emits ``queue_wait`` and ``prefill`` children
        plus a ``request N (prefill)`` envelope ending at export, all
        stamped with the wire trace context so the decode replica's
        fragment and this one share a ``trace_id`` on the merged
        timeline.  No SLO accounting moves — attainment for a migrated
        request is billed exactly once, by the decode-side tracker."""
        if not self._trace:
            return
        from ml_trainer_tpu.telemetry import spans

        tl = req.timeline()
        targs = self._trace_args(req, tl)
        sub = req.submitted_at
        now = time.monotonic()
        if now <= sub:
            return
        spans.complete_event(
            f"request {targs['trace_id']} (prefill)", sub, now,
            category="request", tenant=tl["tenant"], state="migrated_out",
            prompt_tokens=tl["prompt_tokens"], **targs,
        )
        admit = req.first_admitted_at
        if admit is not None and admit > sub:
            spans.complete_event(
                "queue_wait", sub, min(admit, now), category="request",
                **targs,
            )
        if admit is not None and now > admit:
            spans.complete_event(
                "prefill", admit, now, category="request",
                prefix_hit_tokens=tl["prefix_hit_tokens"], **targs,
            )

    # -- reading ---------------------------------------------------------

    def _burn(self, attainment: float) -> float:
        budget = 1.0 - self.policy.target
        return round((1.0 - attainment) / budget, 4) if budget > 0 else 0.0

    def snapshot(self) -> dict:
        """Point-in-time attainment accounting (JSON-safe)."""
        with self._lock:
            tenants = {
                name: (t.requests, dict(t.ok), t.failed)
                for name, t in self._tenants.items()
            }
            n_active = len(self._active)
        total = sum(r for r, _, _ in tenants.values())
        ok_total = {
            k: sum(ok[k] for _, ok, _ in tenants.values())
            for k in ("ttft", "tpot")
        }

        def _att(ok, n):
            return round(ok / n, 4) if n else 1.0

        return {
            "policy": dataclasses.asdict(self.policy),
            "requests_observed": total,
            "requests_failed": sum(f for _, _, f in tenants.values()),
            "active_requests": n_active,
            "attainment": {
                k: _att(ok_total[k], total) for k in ok_total
            },
            "burn_rate": {
                k: self._burn(_att(ok_total[k], total)) for k in ok_total
            },
            "tenants": {
                name: {
                    "requests": r,
                    "failed": f,
                    "attainment": {k: _att(ok[k], r) for k in ok},
                    "burn_rate": {
                        k: self._burn(_att(ok[k], r)) for k in ok
                    },
                }
                for name, (r, ok, f) in sorted(tenants.items())
            },
        }

    def publish(self, registry=None) -> dict:
        """Mirror attainment + burn rate into the registry (and return
        the snapshot).  One labeled series per (slo, tenant), plus the
        ``tenant="all"`` aggregate and the policy targets — everything a
        dashboard needs to plot capacity vs SLO."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        snap = self.snapshot()
        att = r.gauge(
            "serving_slo_attainment",
            "fraction of finished requests meeting the SLO budget",
            labelnames=("slo", "tenant"),
        )
        burn = r.gauge(
            "serving_slo_burn_rate",
            "(1 - attainment) / (1 - target); >1 burns error budget "
            "faster than the target sustains",
            labelnames=("slo", "tenant"),
        )
        target = r.gauge(
            "serving_slo_target_ms", "SLO latency budget",
            labelnames=("slo",),
        )
        target.labels(slo="ttft").set(self.policy.ttft_ms)
        target.labels(slo="tpot").set(self.policy.tpot_ms)
        for k in ("ttft", "tpot"):
            att.labels(slo=k, tenant="all").set(snap["attainment"][k])
            burn.labels(slo=k, tenant="all").set(snap["burn_rate"][k])
        for name, t in snap["tenants"].items():
            for k in ("ttft", "tpot"):
                att.labels(slo=k, tenant=name).set(t["attainment"][k])
                burn.labels(slo=k, tenant=name).set(t["burn_rate"][k])
        r.gauge(
            "serving_slo_requests_observed",
            "finished requests consumed by the SLO accounting",
        ).set(float(snap["requests_observed"]))
        return snap

    def timelines(self, since: Optional[float] = None,
                  tenants=None, predicate=None) -> list:
        """The retained finished timelines (oldest first), optionally
        only those submitted at/after monotonic stamp ``since`` — how
        the load harness scopes its aggregation to one timed window.
        ``tenants`` (a container of tenant names) and/or ``predicate``
        (timeline dict -> bool) narrow further — how a deploy scopes
        burn to its canary traffic slice (serving/deploy.py)."""
        with self._lock:
            tls = list(self._timelines)
        if since is not None:
            tls = [tl for tl in tls if tl["submitted_at"] >= since]
        if tenants is not None:
            tls = [tl for tl in tls if tl.get("tenant") in tenants]
        if predicate is not None:
            tls = [tl for tl in tls if predicate(tl)]
        return tls

    def context_payload(self) -> dict:
        """Flight-recorder context: the policy, the last N finished
        timelines, and the timeline-so-far of every in-flight request —
        so a watchdog/preempt/OOM dump names the requests it hurt with
        their lifecycles attached."""
        with self._lock:
            recent = list(self._timelines)
            active = list(self._active.values())
        return {
            "policy": dataclasses.asdict(self.policy),
            "recent": recent[-16:],
            "active": [req.timeline() for req in active],
        }
