"""Serving metrics: what a dashboard needs to judge a decode engine.

Tracked per engine instance, aggregated in-process (no external metrics
dependency — the container is zero-egress):

* **time-to-first-token** (TTFT): submit -> first token available, the
  user-facing latency of admission + queueing + prefill;
* **per-step decode latency**: one compiled decode step over all active
  slots, the engine's heartbeat;
* **tokens/s**: decoded tokens over busy time (sum of step latencies) and
  over wall time since the first step — busy excludes idle waits, wall
  matches what a load test observes;
* **queue depth** and **slot occupancy**: where the backpressure story
  lives (scheduler watermark / convoy detection).

Exported through ``utils/logging.py``: ``ServingMetrics.log()`` emits one
structured ``serving_metrics`` event with the snapshot as key-values, so
the serving process logs in the same shape as the trainer — and through
the telemetry spine: ``publish()`` mirrors the snapshot into the
process-wide metrics registry (``telemetry/registry.py``) as
``serving_*`` gauges, which is what the HTTP front end's ``/metrics``
serves as Prometheus text exposition (the JSON shape stays available at
``/metrics.json``).

Concurrency contract (hammer-tested in tests/test_serving.py): every
``record_*`` and ``snapshot()`` takes the one instance lock, every
division in ``snapshot()`` is guarded against its empty-window /
zero-denominator edge, so concurrent recording and scraping can never
crash the scrape.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return float(sorted_vals[i])


# Registry-histogram buckets for the spec acceptance distribution: one
# bucket per accepted-draft count.  Draft depths beyond 16 land in +Inf —
# acceptable resolution loss (spec_k above 16 is outside the useful range,
# docs/serving.md) in exchange for a FIXED bucket layout, which idempotent
# registration requires.
SPEC_ACCEPT_BUCKETS = tuple(float(i) for i in range(17))

# Latency histogram buckets (seconds) for the request-lifecycle
# distributions (TTFT / TPOT / queue-wait / end-to-end).  Sub-ms floor
# for a warm CPU decode tick, 60s ceiling for a cold-compile TTFT;
# FIXED so idempotent registration holds across servers in one process.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# The lifecycle latency histograms ``publish()`` maintains: snapshot
# field stem -> registry metric name.  Observations are queued by the
# ``record_*`` sites and DRAINED into the histograms at publish (the
# ``serving_spec_accept`` delta pattern: snapshots are point-in-time,
# histogram observations are not, so repeated scrapes never
# double-count).
LATENCY_HISTOGRAMS = {
    "ttft": "serving_ttft_seconds",
    "tpot": "serving_tpot_seconds",
    "queue_wait": "serving_queue_wait_seconds",
    "e2e": "serving_e2e_seconds",
}


class ServingMetrics:
    """Thread-safe rolling serving metrics (bounded windows)."""

    def __init__(self, window: int = 2048):
        if window < 1:
            # deque(maxlen=0) silently discards every observation — a
            # scrape would then report all-zero latencies while traffic
            # flows, which reads as an outage that is not happening.
            raise ValueError(f"window must be >= 1, got {window}")
        self._lock = threading.Lock()
        self._ttft = collections.deque(maxlen=window)
        self._prefill_secs = collections.deque(maxlen=window)
        self._step_secs = collections.deque(maxlen=window)
        self._occupancy = collections.deque(maxlen=window)
        # Request-lifecycle latency windows (snapshot percentiles) and
        # the publish-drained histogram queues: each entry is
        # ``(seconds, tenant)`` awaiting its one observation into the
        # registry histogram named in LATENCY_HISTOGRAMS.
        self._queue_wait = collections.deque(maxlen=window)
        self._tpot = collections.deque(maxlen=window)
        self._e2e = collections.deque(maxlen=window)
        self._hist_pending: dict = {k: [] for k in LATENCY_HISTOGRAMS}
        self.tokens_total = 0
        self.steps_total = 0
        self.busy_secs = 0.0
        self.requests_admitted = 0
        self.requests_rejected = 0
        self.requests_completed = 0
        self.requests_expired = 0
        # Overload control (serving/overload.py): requests shed by the
        # degradation ladder (structured 503 + retry_after), and
        # hedging losers cancelled after their duplicate won.
        self.requests_shed = 0
        self.requests_cancelled = 0
        # Resilience counters: engine-loop exceptions survived, and
        # watchdog wedge detections (each of which failed all in-flight
        # requests and poisoned the server).
        self.engine_errors = 0
        self.watchdog_trips = 0
        self.max_active_slots = 0
        self.queue_depth = 0
        # Paged KV + prefix cache + multi-tenant scheduling (PR6): pool
        # occupancy gauges, token-weighted prefix hit accounting,
        # preemption counters, and a per-tenant ledger published as
        # labeled ``serving_tenant_*`` gauges.
        self.kv_pages_total = 0
        self.kv_pages_free = 0
        self.kv_pages_used = 0
        # Device bytes of one KV page (page geometry × dtype × layers ×
        # K/V), so the pool gauges price in bytes as well as pages — the
        # hook the HBM ledger (telemetry/memory.py) reads.
        self.kv_page_bytes = 0
        self.prefix_cache_nodes = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0
        self.prefix_lookup_tokens = 0
        self.preemptions_total = 0
        self.admissions_blocked = 0
        # Chunked prefill (engine prefill_chunk mode): admissions that
        # took the chunked path, and total prefill windows dispatched.
        self.chunked_admissions_total = 0
        self.prefill_chunks_total = 0
        # Batched LoRA adapter pool (serving/adapter_pool.py): slot
        # occupancy (free/used/total EXCLUDING the trash slot), resident
        # count, hit/load/eviction counters, and the device bytes one
        # slot occupies across every stack leaf — the pricing behind
        # serving_adapter_pool_bytes{state=}.
        self.adapter_slots_free = 0
        self.adapter_slots_used = 0
        self.adapter_slots_total = 0
        self.adapters_resident = 0
        self.adapter_hits = 0
        self.adapter_loads = 0
        self.adapter_evictions = 0
        self.adapter_slot_bytes = 0
        self._tenants: dict = {}
        # Speculative decoding (engine spec mode): acceptance accounting.
        # One histogram entry per (verify step, active slot); keys are
        # accepted-draft counts 0..K.
        self.spec_draft_k = 0
        self.spec_steps_total = 0
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_accept_hist: collections.Counter = collections.Counter()
        # Watermark of what publish() already observed into the registry
        # histogram: the snapshot is cumulative, histogram observations
        # are not, so publish() feeds only the delta.
        self._spec_hist_published: collections.Counter = collections.Counter()
        self._first_step_at: Optional[float] = None
        self._last_step_at: Optional[float] = None

    # -- recording -------------------------------------------------------

    def record_ttft(self, seconds: float,
                    tenant: Optional[str] = None) -> None:
        with self._lock:
            self._ttft.append(float(seconds))
            self._hist_pending["ttft"].append(
                (float(seconds), tenant or "default")
            )

    def record_queue_wait(self, seconds: float,
                          tenant: Optional[str] = None) -> None:
        """Submit -> first admission: the queueing half of TTFT (the
        other half is prefill compute), so saturation is attributable."""
        with self._lock:
            self._queue_wait.append(float(seconds))
            self._hist_pending["queue_wait"].append(
                (float(seconds), tenant or "default")
            )

    def record_tpot(self, deltas, tenant: Optional[str] = None) -> None:
        """Inter-token latencies (seconds) of one finished request —
        the client-observed time-per-output-token distribution."""
        with self._lock:
            for d in deltas:
                self._tpot.append(float(d))
                self._hist_pending["tpot"].append(
                    (float(d), tenant or "default")
                )

    def record_e2e(self, seconds: float,
                   tenant: Optional[str] = None) -> None:
        """Submit -> finish wall latency of one completed request."""
        with self._lock:
            self._e2e.append(float(seconds))
            self._hist_pending["e2e"].append(
                (float(seconds), tenant or "default")
            )

    def record_prefill(self, seconds: float, tokens: int = 1) -> None:
        """One out-of-band prefill: its latency counts as busy time and
        it emits the request's first token."""
        with self._lock:
            self._prefill_secs.append(float(seconds))
            self.busy_secs += float(seconds)
            self.tokens_total += int(tokens)

    def record_step(self, seconds: float, active_slots: int,
                    total_slots: int, tokens: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._step_secs.append(float(seconds))
            self._occupancy.append(
                active_slots / total_slots if total_slots else 0.0
            )
            self.busy_secs += float(seconds)
            self.tokens_total += int(tokens)
            self.steps_total += 1
            self.max_active_slots = max(self.max_active_slots, active_slots)
            if self._first_step_at is None:
                self._first_step_at = now - seconds
            self._last_step_at = now

    def _tenant(self, tenant: str) -> dict:
        # Caller holds the lock.
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = {
                "admitted": 0, "rejected": 0, "preempted": 0,
                "queue_depth": 0,
            }
        return t

    def record_admission(self, queue_depth: int,
                         tenant: Optional[str] = None,
                         tenant_depth: Optional[int] = None) -> None:
        with self._lock:
            self.requests_admitted += 1
            self.queue_depth = int(queue_depth)
            if tenant is not None:
                t = self._tenant(tenant)
                t["admitted"] += 1
                if tenant_depth is not None:
                    t["queue_depth"] = int(tenant_depth)

    def record_rejection(self, tenant: Optional[str] = None) -> None:
        with self._lock:
            self.requests_rejected += 1
            if tenant is not None:
                self._tenant(tenant)["rejected"] += 1

    def record_completion(self) -> None:
        with self._lock:
            self.requests_completed += 1

    def record_expiry(self) -> None:
        with self._lock:
            self.requests_expired += 1

    def record_shed(self, tenant: Optional[str] = None) -> None:
        """One request shed by the degradation ladder (overload.py)."""
        with self._lock:
            self.requests_shed += 1
            if tenant is not None:
                t = self._tenant(tenant)
                t["shed"] = t.get("shed", 0) + 1

    def record_cancellation(self) -> None:
        """One hedging loser dropped after its duplicate won."""
        with self._lock:
            self.requests_cancelled += 1

    def record_engine_error(self) -> None:
        with self._lock:
            self.engine_errors += 1

    def record_watchdog_trip(self) -> None:
        with self._lock:
            self.watchdog_trips += 1

    def record_queue_depth(self, depth: int,
                           tenant: Optional[str] = None,
                           tenant_depth: Optional[int] = None) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            if tenant is not None and tenant_depth is not None:
                self._tenant(tenant)["queue_depth"] = int(tenant_depth)

    def record_preemption(self, tenant: str) -> None:
        """One preempt-and-requeue under page pressure."""
        with self._lock:
            self.preemptions_total += 1
            self._tenant(tenant)["preempted"] += 1

    def record_admission_blocked(self) -> None:
        """An admission deferred because the page pool could not hold
        the prompt (the request re-queued, not rejected)."""
        with self._lock:
            self.admissions_blocked += 1

    def record_chunked_admission(self) -> None:
        """One long prompt admitted via the chunked-prefill path."""
        with self._lock:
            self.chunked_admissions_total += 1

    def record_prefill_chunk(self) -> None:
        """One chunked-prefill window dispatched (decode ticks run
        between windows — the interleaving behind unblocked TTFT)."""
        with self._lock:
            self.prefill_chunks_total += 1

    def record_kv(self, free: int, used: int, total: int,
                  prefix_nodes: int,
                  bytes_per_page: Optional[int] = None) -> None:
        """Paged-pool occupancy snapshot (allocatable pages — the trash
        page is excluded from ``total``).  ``bytes_per_page`` (device
        bytes of one page across all layers, K and V) turns the page
        counts into ``serving_kv_pool_bytes{state=}`` gauges."""
        with self._lock:
            self.kv_pages_free = int(free)
            self.kv_pages_used = int(used)
            self.kv_pages_total = int(total)
            self.prefix_cache_nodes = int(prefix_nodes)
            if bytes_per_page is not None:
                self.kv_page_bytes = int(bytes_per_page)

    def record_adapters(self, free: int, used: int, total: int,
                        resident, hits: int, loads: int, evictions: int,
                        bytes_per_slot: Optional[int] = None) -> None:
        """Adapter-pool snapshot (serving/adapter_pool.py): slot
        occupancy, cumulative hit/load/eviction counters, and the
        per-slot device-byte price."""
        with self._lock:
            self.adapter_slots_free = int(free)
            self.adapter_slots_used = int(used)
            self.adapter_slots_total = int(total)
            self.adapters_resident = len(resident) if not isinstance(
                resident, int
            ) else int(resident)
            self.adapter_hits = int(hits)
            self.adapter_loads = int(loads)
            self.adapter_evictions = int(evictions)
            if bytes_per_slot is not None:
                self.adapter_slot_bytes = int(bytes_per_slot)

    def record_prefix_stats(self, hits: int, misses: int,
                            hit_tokens: int, lookup_tokens: int) -> None:
        """Cumulative prefix-cache counters (token-weighted hit rate:
        hit_tokens / lookup_tokens)."""
        with self._lock:
            self.prefix_hits = int(hits)
            self.prefix_misses = int(misses)
            self.prefix_hit_tokens = int(hit_tokens)
            self.prefix_lookup_tokens = int(lookup_tokens)

    def record_spec(self, accepted_counts, draft_k: int) -> None:
        """One speculative verify step: per-active-slot accepted-draft
        counts (each slot advanced ``accepted + 1`` tokens)."""
        with self._lock:
            self.spec_draft_k = int(draft_k)
            self.spec_steps_total += 1
            for a in accepted_counts:
                self.spec_accept_hist[int(a)] += 1
                self.spec_drafted_tokens += int(draft_k)
                self.spec_accepted_tokens += int(a)

    # -- reading ---------------------------------------------------------

    def snapshot(self) -> dict:
        """One flat dict of the current aggregates (JSON-safe floats)."""
        with self._lock:
            ttft = sorted(self._ttft)
            steps = sorted(self._step_secs)
            wall = (
                self._last_step_at - self._first_step_at
                if self._first_step_at is not None
                and self._last_step_at is not None
                and self._last_step_at > self._first_step_at
                else 0.0
            )
            occ = (
                sum(self._occupancy) / len(self._occupancy)
                if self._occupancy else 0.0
            )
            prefill = sorted(self._prefill_secs)
            queue_wait = sorted(self._queue_wait)
            tpot = sorted(self._tpot)
            e2e = sorted(self._e2e)
            return {
                "ttft_p50_ms": round(_percentile(ttft, 0.5) * 1e3, 3),
                "ttft_p99_ms": round(_percentile(ttft, 0.99) * 1e3, 3),
                "decode_step_p50_ms": round(
                    _percentile(steps, 0.5) * 1e3, 3
                ),
                "decode_step_p99_ms": round(
                    _percentile(steps, 0.99) * 1e3, 3
                ),
                "prefill_p50_ms": round(
                    _percentile(prefill, 0.5) * 1e3, 3
                ),
                # TTFT decomposition: submit->admit queue wait vs the
                # admit->first-token prefill compute, so a saturated
                # queue and a slow prefill read differently.
                "prefill_p99_ms": round(
                    _percentile(prefill, 0.99) * 1e3, 3
                ),
                "queue_wait_p50_ms": round(
                    _percentile(queue_wait, 0.5) * 1e3, 3
                ),
                "queue_wait_p99_ms": round(
                    _percentile(queue_wait, 0.99) * 1e3, 3
                ),
                "tpot_p50_ms": round(_percentile(tpot, 0.5) * 1e3, 3),
                "tpot_p99_ms": round(_percentile(tpot, 0.99) * 1e3, 3),
                "e2e_p50_ms": round(_percentile(e2e, 0.5) * 1e3, 3),
                "e2e_p99_ms": round(_percentile(e2e, 0.99) * 1e3, 3),
                "tokens_total": self.tokens_total,
                "decode_steps_total": self.steps_total,
                "tokens_per_sec_busy": round(
                    self.tokens_total / self.busy_secs, 1
                ) if self.busy_secs > 0 else 0.0,
                "tokens_per_sec_wall": round(
                    self.tokens_total / wall, 1
                ) if wall > 0 else 0.0,
                "slot_occupancy_mean": round(occ, 4),
                "max_active_slots": self.max_active_slots,
                "queue_depth": self.queue_depth,
                "requests_admitted": self.requests_admitted,
                "requests_rejected": self.requests_rejected,
                "requests_completed": self.requests_completed,
                "requests_expired": self.requests_expired,
                "requests_shed": self.requests_shed,
                "requests_cancelled": self.requests_cancelled,
                "engine_errors": self.engine_errors,
                "watchdog_trips": self.watchdog_trips,
                "kv_pages_total": self.kv_pages_total,
                "kv_pages_free": self.kv_pages_free,
                "kv_pages_used": self.kv_pages_used,
                # Page counts priced in device bytes (geometry × dtype):
                # the serving end of the HBM ledger.
                "kv_pool_bytes": {
                    "free": self.kv_pages_free * self.kv_page_bytes,
                    "used": self.kv_pages_used * self.kv_page_bytes,
                    "total": self.kv_pages_total * self.kv_page_bytes,
                },
                "prefix_cache_nodes": self.prefix_cache_nodes,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_tokens_saved": self.prefix_hit_tokens,
                "prefix_hit_rate": round(
                    self.prefix_hit_tokens / self.prefix_lookup_tokens, 4
                ) if self.prefix_lookup_tokens else 0.0,
                "preemptions_total": self.preemptions_total,
                "admissions_blocked": self.admissions_blocked,
                "chunked_admissions_total": self.chunked_admissions_total,
                "prefill_chunks_total": self.prefill_chunks_total,
                "adapter_slots_free": self.adapter_slots_free,
                "adapter_slots_used": self.adapter_slots_used,
                "adapter_slots_total": self.adapter_slots_total,
                "adapters_resident": self.adapters_resident,
                "adapter_hits_total": self.adapter_hits,
                "adapter_loads_total": self.adapter_loads,
                "adapter_evictions_total": self.adapter_evictions,
                # Slot counts priced in device bytes (stack geometry x
                # dtype): the adapter end of the HBM ledger, beside
                # kv_pool_bytes.
                "adapter_pool_bytes": {
                    "free": self.adapter_slots_free
                    * self.adapter_slot_bytes,
                    "used": self.adapter_slots_used
                    * self.adapter_slot_bytes,
                    "total": self.adapter_slots_total
                    * self.adapter_slot_bytes,
                },
                "tenants": {
                    name: dict(stats)
                    for name, stats in sorted(self._tenants.items())
                },
                "spec_draft_k": self.spec_draft_k,
                "spec_steps_total": self.spec_steps_total,
                "spec_drafted_tokens": self.spec_drafted_tokens,
                "spec_accepted_tokens": self.spec_accepted_tokens,
                "spec_acceptance_rate": round(
                    self.spec_accepted_tokens / self.spec_drafted_tokens, 4
                ) if self.spec_drafted_tokens else 0.0,
                # Mean tokens committed per slot per verify step (1..K+1).
                "spec_tokens_per_step": round(
                    sum((a + 1) * c for a, c in self.spec_accept_hist.items())
                    / sum(self.spec_accept_hist.values()), 3
                ) if self.spec_accept_hist else 0.0,
                "spec_accept_hist": {
                    str(a): self.spec_accept_hist[a]
                    for a in sorted(self.spec_accept_hist)
                },
            }

    def log(self, logger=None) -> dict:
        """Emit the snapshot as one structured log event (and return it)."""
        if logger is None:
            from ml_trainer_tpu.utils.logging import get_logger

            logger = get_logger("ml_trainer_tpu.serving")
        snap = self.snapshot()
        logger.info("serving_metrics", **snap)
        return snap

    def publish(self, registry=None) -> dict:
        """Mirror the snapshot into the telemetry registry as
        ``serving_*`` gauges, and return the snapshot.  Gauges, not
        counters: the snapshot is a point-in-time view and several of its
        fields legally move both ways (queue depth, occupancy).

        The spec acceptance distribution is the exception: it publishes
        as the registry's REAL ``Histogram`` type
        (``serving_spec_accept``, one bucket per accepted-draft count),
        so Prometheus scrapes get proper cumulative ``_bucket{le=...}``
        exposition and ``histogram_quantile`` works on it.  The snapshot
        counts are cumulative while histogram observations are not, so a
        per-instance watermark feeds only the delta — publish() stays
        idempotent under repeated scrapes and safe under the concurrent
        record/scrape hammer (the watermark update holds the instance
        lock)."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        snap = self.snapshot()
        # Request-lifecycle latency histograms (TTFT / TPOT / queue-wait
        # / e2e): drain the pending observations queued by record_* into
        # the registry's REAL Histogram type — proper cumulative
        # ``_bucket{le=...}`` exposition, per-tenant labels, and
        # publish() stays idempotent under repeated scrapes (each
        # observation is consumed exactly once).
        with self._lock:
            drained = {
                k: v for k, v in self._hist_pending.items() if v
            }
            for k in drained:
                self._hist_pending[k] = []
        for stem, obs in drained.items():
            h = r.histogram(
                LATENCY_HISTOGRAMS[stem],
                f"request {stem} latency (seconds)",
                labelnames=("tenant",),
                buckets=LATENCY_BUCKETS,
            )
            for seconds, tenant in obs:
                h.labels(tenant=tenant).observe(seconds)
        for key, value in snap.items():
            if key == "tenants":
                # Per-tenant ledger -> labeled serving_tenant_* gauges
                # (the PR5 cluster_<field>{host=} arrangement applied to
                # tenants): one series per (field, tenant).
                for tenant, stats in value.items():
                    for fname, fval in stats.items():
                        r.gauge(
                            f"serving_tenant_{fname}",
                            f"per-tenant {fname}",
                            labelnames=("tenant",),
                        ).labels(tenant=tenant).set(float(fval))
                continue
            if key == "adapter_pool_bytes":
                g = r.gauge(
                    "serving_adapter_pool_bytes",
                    "LoRA adapter pool device bytes by state "
                    "(stack geometry x dtype, all targets/layers)",
                    labelnames=("state",),
                )
                for state_name, v in value.items():
                    g.labels(state=state_name).set(float(v))
                continue
            if key == "kv_pool_bytes":
                # Labeled by pool state, next to the kv_pages_* gauges,
                # so one scrape prices the serving engine's HBM.
                g = r.gauge(
                    "serving_kv_pool_bytes",
                    "paged KV pool device bytes by state "
                    "(page geometry x dtype x layers x K/V)",
                    labelnames=("state",),
                )
                for state_name, v in value.items():
                    g.labels(state=state_name).set(float(v))
                continue
            if key == "spec_accept_hist":
                h = r.histogram(
                    "serving_spec_accept",
                    "accepted draft tokens per verify step per slot",
                    buckets=SPEC_ACCEPT_BUCKETS,
                )
                with self._lock:
                    deltas = [
                        (int(a), int(c) - self._spec_hist_published[int(a)])
                        for a, c in value.items()
                    ]
                    for a, d in deltas:
                        self._spec_hist_published[a] += max(d, 0)
                for a, d in deltas:
                    for _ in range(d):
                        h.observe(float(a))
                continue
            r.gauge(f"serving_{key}").set(float(value))
        # The JSONL sink (ML_TRAINER_TPU_METRICS_JSONL) gets the same
        # snapshot as one ``serving_metrics`` record — the no-scraper
        # path, same idiom as train_metrics' per-sync registry write.
        from ml_trainer_tpu.telemetry.export import default_sink

        sink = default_sink()
        if sink is not None:
            sink.write(snap, kind="serving_metrics")
        return snap
