"""Radix cache of KV pages keyed on token-block prefixes.

Production traffic is prefix-heavy: the same system prompt, the same
few-shot preamble, the same retrieval header lead thousands of requests.
The contiguous engine re-prefills those tokens for every user.  This
cache maps PAGE-SIZE token blocks to already-filled KV pages, so a new
request walks the radix tree, pins the longest matching chain of pages,
and prefills only its unshared suffix (engine: the paged continuation
window).

Design (the vLLM/SGLang block-hash arrangement, as a radix trie):

* **Block granularity.**  A node keys on a tuple of exactly
  ``page_size`` tokens; its page holds those positions' K/V, valid only
  under the node's full root path (causal attention makes a position's
  K/V a function of its entire prefix — the trie path IS that prefix).
  Sharing below block granularity would require copying partial pages;
  at block granularity a divergent request simply stops matching at the
  last full block and writes its own fresh pages from there —
  copy-on-write by construction, since shared pages are never written
  (appends start on the first un-shared page boundary).
* **Refcount-tied eviction.**  Cache residency holds one pool refcount
  per page.  ``evict`` walks leaves in LRU order and only frees pages
  with no other holder (refcount 1), so a page some slot is actively
  attending can never be reclaimed out from under it.
* **Donation.**  Completed and PREEMPTED requests insert their written
  full blocks (prompt and generated tokens alike) before their slot
  releases, so a preempt-and-requeue victim resumes by re-pinning its
  own pages — resume prefill shrinks to the last partial block.

Single-threaded like the pool: only the engine loop touches it.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ml_trainer_tpu.serving.kv_pool import KVPagePool


class _Node:
    __slots__ = ("block", "page", "children", "parent", "last_used")

    def __init__(self, block: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.block = block
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0


class PrefixCache:
    """Radix trie over page-size token blocks -> refcounted KV pages."""

    def __init__(self, pool: KVPagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._root = _Node((), 0, None)
        self._clock = itertools.count(1)
        self._nodes = 0
        # Stats feeding serving metrics: hit rate is hit_tokens over
        # lookup_tokens (token-weighted — one long hit matters more than
        # three empty ones).
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.lookup_tokens = 0

    def __len__(self) -> int:
        return self._nodes

    # -- read ------------------------------------------------------------

    def lookup(self, tokens: np.ndarray, max_blocks: int) -> Tuple[List[int], int]:
        """Longest cached chain for ``tokens`` (at most ``max_blocks``
        full blocks).  Returns ``(pages, matched_tokens)`` with every
        returned page ALREADY retained for the caller (one pool count
        each) — the slot owns those references until its reset."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        limit = min(int(max_blocks), len(toks) // ps)
        node = self._root
        pages: List[int] = []
        now = next(self._clock)
        for i in range(limit):
            key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = now
            pages.append(child.page)
            node = child
        self.pool.retain(pages)
        matched = len(pages) * ps
        self.lookup_tokens += limit * ps
        self.hit_tokens += matched
        if pages:
            self.hits += 1
        else:
            self.misses += 1
        return pages, matched

    def hit_rate(self) -> float:
        return (
            self.hit_tokens / self.lookup_tokens
            if self.lookup_tokens else 0.0
        )

    # -- write -----------------------------------------------------------

    def insert(self, tokens: np.ndarray, pages: List[int]) -> int:
        """Register a slot's filled chain: block ``i`` of ``tokens`` is
        held by ``pages[i]``.  Blocks already cached are skipped (the
        first writer wins; the duplicate page stays slot-owned and frees
        with the slot); new nodes retain their page for cache residency.
        Returns the number of newly registered blocks."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        n_blocks = min(len(pages), len(toks) // ps)
        node = self._root
        added = 0
        now = next(self._clock)
        for i in range(n_blocks):
            key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                page = pages[i]
                if page == 0:
                    break  # trash can never carry cacheable K/V
                self.pool.retain([page])
                child = _Node(key, page, node)
                node.children[key] = child
                self._nodes += 1
                added += 1
            child.last_used = now
            node = child
        return added

    # -- eviction --------------------------------------------------------

    def evict(self, want_pages: int) -> int:
        """Free up to ``want_pages`` pool pages by dropping LRU leaves
        whose pages have no other holder (refcount 1 — cache residency
        only).  Interior nodes become evictable as their children go, so
        the loop keeps sweeping until it frees enough or nothing moves.
        Returns pages actually freed."""
        freed = 0
        while freed < want_pages:
            candidates = [
                n for n in self._leaves()
                if self.pool.refcount[n.page] == 1
            ]
            if not candidates:
                break
            candidates.sort(key=lambda n: n.last_used)
            progressed = False
            for node in candidates:
                if freed >= want_pages:
                    break
                self._drop(node)
                freed += self.pool.release([node.page])
                progressed = True
            if not progressed:
                break
        return freed

    def _leaves(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                yield node

    def _drop(self, node: _Node) -> None:
        del node.parent.children[node.block]
        self._nodes -= 1
