"""Radix cache of KV pages keyed on token-block prefixes.

Production traffic is prefix-heavy: the same system prompt, the same
few-shot preamble, the same retrieval header lead thousands of requests.
The contiguous engine re-prefills those tokens for every user.  This
cache maps PAGE-SIZE token blocks to already-filled KV pages, so a new
request walks the radix tree, pins the longest matching chain of pages,
and prefills only its unshared suffix (engine: the paged continuation
window).

Design (the vLLM/SGLang block-hash arrangement, as a radix trie):

* **Block granularity.**  A node keys on a tuple of exactly
  ``page_size`` tokens; its page holds those positions' K/V, valid only
  under the node's full root path (causal attention makes a position's
  K/V a function of its entire prefix — the trie path IS that prefix).
  Sharing below block granularity would require copying partial pages;
  at block granularity a divergent request simply stops matching at the
  last full block and writes its own fresh pages from there —
  copy-on-write by construction, since shared pages are never written
  (appends start on the first un-shared page boundary).
* **Namespaces (tenant isolation).**  Each ``namespace`` gets its own
  trie root; lookups never cross namespaces.  Whether a cached block
  exists is observable to a caller (TTFT, hit-rate metrics), so a
  globally shared trie is a cross-tenant side channel: any tenant could
  probe block-by-block whether another tenant's exact prompt — or
  generated output, since completed requests donate those blocks too —
  is resident.  The engine passes the request's tenant as the namespace
  by default (``prefix_scope="tenant"``); explicitly trusted
  deployments can opt back into one shared namespace
  (``prefix_scope="global"``).
* **Refcount-tied eviction.**  Cache residency holds one pool refcount
  per page.  ``evict`` drops LRU leaves (across ALL namespaces — page
  pressure is global) and only frees pages with no other holder
  (refcount 1), so a page some slot is actively attending can never be
  reclaimed out from under it.
* **Donation.**  Completed and PREEMPTED requests insert their written
  full blocks (prompt and generated tokens alike) before their slot
  releases, so a preempt-and-requeue victim resumes by re-pinning its
  own pages — resume prefill shrinks to the last partial block.

Single-threaded like the pool: only the engine loop touches it.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ml_trainer_tpu.serving.kv_pool import KVPagePool


class _Node:
    __slots__ = ("block", "page", "children", "parent", "last_used")

    def __init__(self, block: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.block = block
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0


class PrefixCache:
    """Radix trie over page-size token blocks -> refcounted KV pages,
    one root per namespace (tenant)."""

    def __init__(self, pool: KVPagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._roots: Dict[str, _Node] = {}
        self._clock = itertools.count(1)
        self._nodes = 0
        # Stats feeding serving metrics: hit rate is hit_tokens over
        # lookup_tokens (token-weighted — one long hit matters more than
        # three empty ones).
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.lookup_tokens = 0

    def __len__(self) -> int:
        return self._nodes

    def _root(self, namespace: str) -> _Node:
        root = self._roots.get(namespace)
        if root is None:
            root = self._roots[namespace] = _Node((), 0, None)
        return root

    # -- read ------------------------------------------------------------

    def lookup(self, tokens: np.ndarray, max_blocks: int,
               namespace: str = "",
               record: bool = True) -> Tuple[List[int], int]:
        """Longest chain cached under ``namespace`` for ``tokens`` (at
        most ``max_blocks`` full blocks).  Returns
        ``(pages, matched_tokens)`` with every returned page ALREADY
        retained for the caller (one pool count each) — the slot owns
        those references until its reset.

        ``record=False`` runs the walk without touching stats OR the
        matched nodes' LRU stamps: the engine's retry of a blocked
        ("no_memory") admission must not inflate the hit rate or re-heat
        the blocked request's own prefix pages while eviction is trying
        to relieve the very pressure blocking it."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        limit = min(int(max_blocks), len(toks) // ps)
        node = self._root(namespace)
        pages: List[int] = []
        now = next(self._clock) if record else 0
        for i in range(limit):
            key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                break
            if record:
                child.last_used = now
            pages.append(child.page)
            node = child
        self.pool.retain(pages)
        matched = len(pages) * ps
        if record:
            self.lookup_tokens += limit * ps
            self.hit_tokens += matched
            if pages:
                self.hits += 1
            else:
                self.misses += 1
        return pages, matched

    def hit_rate(self) -> float:
        return (
            self.hit_tokens / self.lookup_tokens
            if self.lookup_tokens else 0.0
        )

    # -- write -----------------------------------------------------------

    def insert(self, tokens: np.ndarray, pages: List[int],
               namespace: str = "") -> int:
        """Register a slot's filled chain under ``namespace``: block
        ``i`` of ``tokens`` is held by ``pages[i]``.  Blocks already
        cached are skipped (the first writer wins; the duplicate page
        stays slot-owned and frees with the slot); new nodes retain
        their page for cache residency.  Returns the number of newly
        registered blocks."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        n_blocks = min(len(pages), len(toks) // ps)
        node = self._root(namespace)
        added = 0
        now = next(self._clock)
        for i in range(n_blocks):
            key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                page = pages[i]
                if page == 0:
                    break  # trash can never carry cacheable K/V
                self.pool.retain([page])
                child = _Node(key, page, node)
                node.children[key] = child
                self._nodes += 1
                added += 1
            child.last_used = now
            node = child
        return added

    # -- eviction --------------------------------------------------------

    def evict(self, want_pages: int) -> int:
        """Free up to ``want_pages`` pool pages by dropping LRU leaves
        (across every namespace) whose pages have no other holder
        (refcount 1 — cache residency only).  One heapify over the
        current leaves, then each freed node is O(log n): a dropped
        node's parent is pushed as it becomes a leaf, so a deep chain
        drains in a single pass instead of one full leaf rescan per
        tree level.  Returns pages actually freed."""
        freed = 0
        # Refcounts of surviving nodes cannot change mid-evict (single
        # threaded; every node holds a distinct page), so filtering
        # pinned leaves up front is safe — they stay pinned all call.
        heap = [
            (n.last_used, n.page, n)
            for n in self._leaves()
            if self.pool.refcount[n.page] == 1
        ]
        heapq.heapify(heap)
        while heap and freed < want_pages:
            _, _, node = heapq.heappop(heap)
            parent = node.parent
            self._drop(node)
            freed += self.pool.release([node.page])
            if (
                parent.parent is not None
                and not parent.children
                and self.pool.refcount[parent.page] == 1
            ):
                heapq.heappush(
                    heap, (parent.last_used, parent.page, parent)
                )
        return freed

    def _leaves(self):
        stack = [
            n for root in self._roots.values()
            for n in root.children.values()
        ]
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                yield node

    def _drop(self, node: _Node) -> None:
        del node.parent.children[node.block]
        self._nodes -= 1
