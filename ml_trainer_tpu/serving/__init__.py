"""Continuous-batching serving engine (docs/serving.md).

One preallocated slot cache, one compiled per-token decode step;
requests join and leave at token boundaries with no recompilation.

    from ml_trainer_tpu.serving import Server

    server = Server(model, variables, max_batch=8)
    stream = server.submit(prompt_ids, max_new_tokens=64)
    for token in stream: ...          # streamed
    full = server.complete(prompt_ids, 64)   # blocking
"""

from ml_trainer_tpu.serving.api import Server, TokenStream
from ml_trainer_tpu.serving.engine import SlotDecodeEngine
from ml_trainer_tpu.serving.metrics import ServingMetrics
from ml_trainer_tpu.serving.scheduler import (
    AdmissionError,
    DeadlineExceeded,
    EngineUnhealthy,
    FifoScheduler,
    Request,
)

__all__ = [
    "Server",
    "TokenStream",
    "SlotDecodeEngine",
    "ServingMetrics",
    "FifoScheduler",
    "Request",
    "AdmissionError",
    "DeadlineExceeded",
    "EngineUnhealthy",
]
