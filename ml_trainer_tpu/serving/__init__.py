"""Continuous-batching serving engine (docs/serving.md).

One compiled per-token decode step over a slot cache; requests join and
leave at token boundaries with no recompilation.  Opt into the paged KV
cache + radix prefix cache + multi-tenant scheduler with
``kv_page_size``/``tenants``:

    from ml_trainer_tpu.serving import Server, TenantConfig

    server = Server(model, variables, max_batch=8,
                    kv_page_size=16,              # paged KV + prefix cache
                    tenants={"pro": TenantConfig(weight=3.0)})
    stream = server.submit(prompt_ids, max_new_tokens=64, tenant="pro")
    for token in stream: ...          # streamed
    full = server.complete(prompt_ids, 64)   # blocking

Scale out with the disaggregated prefill/decode router (router.py +
transfer.py, docs/serving.md "Disaggregated serving"):

    from ml_trainer_tpu.serving import Router

    router = Router.build(model, variables,
                          roles=["prefill", "decode", "decode"],
                          kv_page_size=16)
    out = router.complete(prompt_ids, 64, session="chat-1")
"""

from ml_trainer_tpu.serving.adapter_pool import (
    AdapterConfig,
    AdapterPool,
    AdapterPoolExhausted,
    UnknownAdapter,
)
from ml_trainer_tpu.serving.api import Server, TokenStream
from ml_trainer_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig
from ml_trainer_tpu.serving.engine import SlotDecodeEngine
from ml_trainer_tpu.serving.kv_pool import KVPagePool
from ml_trainer_tpu.serving.overload import (
    CircuitBreaker,
    DegradationConfig,
    DegradationLadder,
    OverloadShed,
    RollingQuantile,
)
from ml_trainer_tpu.serving.metrics import ServingMetrics
from ml_trainer_tpu.serving.prefix_cache import PrefixCache
from ml_trainer_tpu.serving.scheduler import (
    AdmissionError,
    DeadlineExceeded,
    EngineUnhealthy,
    FifoScheduler,
    Request,
    TenantConfig,
    TenantScheduler,
)
from ml_trainer_tpu.serving.slo import SloPolicy, SloTracker
from ml_trainer_tpu.serving.loadgen import (
    ScheduledRequest,
    TenantLoad,
    poisson_schedule,
    run_open_loop,
    schedule_from_trace,
    schedule_to_records,
)
from ml_trainer_tpu.serving.deploy import DeployConfig, Deployment
from ml_trainer_tpu.serving.fleet import Fleet, RemoteServer
from ml_trainer_tpu.serving.router import Router
from ml_trainer_tpu.serving.transfer import (
    KVSlotExport,
    MigrationCorrupt,
    WeightsMismatch,
    export_kv_slot,
    import_kv_slot,
)

__all__ = [
    "AdapterConfig",
    "AdapterPool",
    "AdapterPoolExhausted",
    "UnknownAdapter",
    "Router",
    "Fleet",
    "RemoteServer",
    "Autoscaler",
    "AutoscalerConfig",
    "DeployConfig",
    "Deployment",
    "WeightsMismatch",
    "CircuitBreaker",
    "DegradationConfig",
    "DegradationLadder",
    "MigrationCorrupt",
    "OverloadShed",
    "RollingQuantile",
    "KVSlotExport",
    "export_kv_slot",
    "import_kv_slot",
    "ScheduledRequest",
    "schedule_to_records",
    "SloPolicy",
    "SloTracker",
    "TenantLoad",
    "poisson_schedule",
    "run_open_loop",
    "schedule_from_trace",
    "Server",
    "TokenStream",
    "SlotDecodeEngine",
    "ServingMetrics",
    "KVPagePool",
    "PrefixCache",
    "FifoScheduler",
    "TenantScheduler",
    "TenantConfig",
    "Request",
    "AdmissionError",
    "DeadlineExceeded",
    "EngineUnhealthy",
]
