"""True multi-process serving fleet (docs/serving.md "Multi-process
fleet").

Every replica is its own OS PROCESS — a real ``Server`` behind its
stdlib HTTP front end — and the router in the driver process talks to
it ONLY over sockets.  Two pieces:

* :class:`RemoteServer` — a duck-typed stand-in for the in-process
  ``Server`` that the existing :class:`~ml_trainer_tpu.serving.Router`
  (and autoscaler, degradation ladder, chaos harness) drives
  unmodified.  Token streams ride ``POST /v1/stream`` NDJSON; KV
  migration ships the serialized :class:`KVSlotExport` bytes over
  ``POST /v1/adopt`` with the CRC verified at the RECEIVING process,
  whose structured verdict (``corrupt`` / ``no_memory`` / ``draining``
  / ``unhealthy``) maps back into the router's fallback-candidate
  machinery as the same exceptions the in-process path raises.

* :class:`Fleet` — the launcher: spawns each replica as
  ``python -m ml_trainer_tpu.serving.fleet --worker ...`` with its own
  port, role, pool geometry and a SHARED on-disk compile cache, waits
  for readiness, and hands the router a ``{name: RemoteServer}`` map.
  ``Fleet.factory`` is an autoscaler ``server_factory`` that spawns a
  REAL process per scale-up; ``RemoteServer.kill_process`` is a real
  ``SIGKILL`` (the chaos ``replica_kill`` path), and ``close`` is a
  graceful shutdown only after evacuation.

Determinism across processes: every worker builds the model with the
same ``jax.random.PRNGKey(seed)`` init, so weights are identical in
every process without shipping checkpoints, and migration is
byte-exact by the same CRC + step-counter machinery the in-process
router pins.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import types
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

from ml_trainer_tpu.utils.logging import get_logger
from ml_trainer_tpu.serving.overload import OverloadShed
from ml_trainer_tpu.serving.scheduler import (
    AdmissionError,
    EngineUnhealthy,
    Request,
)
from ml_trainer_tpu.serving.transfer import (
    MigrationCorrupt,
    WeightsMismatch,
    request_wire_meta,
)

# The router's migration sentinel (api.py carries the same literal so
# api never has to import router).
_MIGRATE = "__kv_migrate__"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _RemoteSlo:
    """``server.slo`` facade over ``GET /slo`` — the router's publish
    loop reads ``snapshot()["attainment"]`` per replica; a dead process
    degrades to perfect attainment instead of wedging the poller."""

    def __init__(self, remote: "RemoteServer"):
        self._remote = remote

    def snapshot(self) -> dict:
        try:
            return self._remote._get("/slo")
        except Exception:
            return {"attainment": {"ttft": 1.0, "tpot": 1.0}}

    def forget(self, req) -> None:  # shadow bookkeeping is local-only
        pass


class RemoteServer:
    """HTTP proxy for one replica PROCESS, duck-typed to the surface
    the router/autoscaler/ladder expect from an in-process ``Server``.

    The constructor fetches ``GET /v1/spec`` and mirrors the engine
    geometry into ``self.engine`` / ``self.scheduler`` namespaces so
    the router's geometry validation, placement math and inflight
    budget work unchanged.  ``submit_request``/``adopt_payload`` open
    long-lived NDJSON streams and pump tokens into the SHADOW request
    from a daemon thread; a severed socket (SIGKILL'd replica) finishes
    the shadow with a retryable ``unhealthy`` error, so the router
    redistributes from the committed prefix exactly like the
    in-process kill path."""

    def __init__(self, url: str, proc: Optional[subprocess.Popen] = None,
                 name: str = "", stream_timeout: float = 600.0,
                 log_path: Optional[str] = None):
        self.url = url.rstrip("/")
        self.proc = proc
        self.name = name or self.url
        self.transport = "http"
        self.log_path = log_path
        self._stream_timeout = float(stream_timeout)
        self._log = get_logger("ml_trainer_tpu.serving.fleet")
        spec = self._get("/v1/spec", timeout=10.0)
        self.pid = spec.get("pid")
        self.engine = types.SimpleNamespace(
            max_len=int(spec["max_len"]),
            vocab_size=int(spec["vocab_size"]),
            spec_k=int(spec["spec_k"]),
            kv_page_size=int(spec["kv_page_size"]),
            paged=bool(spec["paged"]),
            max_batch=int(spec["max_batch"]),
            prefill_chunk=int(spec.get("prefill_chunk", 0)),
            weights_fp=spec.get("weights_fp"),
        )
        self.scheduler = types.SimpleNamespace(
            max_queue=int(spec["max_queue"])
        )
        self._role = spec.get("role", "both")
        self._replica_index = 0
        self.slo = _RemoteSlo(self)

    # -- plumbing ---------------------------------------------------------

    def _get(self, path: str, timeout: float = 5.0) -> dict:
        with urllib.request.urlopen(
            f"{self.url}{path}", timeout=timeout
        ) as resp:
            return json.loads(resp.read())

    def _get_text(self, path: str, timeout: float = 5.0) -> str:
        with urllib.request.urlopen(
            f"{self.url}{path}", timeout=timeout
        ) as resp:
            return resp.read().decode("utf-8", errors="replace")

    def metrics_text(self, timeout: float = 5.0) -> str:
        """Raw Prometheus text from the worker's ``/metrics`` — what
        the router's federation scrape re-exports with replica labels."""
        return self._get_text("/metrics", timeout=timeout)

    def _post(self, path: str, body: dict, timeout: float = 10.0) -> dict:
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            f"{self.url}{path}", data=data,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def _open_stream(self, path: str, data: bytes, headers: dict,
                     timeout: float):
        """POST and return the live close-delimited NDJSON response."""
        req = urllib.request.Request(
            f"{self.url}{path}", data=data, headers=headers
        )
        return urllib.request.urlopen(req, timeout=timeout)

    @staticmethod
    def _read_line(resp) -> Optional[dict]:
        line = resp.readline()
        if not line:
            return None
        return json.loads(line)

    # -- health / role surface --------------------------------------------

    @property
    def role(self) -> str:
        return self._role

    @role.setter
    def role(self, value: str) -> None:
        self._role = value
        self._post("/admin/role", {"role": value})

    @property
    def replica_index(self) -> int:
        return self._replica_index

    @replica_index.setter
    def replica_index(self, value: int) -> None:
        self._replica_index = int(value)
        try:  # best effort — reindex runs right after a SIGKILL too
            self._post("/admin/replica_index", {"replica_index": value},
                       timeout=2.0)
        except Exception:
            pass

    def stderr_tail(self, max_bytes: int = 2048) -> Optional[str]:
        """Bounded tail of the worker's combined stdout+stderr log —
        the post-mortem a post-ready crash would otherwise lose (the
        readiness handshake only surfaces PRE-ready exits).  The
        autoscaler attaches it to the replace-dead flight event."""
        if not self.log_path:
            return None
        try:
            with open(self.log_path, "rb") as fp:
                fp.seek(0, os.SEEK_END)
                size = fp.tell()
                fp.seek(max(size - int(max_bytes), 0))
                return fp.read().decode("utf-8", errors="replace")
        except OSError:
            return None

    def health(self) -> dict:
        try:
            return self._get("/healthz", timeout=2.0)
        except urllib.error.HTTPError as e:  # 503 still carries it
            try:
                return json.loads(e.read())
            except Exception:
                return {"ok": False, "healthy": False, "closed": True,
                        "reason": f"healthz HTTP {e.code}"}
        except Exception as e:
            return {"ok": False, "healthy": False, "closed": True,
                    "reason": f"replica process unreachable: {e}"}

    # -- request path -----------------------------------------------------

    def _raise_refusal(self, first: Optional[dict]) -> None:
        """Map a first-line refusal onto the in-process exceptions."""
        if first is None:
            raise EngineUnhealthy(
                "serving engine unhealthy: replica closed the "
                "connection before the admission verdict"
            )
        status = first.get("status")
        err = first.get("error", status)
        if status == "shed":
            raise OverloadShed(err, retry_after=first.get("retry_after"))
        if status == "draining":
            raise AdmissionError(err)
        if status == "unhealthy":
            raise EngineUnhealthy(err)
        if status == "closed":
            raise RuntimeError(err)
        if status == "corrupt":
            raise MigrationCorrupt(err)
        if status == "weights_mismatch":
            raise WeightsMismatch(err)
        if status == "no_memory":
            raise AdmissionError(f"adoption refused (no_memory): {err}")
        raise RuntimeError(f"unexpected fleet reply: {first}")

    def _pump_stream(self, shadow: Request, resp) -> None:
        """Daemon-thread body: NDJSON lines -> the shadow request.  A
        ``migrated`` terminal leaves the shadow UNFINISHED — the export
        already rode an ``m`` line into its stream and the router's
        pump adopts it elsewhere.  Any transport failure is a
        retryable ``unhealthy`` finish (redistribute, don't surface)."""
        from ml_trainer_tpu.serving import transfer

        try:
            with resp:
                while True:
                    obj = self._read_line(resp)
                    if obj is None:
                        shadow.finish(
                            "error",
                            "serving engine unhealthy: replica "
                            f"'{self.name}' connection lost mid-stream",
                        )
                        return
                    if "t" in obj:
                        shadow.push_token(int(obj["t"]))
                        continue
                    if "m" in obj:
                        payload = base64.b64decode(obj["m"])
                        try:
                            export = transfer.from_bytes(payload)
                        except MigrationCorrupt as e:
                            shadow.finish(
                                "error",
                                "serving engine unhealthy: migration "
                                f"payload corrupt in transit from "
                                f"'{self.name}': {e}",
                            )
                            return
                        shadow._stream.put((_MIGRATE, export))
                        continue
                    done = obj.get("done")
                    if done is not None:
                        state = done.get("state")
                        if state == "migrated":
                            return  # adoption continues the stream
                        if done.get("retry_after") is not None:
                            shadow.retry_after = done["retry_after"]
                        shadow.finish(state, done.get("error"))
                        return
        except Exception as e:  # severed socket, timeout, bad line
            shadow.finish(
                "error",
                "serving engine unhealthy: replica "
                f"'{self.name}' stream failed mid-flight: {e}",
            )

    def _start_pump(self, shadow: Request, resp) -> None:
        threading.Thread(
            target=self._pump_stream, args=(shadow, resp), daemon=True,
            name=f"fleet-pump-{self.name}-{shadow.id}",
        ).start()

    def submit_request(self, shadow: Request) -> None:
        """``POST /v1/stream``: ship the request identity, read the
        synchronous admission verdict, then pump the token stream into
        the shadow from a daemon thread."""
        body = request_wire_meta(shadow)
        body["migrate"] = shadow.migration_sink is not None
        headers = {"Content-Type": "application/json"}
        if getattr(shadow, "trace_ctx", None):
            # The trace context also rides the wire meta; the header is
            # the RPC-level contract (api.py TRACE_HEADER) so even a
            # meta-stripping proxy keeps the request traceable.
            headers["X-Trace-Context"] = json.dumps(shadow.trace_ctx)
        try:
            resp = self._open_stream(
                "/v1/stream", json.dumps(body).encode(),
                headers,
                self._stream_timeout,
            )
            first = self._read_line(resp)
        except (OSError, ValueError) as e:
            raise EngineUnhealthy(
                "serving engine unhealthy: replica "
                f"'{self.name}' unreachable: {e}"
            )
        if first is None or first.get("status") != "accepted":
            with resp:
                self._raise_refusal(first)
        self._start_pump(shadow, resp)

    def adopt_payload(self, shadow: Request, payload: bytes) -> None:
        """``POST /v1/adopt``: the serialized ``KVSlotExport`` rides as
        the raw body (request identity in the ``X-Request-Meta``
        header); the receiving PROCESS verifies the CRC and replies a
        structured verdict mapped back onto the in-process adopt
        exceptions, so the router's fallback-candidate loop works
        unchanged.  On ``adopted`` the same connection becomes the
        continuation token stream."""
        meta = json.dumps(request_wire_meta(shadow))
        headers = {"Content-Type": "application/octet-stream",
                   "X-Request-Meta": meta}
        if getattr(shadow, "trace_ctx", None):
            headers["X-Trace-Context"] = json.dumps(shadow.trace_ctx)
        try:
            resp = self._open_stream(
                "/v1/adopt", payload,
                headers,
                self._stream_timeout,
            )
            first = self._read_line(resp)
        except (OSError, ValueError) as e:
            raise EngineUnhealthy(
                "serving engine unhealthy: replica "
                f"'{self.name}' unreachable for adoption: {e}"
            )
        status = (first or {}).get("status")
        if status == "adopted":
            self._start_pump(shadow, resp)
            return
        if status in ("error", "expired", "cancelled"):
            # Structured terminals the in-process path also surfaces by
            # finishing the request after a SUCCESSFUL adoption enqueue.
            with resp:
                state = "expired" if status == "expired" else "error"
                shadow.finish(state, first.get("error", status))
            return
        with resp:
            self._raise_refusal(first)

    def cancel(self, req: Request) -> None:
        req.cancel_requested = True
        try:
            self._post("/v1/cancel", {"id": int(req.id)}, timeout=5.0)
        except Exception:
            pass  # best effort — the replica may already be failing it

    # -- control surface --------------------------------------------------

    def evacuate(self, sink, timeout: float = 30.0) -> bool:
        """The exports ride each request's own open stream as ``m``
        lines (the router's pump adopts them), so the router-provided
        in-process ``sink`` is unused here."""
        del sink
        resp = self._post(
            "/admin/evacuate", {"timeout": timeout}, timeout=timeout + 10.0
        )
        return bool(resp.get("ok"))

    def set_degradation(self, level: int, config) -> None:
        import dataclasses

        cfg = (
            dataclasses.asdict(config)
            if dataclasses.is_dataclass(config) else dict(config or {})
        )
        try:
            self._post("/admin/degradation",
                       {"level": int(level), "config": cfg}, timeout=5.0)
        except Exception:
            pass  # ladder sweeps every replica; a dead one is fine

    def shed_queued(self, below_priority: int, retry_after: float,
                    cause: str = "overload") -> int:
        try:
            resp = self._post(
                "/admin/shed_queued",
                {"below_priority": int(below_priority),
                 "retry_after": float(retry_after), "cause": cause},
                timeout=5.0,
            )
            return int(resp.get("shed", 0))
        except Exception:
            return 0

    def _mark_unhealthy(self, reason: str) -> None:
        try:  # the process may already be SIGKILL'd — that's the point
            self._post("/admin/fail", {"reason": reason}, timeout=2.0)
        except Exception:
            pass

    def kill_process(self) -> None:
        """Real ``SIGKILL`` — the chaos/router ``replica_kill`` action.
        No cleanup runs in the replica; recovery is redistribution."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait(timeout=10.0)
        elif self.pid:
            try:
                os.kill(int(self.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def close(self) -> None:
        """Graceful shutdown: ask the process to exit, then reap it."""
        try:
            self._post("/admin/shutdown", {}, timeout=5.0)
        except Exception:
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10.0)


class Fleet:
    """Spawn-and-wire launcher for a multi-process replica fleet.

        fleet = Fleet(roles=["prefill", "decode", "decode"],
                      kv_page_size=16, prefill_chunk=32)
        fleet.start()
        router = fleet.make_router()   # owns the RemoteServers
        ...
        router.close(); fleet.stop()

    Worker processes share one on-disk XLA compile cache directory
    (``compile_cache_dir``), are pinned to CPU with a single device,
    and never inherit an active chaos plan — faults are the DRIVER's
    job, a worker must only ever die by real signal."""

    def __init__(self, roles: Sequence[str], *,
                 model_name: str = "gpt2_tiny", max_len: int = 256,
                 max_batch: int = 4, max_queue: int = 64,
                 kv_page_size: int = 16, kv_pages: int = 0,
                 seed: int = 0, prefill_chunk: int = 0,
                 prefix_cache: bool = True,
                 host: str = "127.0.0.1",
                 compile_cache_dir: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 spawn_timeout: float = 180.0,
                 stream_timeout: float = 600.0):
        self.roles = list(roles)
        self.model_name = model_name
        self.max_len = int(max_len)
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.kv_page_size = int(kv_page_size)
        self.kv_pages = int(kv_pages)
        self.seed = int(seed)
        self.prefill_chunk = int(prefill_chunk)
        self.prefix_cache = bool(prefix_cache)
        self.host = host
        self.spawn_timeout = float(spawn_timeout)
        self.stream_timeout = float(stream_timeout)
        self.compile_cache_dir = compile_cache_dir or tempfile.mkdtemp(
            prefix="fleet-xla-cache-"
        )
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="fleet-logs-")
        self.replicas: Dict[str, RemoteServer] = {}
        self._role_seq: Dict[str, int] = {}
        self._log = get_logger("ml_trainer_tpu.serving.fleet")

    # -- lifecycle --------------------------------------------------------

    def _next_name(self, role: str) -> str:
        n = self._role_seq.get(role, 0)
        self._role_seq[role] = n + 1
        return f"{role}{n}"

    def _worker_env(self) -> dict:
        env = dict(os.environ)
        # Chaos plans fire in the DRIVER (router) process only; a
        # worker inheriting one would double-fire every fault.
        env.pop("ML_TRAINER_TPU_FAULTS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["JAX_COMPILATION_CACHE_DIR"] = self.compile_cache_dir
        return env

    def spawn(self, name: str, role: str,
              ckpt: Optional[str] = None) -> RemoteServer:
        """Spawn one replica process and block until its HTTP front end
        answers ``/v1/spec`` (the compile-warm readiness gate).  With
        ``ckpt`` the worker loads its weights from that export
        (``model.msgpack`` path or dir) instead of the seed init — the
        deploy path (serving/deploy.py) spawns new-generation replicas
        this way."""
        port = _free_port()
        url = f"http://{self.host}:{port}"
        cmd = [
            sys.executable, "-m", "ml_trainer_tpu.serving.fleet",
            "--worker", "--name", name, "--role", role,
            "--host", self.host, "--port", str(port),
            "--model", self.model_name, "--max-len", str(self.max_len),
            "--max-batch", str(self.max_batch),
            "--max-queue", str(self.max_queue),
            "--kv-page-size", str(self.kv_page_size),
            "--kv-pages", str(self.kv_pages),
            "--seed", str(self.seed),
            "--prefill-chunk", str(self.prefill_chunk),
        ]
        if ckpt:
            cmd += ["--ckpt", ckpt]
        if not self.prefix_cache:
            cmd.append("--no-prefix-cache")
        log_path = os.path.join(self.log_dir, f"{name}.log")
        log_file = open(log_path, "w")
        env = self._worker_env()
        # Per-worker JSONL sink isolation (telemetry/export.py): a
        # shared ML_TRAINER_TPU_METRICS_JSONL path gains a `.{name}`
        # suffix in each worker, so N processes never interleave lines
        # into one file.
        env["ML_TRAINER_TPU_METRICS_WORKER"] = name
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=log_file, stderr=subprocess.STDOUT,
        )
        log_file.close()  # the child holds its own descriptor
        deadline = time.monotonic() + self.spawn_timeout
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker '{name}' exited rc={proc.returncode} "
                    f"before readiness; log: {log_path}"
                )
            try:
                remote = RemoteServer(
                    url, proc=proc, name=name,
                    stream_timeout=self.stream_timeout,
                    log_path=log_path,
                )
                self.replicas[name] = remote
                self._log.info(
                    "fleet_spawn", name=name, role=role, url=url,
                    pid=remote.pid, ckpt=ckpt,
                )
                return remote
            except Exception as e:
                last_err = e
                time.sleep(0.1)
        proc.kill()
        raise RuntimeError(
            f"fleet worker '{name}' not ready after "
            f"{self.spawn_timeout}s ({last_err}); log: {log_path}"
        )

    def start(self) -> "Fleet":
        for role in self.roles:
            self.spawn(self._next_name(role), role)
        return self

    def factory(self, role: str) -> RemoteServer:
        """Autoscaler ``server_factory``: every scale-up (and every
        replace-dead repair) spawns a REAL process."""
        return self.spawn(self._next_name(role), role)

    def deploy_factory(self, ckpt: str):
        """A ``server_factory`` bound to a checkpoint: new-generation
        replicas for ``Router.deploy`` load their weights from ``ckpt``
        (and share the fleet's on-disk compile cache, so a deploy is
        not a recompile storm)."""
        def spawn(role: str) -> RemoteServer:
            return self.spawn(self._next_name(role), role, ckpt=ckpt)

        return spawn

    def kill(self, name: str) -> None:
        """SIGKILL one replica process directly (chaos harness)."""
        self.replicas[name].kill_process()

    def stop(self) -> None:
        for remote in self.replicas.values():
            try:
                remote.close()
            except Exception:
                pass
        self.replicas.clear()

    def make_router(self, **router_kwargs):
        """Build a :class:`Router` over the spawned fleet.  The router
        owns the RemoteServers (``close`` shuts the processes down) and
        polls health over HTTP via ``replica_urls``."""
        from ml_trainer_tpu.serving.router import Router

        router_kwargs.setdefault("own_servers", True)
        router = Router(
            replicas=dict(self.replicas),
            replica_urls={n: r.url for n, r in self.replicas.items()},
            **router_kwargs,
        )
        # Router.deploy spawns new-generation workers through this
        # launcher's checkpoint-loading factory.
        router.fleet = self
        return router


# -- worker entry ---------------------------------------------------------


def _worker_main(argv: Optional[List[str]] = None) -> int:
    """``python -m ml_trainer_tpu.serving.fleet --worker ...`` — build
    the model deterministically from the seed, serve HTTP, block until
    killed or ``/admin/shutdown``."""
    import argparse

    parser = argparse.ArgumentParser(prog="ml_trainer_tpu.serving.fleet")
    parser.add_argument("--worker", action="store_true", required=True)
    parser.add_argument("--name", default="replica")
    parser.add_argument("--role", default="both")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--model", default="gpt2_tiny")
    parser.add_argument("--max-len", type=int, default=256)
    parser.add_argument("--max-batch", type=int, default=4)
    parser.add_argument("--max-queue", type=int, default=64)
    parser.add_argument("--kv-page-size", type=int, default=16)
    parser.add_argument("--kv-pages", type=int, default=0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--prefill-chunk", type=int, default=0)
    parser.add_argument("--no-prefix-cache", action="store_true")
    parser.add_argument("--ckpt", default=None,
                        help="load weights from this model export "
                        "(model.msgpack path or dir) instead of the "
                        "seed init — the deploy path")
    args = parser.parse_args(argv)

    import jax
    import numpy as np

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        try:  # shared on-disk compile cache (best effort on CPU)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
        except Exception:
            pass

    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving.api import Server
    from ml_trainer_tpu.telemetry import compile_watch

    compile_watch.install()
    model = get_model(args.model, max_len=args.max_len)
    if args.ckpt:
        from ml_trainer_tpu.checkpoint import load_model_variables

        variables = load_model_variables(args.ckpt)
    else:
        variables = model.init(
            {"params": jax.random.PRNGKey(args.seed)},
            np.zeros((1, 8), np.int32), train=False,
        )
    server = Server(
        model, variables, max_batch=args.max_batch,
        max_queue=args.max_queue, kv_page_size=args.kv_page_size,
        kv_pages=args.kv_pages, role=args.role,
        prefill_chunk=args.prefill_chunk,
        prefix_cache=not args.no_prefix_cache,
    )
    server.transport = "http"  # /admin/shutdown may os._exit this process
    server.name = args.name    # trace lanes / accept lines carry this
    host, port = server.serve_http(args.host, args.port)
    print(
        "FLEET_WORKER_READY "
        + json.dumps({
            "name": args.name, "url": f"http://{host}:{port}",
            "pid": os.getpid(), "role": args.role,
        }),
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(_worker_main())
