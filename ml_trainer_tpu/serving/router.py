"""Disaggregated prefill/decode serving: a router over N engine replicas.

At production traffic, prefill (compute-bound, bursty) and decode
(memory-bandwidth-bound, steady) fight for the same chips; the
Gemma-on-TPU serving study (PAPERS.md, arXiv 2605.25645) argues the
economics favor splitting them onto role-specialized replicas.  This
module is that split, simulated multi-replica on CPU (each replica is a
full :class:`~ml_trainer_tpu.serving.api.Server` with its own engine,
scheduler, worker thread and optional HTTP front end — the in-process
analog of the mp_worker cluster harness):

* **Roles.**  Every replica advertises ``role`` (``prefill`` /
  ``decode`` / ``both``) on its ``/healthz``.  In DISAGGREGATED mode a
  request prefills on a prefill replica — whose slots turn over in one
  prefill's time, so TTFT stops queueing behind other requests' decode
  residency — then its KV migrates at page granularity
  (serving/transfer.py) to a decode replica that carries the stream to
  completion.  In COLOCATED mode (every replica ``both``) the same
  router serves the same traffic with no migration, which is what makes
  ``bench.py --serve-disagg`` an equal-replica-count comparison.

* **Placement.**  Prefill placement is tenant-affinity-aware:
  consistent hashing (a vnode ring) on ``tenant + the prompt's first
  KV block``, so requests sharing a system prompt land on the same
  prefill replica and its radix prefix cache keeps its hit rate after
  the split.  Decode placement is least-loaded over live ``/healthz``
  data (``queue_depth``, ``active_slots``, ``kv_pages_free``), with
  SESSION STICKINESS: a ``session`` key pins a multi-turn stream to one
  decode replica until that replica dies.

* **Migration.**  The prefill replica emits the request's first token,
  exports the slot's refcounted pages + page-table row (bit-for-bit,
  trash-padded to a static shape so migration never mints compiles),
  releases the slot with the usual prefix-cache donation, and the
  router adopts the request into the decode replica — which scatters
  the pages in, re-donates the migrated blocks to ITS prefix cache, and
  continues the stream byte-identically (tests/test_router.py pins
  greedy and spec_k continuations against never-migrated runs).

* **Failure semantics.**  A health poller consumes every replica's
  ``/healthz``; a replica that dies (watchdog trip, engine-thread
  death, kill) fails its in-flight requests with structured errors,
  and the router REDISTRIBUTES them: each request resubmits on a
  surviving replica with its committed tokens as a resumable prefix —
  exactly the preemption-requeue resume, so redistributed streams stay
  byte-identical.  Requests that exhaust ``max_redistributes`` (and
  engine-side ``max_preemptions`` give-ups) surface as structured
  client errors; nothing ever hangs.

Telemetry rides the process registry: ``router_requests_total{role=,
replica=}``, ``router_kv_migrated_bytes_total``,
``router_replica_healthy{replica=}``, ``router_migrations_total``,
``router_redistributes_total``, plus per-replica SLO attainment
(``router_replica_slo_attainment{slo=,replica=}``) through each
replica's existing SloTracker, and the router's own request-level SLO
accounting on ``/slo``.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import queue as _queue
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ml_trainer_tpu.serving import transfer
from ml_trainer_tpu.serving.api import Server, TokenStream
from ml_trainer_tpu.serving.scheduler import (
    AdmissionError,
    EngineUnhealthy,
    Request,
    _DONE,
)
from ml_trainer_tpu.serving.slo import SloPolicy, SloTracker
from ml_trainer_tpu.utils.logging import get_logger

# Stream sentinel kind the migration sink pushes between tokens: the
# request's pump adopts the export into the decode replica when it
# drains this item (tokens are plain ints, _DONE is ("done", None)).
_MIGRATE = "__kv_migrate__"


class Replica:
    """One engine replica behind the router: the in-process ``Server``
    plus its routing state (role, last health payload, liveness)."""

    def __init__(self, name: str, server: Server,
                 url: Optional[str] = None):
        self.name = name
        self.server = server
        self.url = url
        self.role = server.role
        self.healthy = True
        self.last_health: dict = {}
        # Placements since the last health refresh: the health payload
        # is a quarter-second stale under burst arrivals, so without
        # this every tie lands on the same replica until the next poll.
        self.pending = 0

    def fetch_health(self, timeout: float = 2.0) -> dict:
        """The replica's ``/healthz`` payload — over HTTP when the
        replica exposes a front end (a 503 still carries the payload),
        else the in-process snapshot."""
        if self.url:
            try:
                with urllib.request.urlopen(
                    f"{self.url}/healthz", timeout=timeout
                ) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                try:
                    return json.loads(e.read())
                except Exception:
                    return {"ok": False, "healthy": False,
                            "reason": f"healthz HTTP {e.code}"}
            except Exception as e:
                return {"ok": False, "healthy": False,
                        "reason": f"healthz unreachable: {e}"}
        return self.server.health()

    def placeable(self) -> bool:
        return self.healthy

    def load_score(self) -> tuple:
        """Least-loaded ordering key from the last health payload:
        occupied slots + queued + pending adoptions first, freest KV
        pool as the tie-break, name for determinism."""
        h = self.last_health or {}
        depth = (
            int(h.get("active_slots") or 0)
            + int(h.get("queue_depth") or 0)
            + int(h.get("adoptions_pending") or 0)
            + self.pending
        )
        return (depth, -(int(h.get("kv_pages_free") or 0)), self.name)


class _HashRing:
    """Consistent hashing with virtual nodes (sha1): the affinity key
    maps to the first clockwise vnode whose replica is alive, so a
    replica loss only remaps its own arc."""

    def __init__(self, names: Sequence[str], vnodes: int = 64):
        self._points: List[Tuple[int, str]] = sorted(
            (self._hash(f"{name}#{i}".encode()), name)
            for name in names for i in range(vnodes)
        )

    @staticmethod
    def _hash(key: bytes) -> int:
        return int(hashlib.sha1(key).hexdigest()[:16], 16)

    def place(self, key: bytes, alive) -> Optional[str]:
        if not self._points:
            return None
        h = self._hash(key)
        start = bisect.bisect_right(self._points, (h, ""))
        n = len(self._points)
        for i in range(n):
            name = self._points[(start + i) % n][1]
            if name in alive:
                return name
        return None


class RouterMetrics:
    """Thread-safe router counters (published as ``router_*`` series)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total: Dict[Tuple[str, str], int] = {}
        self.migrations_total = 0
        self.kv_migrated_bytes_total = 0
        self.redistributes_total = 0
        self.errors_total = 0
        self.replica_healthy: Dict[str, int] = {}

    def record_request(self, replica: str, role: str) -> None:
        with self._lock:
            key = (role, replica)
            self.requests_total[key] = self.requests_total.get(key, 0) + 1

    def record_migration(self, nbytes: int) -> None:
        with self._lock:
            self.migrations_total += 1
            self.kv_migrated_bytes_total += int(nbytes)

    def record_redistribute(self) -> None:
        with self._lock:
            self.redistributes_total += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def set_replica_health(self, name: str, ok: bool) -> None:
        with self._lock:
            self.replica_healthy[name] = int(ok)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_total": {
                    f"{role}/{rep}": n
                    for (role, rep), n in sorted(self.requests_total.items())
                },
                "migrations_total": self.migrations_total,
                "kv_migrated_bytes_total": self.kv_migrated_bytes_total,
                "redistributes_total": self.redistributes_total,
                "errors_total": self.errors_total,
                "replica_healthy": dict(sorted(
                    self.replica_healthy.items()
                )),
            }


class Router:
    """The multi-replica front end: role-aware placement, KV migration,
    session stickiness, health polling, drain-and-redistribute.  Use as
    a context manager; ``Router.build`` constructs the replica fleet
    in-process."""

    def __init__(self, replicas: Dict[str, Server], *,
                 replica_urls: Optional[Dict[str, str]] = None,
                 max_redistributes: int = 8,
                 health_interval: float = 0.25,
                 admission_retry_s: float = 10.0,
                 max_inflight: Optional[int] = None,
                 slo: Optional[SloPolicy] = None,
                 slo_timelines: int = 256,
                 own_servers: bool = False):
        if not replicas:
            raise ValueError("router needs at least one replica")
        urls = replica_urls or {}
        self._replicas: Dict[str, Replica] = {
            name: Replica(name, srv, urls.get(name))
            for name, srv in sorted(replicas.items())
        }
        roles = {r.role for r in self._replicas.values()}
        self.mode = "colocated" if roles == {"both"} else "disagg"
        engines = [r.server.engine for r in self._replicas.values()]
        e0 = engines[0]
        for e in engines[1:]:
            if (e.max_len != e0.max_len
                    or e.vocab_size != e0.vocab_size):
                raise ValueError(
                    "replicas must share model geometry: got max_len "
                    f"{e.max_len} vs {e0.max_len}, vocab {e.vocab_size} "
                    f"vs {e0.vocab_size}"
                )
        if self.mode == "disagg":
            for name, rep in self._replicas.items():
                e = rep.server.engine
                if not e.paged:
                    raise ValueError(
                        f"disaggregated mode needs paged engines "
                        f"(kv_page_size > 0): replica '{name}' is "
                        "contiguous — pages are the migration unit"
                    )
                if e.kv_page_size != engines[0].kv_page_size:
                    raise ValueError(
                        "replicas must share kv_page_size for migration"
                    )
        self.max_len = e0.max_len
        self.vocab_size = e0.vocab_size
        self._spec_slack = max(e.spec_k for e in engines)
        self._affinity_block = max(
            e0.kv_page_size, 1
        ) if e0.paged else 16
        self.max_redistributes = int(max_redistributes)
        self.admission_retry_s = float(admission_retry_s)
        self.max_inflight = (
            int(max_inflight) if max_inflight is not None
            else sum(
                r.server.scheduler.max_queue + r.server.engine.max_batch
                for r in self._replicas.values()
            )
        )
        self._own_servers = own_servers
        self.metrics = RouterMetrics()
        self.slo = SloTracker(policy=slo, keep_timelines=slo_timelines)
        self._log = get_logger("ml_trainer_tpu.serving.router")
        self._lock = threading.Lock()
        self._sessions: Dict[str, str] = {}
        self._inflight = 0
        self._stopping = False
        self._stop_event = threading.Event()
        self._httpd = None
        self._http_thread = None
        prefill_names = [
            n for n, r in self._replicas.items()
            if r.role in ("prefill", "both")
        ] or list(self._replicas)
        self._ring = _HashRing(prefill_names)
        for rep in self._replicas.values():
            rep.last_health = rep.fetch_health()
            self.metrics.set_replica_health(rep.name, True)
        self._health_interval = float(health_interval)
        self._poller = threading.Thread(
            target=self._poll_health, daemon=True, name="router-health"
        )
        self._poller.start()

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, model, variables: dict, roles: Sequence[str],
              max_batch: int = 4, kv_page_size: int = 16,
              router_kwargs: Optional[dict] = None,
              **server_kwargs) -> "Router":
        """Build an in-process replica fleet: one ``Server`` per entry
        of ``roles`` (named ``prefill0``/``decode0``/``rep0``...), all
        sharing ``model``/``variables`` (and therefore the process
        compile cache), plus the router in front.  The router OWNS the
        servers — ``close()`` closes them."""
        counts: Dict[str, int] = {}
        replicas: Dict[str, Server] = {}
        for role in roles:
            stem = {"prefill": "prefill", "decode": "decode"}.get(
                role, "rep"
            )
            i = counts.get(stem, 0)
            counts[stem] = i + 1
            replicas[f"{stem}{i}"] = Server(
                model, variables, max_batch=max_batch,
                kv_page_size=kv_page_size, role=role, **server_kwargs
            )
        return cls(replicas, own_servers=True, **(router_kwargs or {}))

    def replica(self, name: str) -> Replica:
        return self._replicas[name]

    @property
    def replicas(self) -> Dict[str, Replica]:
        return dict(self._replicas)

    # -- client surface ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, rng=None,
               eos_token_id: Optional[int] = None,
               deadline: Optional[float] = None,
               tenant: str = "default", priority: int = 0,
               session: Optional[str] = None) -> TokenStream:
        """Route one request (thread-safe).  The returned stream is the
        same surface ``Server.submit`` gives — tokens arrive as the
        serving replicas produce them, across migration and
        redistribution transparently.  ``session`` pins the request's
        decode to a sticky replica for multi-turn streams."""
        if self._stopping:
            raise RuntimeError("router is closed")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens + self._spec_slack > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + new tokens ({max_new_tokens}) "
                f"exceeds the fleet's max_len ({self.max_len})"
            )
        if eos_token_id is not None and not (
            0 <= eos_token_id < self.vocab_size
        ):
            raise ValueError(
                f"eos_token_id must be in [0, {self.vocab_size}), got "
                f"{eos_token_id}"
            )
        if not isinstance(tenant, str) or not tenant:
            raise ValueError(
                f"tenant must be a non-empty string, got {tenant!r}"
            )
        with self._lock:
            if self._inflight >= self.max_inflight:
                raise AdmissionError(
                    f"router at its in-flight watermark "
                    f"({self.max_inflight}); request rejected"
                )
            self._inflight += 1
        creq = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), rng=rng,
            eos_token_id=eos_token_id, deadline=deadline,
            tenant=tenant, priority=int(priority),
        )
        creq.observer = self.slo.observe
        self.slo.track(creq)
        threading.Thread(
            target=self._run_request, args=(creq, session), daemon=True,
            name=f"router-req-{creq.id}",
        ).start()
        return TokenStream(creq, prompt)

    def complete(self, prompt, max_new_tokens: int,
                 timeout: Optional[float] = None, **kwargs) -> np.ndarray:
        """Blocking one-shot through the router."""
        return self.submit(prompt, max_new_tokens, **kwargs).result(
            timeout=timeout
        )

    def kill_replica(self, name: str) -> None:
        """Simulate a replica death (tests/chaos): the replica fails its
        in-flight work with structured errors — which the router
        redistributes — and leaves the placement pool."""
        rep = self._replicas[name]
        rep.healthy = False
        self.metrics.set_replica_health(name, False)
        rep.server._mark_unhealthy(f"replica '{name}' killed")

    def health(self) -> dict:
        """The router ``/healthz`` payload: aggregate liveness plus
        every replica's last health snapshot."""
        reps = {
            name: {
                "healthy": rep.healthy,
                "role": rep.role,
                **{
                    k: rep.last_health.get(k)
                    for k in ("active_slots", "queue_depth",
                              "kv_pages_free", "adoptions_pending")
                },
            }
            for name, rep in self._replicas.items()
        }
        n_alive = sum(1 for r in self._replicas.values() if r.healthy)
        with self._lock:
            inflight = self._inflight
        return {
            "ok": n_alive > 0 and not self._stopping,
            "mode": self.mode,
            "replicas_alive": n_alive,
            "replicas_total": len(self._replicas),
            "inflight": inflight,
            "sessions": len(self._sessions),
            "replicas": reps,
        }

    def snapshot(self) -> dict:
        """Router metrics + health in one JSON-safe dict (the bench
        artifact's router section)."""
        snap = self.metrics.snapshot()
        snap["mode"] = self.mode
        with self._lock:
            snap["inflight"] = self._inflight
            snap["sessions"] = len(self._sessions)
        return snap

    def close(self) -> None:
        self._stopping = True
        self._stop_event.set()
        if self._own_servers:
            for rep in self._replicas.values():
                rep.server.close()
        self._poller.join(timeout=10.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- placement --------------------------------------------------------

    def _alive(self) -> Dict[str, Replica]:
        return {
            n: r for n, r in self._replicas.items() if r.placeable()
        }

    def _affinity_key(self, tenant: str, prompt: np.ndarray) -> bytes:
        block = np.asarray(
            prompt[: self._affinity_block], np.int32
        ).tobytes()
        return tenant.encode() + b"|" + block

    def _place(self, creq: Request,
               session: Optional[str]) -> Tuple[Replica, Replica]:
        """(prefill replica, decode replica) for this attempt, from live
        health.  Raises ``EngineUnhealthy`` when nothing is placeable."""
        alive = self._alive()
        if not alive:
            raise EngineUnhealthy("no healthy replica available")
        key = self._affinity_key(creq.tenant, creq.prompt)
        if self.mode == "colocated":
            name = self._ring.place(key, alive) or sorted(alive)[0]
            rep = alive[name]
            return rep, rep
        prefill_pool = {
            n: r for n, r in alive.items()
            if r.role in ("prefill", "both")
        } or alive  # degraded: every engine CAN prefill
        decode_pool = {
            n: r for n, r in alive.items()
            if r.role in ("decode", "both")
        } or alive
        name = self._ring.place(key, prefill_pool) or sorted(prefill_pool)[0]
        prefill = prefill_pool[name]
        decode = None
        if session:
            with self._lock:
                sticky = self._sessions.get(session)
            if sticky in decode_pool:
                decode = decode_pool[sticky]
        if decode is None:
            decode = min(decode_pool.values(), key=Replica.load_score)
            if session:
                with self._lock:
                    self._sessions[session] = decode.name
        decode.pending += 1
        return prefill, decode

    def _decode_candidates(self) -> List[Replica]:
        alive = self._alive()
        pool = [
            r for r in alive.values() if r.role in ("decode", "both")
        ] or list(alive.values())
        return sorted(pool, key=Replica.load_score)

    # -- the per-request state machine ------------------------------------

    def _run_request(self, creq: Request, session: Optional[str]) -> None:
        try:
            self._serve(creq, session)
        except Exception as e:  # noqa: BLE001 — never hang a client
            if creq.state in ("queued", "active"):
                self.metrics.record_error()
                creq.finish(
                    "error", f"router failure: {type(e).__name__}: {e}"
                )
        finally:
            with self._lock:
                self._inflight -= 1

    def _remaining_deadline(self, creq: Request) -> Optional[float]:
        if creq.deadline is None:
            return None
        return creq.deadline - (time.monotonic() - creq.submitted_at)

    def _shadow(self, creq: Request, committed: List[int],
                deadline: Optional[float]) -> Request:
        """The per-attempt replica-local request: same prompt and
        sampling state, committed tokens preloaded (resume prefix), the
        remaining deadline budget, and the cumulative preemption count
        so engine give-ups stay structured across replicas."""
        shadow = Request(
            prompt=creq.prompt, max_new_tokens=creq.max_new_tokens,
            temperature=creq.temperature, rng=creq.rng,
            eos_token_id=creq.eos_token_id, deadline=deadline,
            tenant=creq.tenant, priority=creq.priority,
        )
        shadow.tokens = [int(t) for t in committed]
        shadow.preemptions = creq.preemptions
        return shadow

    def _serve(self, creq: Request, session: Optional[str]) -> None:
        redistributes = 0
        while True:
            if self._stopping:
                creq.finish("error", "router is closed")
                return
            deadline = self._remaining_deadline(creq)
            if deadline is not None and deadline <= 0:
                creq.finish(
                    "expired",
                    f"deadline ({creq.deadline}s) passed while routing",
                )
                return
            # Resume from what the CLIENT received, not what the shadow
            # recorded: a dying replica's last decode step can append a
            # token to the shadow after its stream was failed, and a
            # token the pump never forwarded must be recomputed (it is —
            # deterministically), never skipped.
            shadow = self._shadow(creq, list(creq.tokens), deadline)
            placed = self._submit_attempt(creq, shadow, session)
            if placed is None:
                return  # _submit_attempt finished creq with the reason
            decode_rep = placed
            outcome = self._pump(creq, shadow, decode_rep)
            if outcome == "done":
                creq.preemptions = shadow.preemptions
                creq.finish("done")
                return
            if outcome == "expired":
                creq.finish("expired", shadow.error)
                return
            if outcome == "retry":
                redistributes += 1
                self.metrics.record_redistribute()
                creq.preemptions = shadow.preemptions + 1
                creq.mark(
                    "redistributed", attempt=redistributes,
                    committed_tokens=len(creq.tokens), error=shadow.error,
                )
                if redistributes > self.max_redistributes:
                    self.metrics.record_error()
                    creq.finish(
                        "error",
                        f"request {creq.id} (tenant '{creq.tenant}') "
                        f"redistributed {redistributes}x after replica "
                        f"failures; giving up after max_redistributes="
                        f"{self.max_redistributes} (last: {shadow.error})",
                    )
                    return
                continue
            self.metrics.record_error()
            creq.finish("error", shadow.error or "replica error")
            return

    def _submit_attempt(self, creq: Request, shadow: Request,
                        session: Optional[str]) -> Optional[Replica]:
        """Place + submit one attempt.  Returns the decode replica on
        success, or None after finishing ``creq`` with a structured
        error (placement/admission exhausted)."""
        give_up_at = time.monotonic() + self.admission_retry_s
        last_err = "no healthy replica available"
        while not self._stopping:
            try:
                prefill_rep, decode_rep = self._place(creq, session)
            except EngineUnhealthy as e:
                last_err = str(e)
                if time.monotonic() > give_up_at:
                    break
                self._stop_event.wait(0.05)
                continue
            disagg = prefill_rep is not decode_rep
            shadow.migration_sink = (
                (lambda r, exp: r._stream.put((_MIGRATE, exp)))
                if disagg else None
            )
            try:
                prefill_rep.server.submit_request(shadow)
            except AdmissionError as e:
                last_err = str(e)
                if time.monotonic() > give_up_at:
                    break
                self._stop_event.wait(0.02)
                continue
            except (EngineUnhealthy, RuntimeError) as e:
                # The poller will confirm, but don't wait for it.
                last_err = str(e)
                prefill_rep.healthy = False
                self.metrics.set_replica_health(prefill_rep.name, False)
                if time.monotonic() > give_up_at:
                    break
                continue
            creq.mark(
                "routed", prefill=prefill_rep.name,
                decode=decode_rep.name, disagg=disagg,
            )
            self.metrics.record_request(
                prefill_rep.name, "prefill" if disagg else "colocated"
            )
            return decode_rep
        self.metrics.record_error()
        creq.finish(
            "error",
            f"router could not place request {creq.id} (tenant "
            f"'{creq.tenant}'): {last_err}",
        )
        return None

    def _pump(self, creq: Request, shadow: Request,
              decode_rep: Replica) -> str:
        """Forward the shadow's stream to the client, adopting the KV
        export into the decode replica when it arrives.  Returns
        ``done`` / ``expired`` / ``retry`` (replica failure —
        redistribute) / ``error`` (structured terminal)."""
        while True:
            try:
                item = shadow._stream.get(timeout=0.5)
            except _queue.Empty:
                if self._stopping:
                    shadow.error = shadow.error or "router is closed"
                    return "error"
                continue
            if item == _DONE:
                if shadow.state == "done":
                    return "done"
                if shadow.state == "expired":
                    return "expired"
                if self._stopping or not self._retryable(shadow.error):
                    return "error"
                return "retry"
            if isinstance(item, tuple) and item[0] == _MIGRATE:
                if not self._adopt(creq, shadow, decode_rep, item[1]):
                    return "retry"
                continue
            creq.push_token(int(item))

    def _adopt(self, creq: Request, shadow: Request,
               decode_rep: Replica, export) -> bool:
        """Hand the exported KV to a decode replica — the placed one
        first, any healthy decode candidate as fallback.  The payload
        round-trips through the serialized form so the migration is
        transport-shaped and metered in real bytes."""
        payload = transfer.to_bytes(export)
        export = transfer.from_bytes(payload)
        candidates = [decode_rep] + [
            r for r in self._decode_candidates() if r is not decode_rep
        ]
        for rep in candidates:
            if not rep.placeable():
                continue
            try:
                rep.server.adopt(shadow, export)
            except AdmissionError:
                continue
            except (EngineUnhealthy, RuntimeError):
                rep.healthy = False
                self.metrics.set_replica_health(rep.name, False)
                continue
            self.metrics.record_migration(len(payload))
            self.metrics.record_request(rep.name, "decode")
            creq.mark(
                "kv_migrated", to=rep.name, kv_bytes=len(payload),
                pages=export.n_pages,
            )
            return True
        shadow.error = (
            "serving engine unhealthy: no decode replica could adopt "
            "the migrated KV"
        )
        return False

    @staticmethod
    def _retryable(err: Optional[str]) -> bool:
        """Replica-level failures redistribute; the engine's structured
        give-ups (max_preemptions) and unknown errors surface to the
        client as-is."""
        if not err:
            return False
        if "max_preemptions" in err:
            return False
        return any(
            needle in err
            for needle in ("unhealthy", "server closed", "wedged",
                           "engine thread died", "killed")
        )

    # -- health polling ---------------------------------------------------

    def _poll_health(self) -> None:
        while not self._stopping:
            for rep in self._replicas.values():
                payload = rep.fetch_health()
                rep.last_health = payload
                rep.pending = 0
                ok = (
                    bool(payload.get("healthy"))
                    and not payload.get("draining")
                    and not payload.get("closed")
                )
                if rep.healthy and not ok:
                    self._log.error(
                        "router_replica_unhealthy", replica=rep.name,
                        reason=payload.get("reason"),
                    )
                rep.healthy = ok
                self.metrics.set_replica_health(rep.name, ok)
            self._stop_event.wait(self._health_interval)

    # -- telemetry --------------------------------------------------------

    def publish(self, registry=None) -> dict:
        """Mirror the router counters into the telemetry registry (and
        return the snapshot): ``router_requests_total{role=,replica=}``,
        ``router_kv_migrated_bytes_total``,
        ``router_replica_healthy{replica=}``, redistribution/migration
        totals, the router-level SLO attainment, and each replica's
        attainment re-labeled by replica through its existing
        SloTracker."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        snap = self.metrics.snapshot()
        req = r.gauge(
            "router_requests_total",
            "requests placed by the router, by role and replica",
            labelnames=("role", "replica"),
        )
        for key, n in snap["requests_total"].items():
            role, replica = key.split("/", 1)
            req.labels(role=role, replica=replica).set(float(n))
        r.gauge(
            "router_kv_migrated_bytes_total",
            "serialized KV payload bytes migrated prefill -> decode",
        ).set(float(snap["kv_migrated_bytes_total"]))
        r.gauge(
            "router_migrations_total",
            "KV migrations adopted by decode replicas",
        ).set(float(snap["migrations_total"]))
        r.gauge(
            "router_redistributes_total",
            "in-flight requests redistributed off a failed replica",
        ).set(float(snap["redistributes_total"]))
        healthy = r.gauge(
            "router_replica_healthy",
            "1 while the replica is placeable, 0 once it left the pool",
            labelnames=("replica",),
        )
        for name, ok in snap["replica_healthy"].items():
            healthy.labels(replica=name).set(float(ok))
        att = r.gauge(
            "router_replica_slo_attainment",
            "per-replica SLO attainment (each replica's own SloTracker)",
            labelnames=("slo", "replica"),
        )
        for name, rep in self._replicas.items():
            rep_snap = rep.server.slo.snapshot()
            for k in ("ttft", "tpot"):
                att.labels(slo=k, replica=name).set(
                    rep_snap["attainment"][k]
                )
        self.slo.publish(r)
        return snap

    # -- HTTP front end ---------------------------------------------------

    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """The router's stdlib HTTP front end (same contract as
        ``Server.serve_http``): POST ``/v1/generate`` (plus an optional
        ``"session"`` key for stickiness), GET ``/healthz`` /
        ``/metrics`` / ``/metrics.json`` / ``/slo``."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ml_trainer_tpu.serving.scheduler import DeadlineExceeded

        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: we have metrics
                pass

            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    payload = router.health()
                    self._send(200 if payload["ok"] else 503, payload)
                elif self.path == "/metrics":
                    from ml_trainer_tpu.telemetry.registry import (
                        default_registry,
                    )

                    registry = default_registry()
                    router.publish(registry)
                    body = registry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics.json":
                    self._send(200, router.snapshot())
                elif self.path == "/slo":
                    self._send(200, router.slo.snapshot())
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/generate":
                    self._send(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    session = body.get("session")
                    out = router.complete(
                        np.asarray(body["prompt"], np.int32),
                        int(body.get("max_new_tokens", 16)),
                        temperature=float(body.get("temperature", 0.0)),
                        rng=body.get("seed"),
                        eos_token_id=body.get("eos_token_id"),
                        deadline=body.get("deadline"),
                        tenant=str(body.get("tenant", "default")),
                        priority=int(body.get("priority", 0)),
                        session=str(session) if session else None,
                    )
                    self._send(200, {"tokens": [int(t) for t in out]})
                except AdmissionError as e:
                    self._send(429, {"error": str(e)})
                except EngineUnhealthy as e:
                    self._send(503, {"error": str(e)})
                except (DeadlineExceeded, TimeoutError) as e:
                    self._send(504, {"error": str(e)})
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http",
        )
        self._http_thread.start()
        return self._httpd.server_address
