"""Disaggregated prefill/decode serving: a router over N engine replicas.

At production traffic, prefill (compute-bound, bursty) and decode
(memory-bandwidth-bound, steady) fight for the same chips; the
Gemma-on-TPU serving study (PAPERS.md, arXiv 2605.25645) argues the
economics favor splitting them onto role-specialized replicas.  This
module is that split, simulated multi-replica on CPU (each replica is a
full :class:`~ml_trainer_tpu.serving.api.Server` with its own engine,
scheduler, worker thread and optional HTTP front end — the in-process
analog of the mp_worker cluster harness):

* **Roles.**  Every replica advertises ``role`` (``prefill`` /
  ``decode`` / ``both``) on its ``/healthz``.  In DISAGGREGATED mode a
  request prefills on a prefill replica — whose slots turn over in one
  prefill's time, so TTFT stops queueing behind other requests' decode
  residency — then its KV migrates at page granularity
  (serving/transfer.py) to a decode replica that carries the stream to
  completion.  In COLOCATED mode (every replica ``both``) the same
  router serves the same traffic with no migration, which is what makes
  ``bench.py --serve-disagg`` an equal-replica-count comparison.

* **Placement.**  Prefill placement is tenant-affinity-aware:
  consistent hashing (a vnode ring) on ``tenant + the prompt's first
  KV block``, so requests sharing a system prompt land on the same
  prefill replica and its radix prefix cache keeps its hit rate after
  the split.  Decode placement is least-loaded over live ``/healthz``
  data (``queue_depth``, ``active_slots``, ``kv_pages_free``), with
  SESSION STICKINESS: a ``session`` key pins a multi-turn stream to one
  decode replica until that replica dies.

* **Migration.**  The prefill replica emits the request's first token,
  exports the slot's refcounted pages + page-table row (bit-for-bit,
  trash-padded to a static shape so migration never mints compiles),
  releases the slot with the usual prefix-cache donation, and the
  router adopts the request into the decode replica — which scatters
  the pages in, re-donates the migrated blocks to ITS prefix cache, and
  continues the stream byte-identically (tests/test_router.py pins
  greedy and spec_k continuations against never-migrated runs).

* **Failure semantics.**  A health poller consumes every replica's
  ``/healthz``; a replica that dies (watchdog trip, engine-thread
  death, kill) fails its in-flight requests with structured errors,
  and the router REDISTRIBUTES them: each request resubmits on a
  surviving replica with its committed tokens as a resumable prefix —
  exactly the preemption-requeue resume, so redistributed streams stay
  byte-identical.  Requests that exhaust ``max_redistributes`` (and
  engine-side ``max_preemptions`` give-ups) surface as structured
  client errors; nothing ever hangs.

Telemetry rides the process registry: ``router_requests_total{role=,
replica=}``, ``router_kv_migrated_bytes_total``,
``router_replica_healthy{replica=}``, ``router_migrations_total``,
``router_redistributes_total``, plus per-replica SLO attainment
(``router_replica_slo_attainment{slo=,replica=}``) through each
replica's existing SloTracker, and the router's own request-level SLO
accounting on ``/slo``.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import os
import queue as _queue
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ml_trainer_tpu.serving import transfer
from ml_trainer_tpu.serving.api import (
    Server,
    TokenStream,
    _trace_ctx_header,
)
from ml_trainer_tpu.serving.overload import (
    CircuitBreaker,
    DegradationConfig,
    DegradationLadder,
    OverloadShed,
    RollingQuantile,
)
from ml_trainer_tpu.serving.scheduler import (
    AdmissionError,
    EngineUnhealthy,
    Request,
    _DONE,
)
from ml_trainer_tpu.serving.slo import SloPolicy, SloTracker
from ml_trainer_tpu.serving.transfer import MigrationCorrupt
from ml_trainer_tpu.telemetry import federation, spans
from ml_trainer_tpu.telemetry.alerts import AlertEngine, AlertRule
from ml_trainer_tpu.telemetry.flight import get_recorder
from ml_trainer_tpu.telemetry.watchtower import (
    TimeSeriesStore,
    render_dashboard,
)
from ml_trainer_tpu.utils.logging import get_logger

# Stream sentinel kind the migration sink pushes between tokens: the
# request's pump adopts the export into the decode replica when it
# drains this item (tokens are plain ints, _DONE is ("done", None)).
_MIGRATE = "__kv_migrate__"

# Incident bundles (save_incident_bundle) land under this directory
# when no explicit ``incident_dir`` was configured; the flight-dump
# env var is a separate knob on purpose — a bundle COLLECTS flight
# dumps, it is not one.
INCIDENT_DIR_ENV = "ML_TRAINER_TPU_INCIDENT_DIR"


class Replica:
    """One engine replica behind the router: the in-process ``Server``
    plus its routing state (role, last health payload, liveness)."""

    def __init__(self, name: str, server: Server,
                 url: Optional[str] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 generation: int = 0):
        self.name = name
        self.server = server
        self.url = url
        self.role = server.role
        # Deploy generation (serving/deploy.py): which weights wave this
        # replica belongs to.  Placement never mixes generations within
        # one stream — KV is not portable across weights — and the
        # canary traffic split selects the pool by generation.
        self.generation = int(generation)
        self.weights_fp = getattr(
            getattr(server, "engine", None), "weights_fp", None
        )
        self.healthy = True
        self.last_health: dict = {}
        # Placements since the last health refresh: the health payload
        # is a quarter-second stale under burst arrivals, so without
        # this every tie lands on the same replica until the next poll.
        self.pending = 0
        # Client-path hardening (serving/overload.py): the per-replica
        # circuit breaker (K consecutive failures open it — the router
        # stops placing here without waiting for the poller), the
        # consecutive-failed-poll counter behind flap damping, and the
        # drain latch a scale-down/role-flip sets while it empties.
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.fail_polls = 0
        self.removing = False
        # Fleet observability plane: the replica's latest raw /metrics
        # exposition (the federation re-exports it with replica labels),
        # when it was scraped, and the per-process clock estimates the
        # trace merge aligns lanes with (telemetry/federation.py):
        # the exact monotonic-epoch shift and the NTP-style handshake
        # estimate (min-rtt filtered across health polls).
        self.metrics_text: Optional[str] = None
        self.metrics_scraped_at = 0.0
        self.epoch_shift_us: Optional[float] = None
        self.ntp_shift_us: Optional[float] = None
        self.ntp_rtt_us: Optional[float] = None

    def _note_clock(self, payload: dict, t0_us: float,
                    t1_us: float) -> None:
        """Clock handshake piggybacked on a health fetch: ``payload``
        carries the worker's trace-clock "now" and monotonic epoch
        (api.py health() via spans.clock_payload()); ``t0/t1`` bracket
        the HTTP round-trip on the ROUTER's trace clock."""
        worker_now = payload.get("trace_now_us")
        if worker_now is None:
            return
        rtt = t1_us - t0_us
        # NTP-style: the worker's reading maps to the bracket midpoint,
        # error <= rtt/2.  Keep the tightest bracket seen (min-rtt
        # filter) — a scheduling hiccup must not loosen the estimate.
        if self.ntp_rtt_us is None or rtt <= self.ntp_rtt_us:
            self.ntp_shift_us = (t0_us + t1_us) / 2.0 - float(worker_now)
            self.ntp_rtt_us = rtt
        mono_epoch = payload.get("mono_epoch")
        if mono_epoch is not None:
            # Exact when time.monotonic() is system-wide (CLOCK_MONOTONIC
            # on Linux): worker ts + this = ts on the router's clock.
            self.epoch_shift_us = (
                float(mono_epoch) - spans._MONO_EPOCH
            ) * 1e6

    def fetch_health(self, timeout: float = 2.0) -> dict:
        """The replica's ``/healthz`` payload — over HTTP when the
        replica exposes a front end (a 503 still carries the payload),
        else the in-process snapshot."""
        if self.url:
            t0 = spans._now_us()
            try:
                with urllib.request.urlopen(
                    f"{self.url}/healthz", timeout=timeout
                ) as resp:
                    payload = json.loads(resp.read())
                self._note_clock(payload, t0, spans._now_us())
                return payload
            except urllib.error.HTTPError as e:
                try:
                    payload = json.loads(e.read())
                    self._note_clock(payload, t0, spans._now_us())
                    return payload
                except Exception:
                    return {"ok": False, "healthy": False,
                            "reason": f"healthz HTTP {e.code}"}
            except Exception as e:
                return {"ok": False, "healthy": False,
                        "reason": f"healthz unreachable: {e}"}
        return self.server.health()

    def fetch_metrics_text(self, timeout: float = 2.0) -> Optional[str]:
        """Raw ``/metrics`` exposition over HTTP; None for in-process
        replicas (they share the router's registry already — federating
        them would double every series).  Raises on an unreachable
        process — the poller turns that into a scrape-error counter."""
        if not self.url:
            return None
        with urllib.request.urlopen(
            f"{self.url}/metrics", timeout=timeout
        ) as resp:
            return resp.read().decode("utf-8", errors="replace")

    def fetch_trace(self, timeout: float = 5.0) -> Optional[dict]:
        """The replica's ``GET /trace`` payload (span buffer + clock
        identity); None for in-process replicas (their spans are
        already in the router's own buffer)."""
        if not self.url:
            return None
        with urllib.request.urlopen(
            f"{self.url}/trace", timeout=timeout
        ) as resp:
            return json.loads(resp.read())

    def fetch_flight(self, timeout: float = 5.0) -> Optional[dict]:
        """The replica's live flight-recorder payload (``GET /flight``);
        None for in-process replicas (one process, one recorder — the
        router's own dump already has it)."""
        if not self.url:
            return None
        with urllib.request.urlopen(
            f"{self.url}/flight", timeout=timeout
        ) as resp:
            return json.loads(resp.read())

    def placeable(self) -> bool:
        """In the placement pool at all: alive, not draining for a
        scale-down/role-flip, and the breaker is not OPEN.  The
        half-open single-probe admission is enforced separately
        (``try_place`` consumes the probe slot)."""
        from ml_trainer_tpu.serving import overload

        return (
            self.healthy and not self.removing
            and self.breaker.state != overload.OPEN
        )

    def try_place(self) -> bool:
        """May a request land here RIGHT NOW — placeable, and if the
        breaker is half-open, this caller won the single probe slot."""
        return self.placeable() and self.breaker.allow()

    def load_score(self) -> tuple:
        """Least-loaded ordering key from the last health payload:
        occupied slots + queued + pending adoptions first, freest KV
        pool as the tie-break, name for determinism."""
        h = self.last_health or {}
        depth = (
            int(h.get("active_slots") or 0)
            + int(h.get("queue_depth") or 0)
            + int(h.get("adoptions_pending") or 0)
            + self.pending
        )
        return (depth, -(int(h.get("kv_pages_free") or 0)), self.name)


class _HashRing:
    """Consistent hashing with virtual nodes (sha1): the affinity key
    maps to the first clockwise vnode whose replica is alive, so a
    replica loss only remaps its own arc."""

    def __init__(self, names: Sequence[str], vnodes: int = 64):
        self._points: List[Tuple[int, str]] = sorted(
            (self._hash(f"{name}#{i}".encode()), name)
            for name in names for i in range(vnodes)
        )

    @staticmethod
    def _hash(key: bytes) -> int:
        return int(hashlib.sha1(key).hexdigest()[:16], 16)

    def place(self, key: bytes, alive) -> Optional[str]:
        if not self._points:
            return None
        h = self._hash(key)
        start = bisect.bisect_right(self._points, (h, ""))
        n = len(self._points)
        for i in range(n):
            name = self._points[(start + i) % n][1]
            if name in alive:
                return name
        return None


class RouterMetrics:
    """Thread-safe router counters (published as ``router_*`` series)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total: Dict[Tuple[str, str], int] = {}
        self.migrations_total = 0
        self.kv_migrated_bytes_total = 0
        self.redistributes_total = 0
        self.errors_total = 0
        self.replica_healthy: Dict[str, int] = {}
        # Overload/failure hardening counters (serving/overload.py,
        # docs/serving.md "Surviving overload"): hedged prefills fired
        # and won, CRC-rejected migration payloads, requests the ladder
        # shed at the router, and damped (absorbed) health-poll flaps.
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.migrations_corrupt_total = 0
        self.shed_total = 0
        self.flaps_damped_total = 0
        # Fleet plane: federation scrapes that failed (per replica) and
        # incident bundles assembled.
        self.scrape_errors_total: Dict[str, int] = {}
        self.incidents_total = 0

    def record_request(self, replica: str, role: str) -> None:
        with self._lock:
            key = (role, replica)
            self.requests_total[key] = self.requests_total.get(key, 0) + 1

    def record_migration(self, nbytes: int) -> None:
        with self._lock:
            self.migrations_total += 1
            self.kv_migrated_bytes_total += int(nbytes)

    def record_redistribute(self) -> None:
        with self._lock:
            self.redistributes_total += 1

    def record_hedge(self) -> None:
        with self._lock:
            self.hedges_total += 1

    def record_hedge_win(self) -> None:
        with self._lock:
            self.hedge_wins_total += 1

    def record_corrupt_migration(self) -> None:
        with self._lock:
            self.migrations_corrupt_total += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def record_flap_damped(self) -> None:
        with self._lock:
            self.flaps_damped_total += 1

    def record_scrape_error(self, replica: str) -> None:
        with self._lock:
            self.scrape_errors_total[replica] = (
                self.scrape_errors_total.get(replica, 0) + 1
            )

    def record_incident(self) -> None:
        with self._lock:
            self.incidents_total += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def set_replica_health(self, name: str, ok: bool) -> None:
        with self._lock:
            self.replica_healthy[name] = int(ok)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_total": {
                    f"{role}/{rep}": n
                    for (role, rep), n in sorted(self.requests_total.items())
                },
                "migrations_total": self.migrations_total,
                "kv_migrated_bytes_total": self.kv_migrated_bytes_total,
                "redistributes_total": self.redistributes_total,
                "hedges_total": self.hedges_total,
                "hedge_wins_total": self.hedge_wins_total,
                "migrations_corrupt_total": self.migrations_corrupt_total,
                "shed_total": self.shed_total,
                "flaps_damped_total": self.flaps_damped_total,
                "scrape_errors_total": dict(sorted(
                    self.scrape_errors_total.items()
                )),
                "incidents_total": self.incidents_total,
                "errors_total": self.errors_total,
                "replica_healthy": dict(sorted(
                    self.replica_healthy.items()
                )),
            }


class Router:
    """The multi-replica front end: role-aware placement, KV migration,
    session stickiness, health polling, drain-and-redistribute.  Use as
    a context manager; ``Router.build`` constructs the replica fleet
    in-process."""

    def __init__(self, replicas: Dict[str, Server], *,
                 replica_urls: Optional[Dict[str, str]] = None,
                 max_redistributes: int = 8,
                 health_interval: float = 0.25,
                 admission_retry_s: float = 10.0,
                 max_inflight: Optional[int] = None,
                 slo: Optional[SloPolicy] = None,
                 slo_timelines: int = 256,
                 own_servers: bool = False,
                 unhealthy_after: int = 2,
                 breaker_threshold: Optional[int] = 3,
                 breaker_cooldown_s: float = 2.0,
                 hedging: bool = True,
                 hedge_quantile: float = 0.99,
                 hedge_factor: float = 1.5,
                 hedge_min_s: float = 0.05,
                 degradation: Optional[DegradationConfig] = None,
                 metrics_scrape_interval: float = 1.0,
                 incident_dir: Optional[str] = None,
                 incident_min_interval_s: float = 30.0,
                 alert_rules: Optional[Sequence[AlertRule]] = None):
        """Hardening knobs (docs/serving.md "Surviving overload"):

        ``unhealthy_after``: consecutive FAILED health polls before a
        replica is marked unhealthy (flap damping — one transient
        timeout must not trigger a spurious drain-and-redistribute).
        ``breaker_threshold``/``breaker_cooldown_s``: per-replica
        circuit breakers — K consecutive placement failures open the
        breaker without waiting for the poller; after the cooldown one
        half-open probe decides.  ``breaker_threshold=None`` disables
        breakers (chaos baselines).  ``hedging``: fire a duplicate
        prefill on another replica once a request has waited past
        ``hedge_factor`` x the rolling ``hedge_quantile`` first-result
        latency (floored at ``hedge_min_s``); first winner cancels the
        loser.  Only deterministic requests hedge (greedy, or sampled
        with an explicit seed — the duplicate then computes identical
        bytes, so the race cannot change the output).  ``degradation``
        configures the router's :class:`DegradationLadder`
        (``router.ladder``) applied fleet-wide.

        Fleet observability plane (docs/observability.md "Fleet
        plane"): ``metrics_scrape_interval`` paces the health poller's
        piggybacked ``/metrics`` scrape per replica (the federated
        exposition re-exports the latest snapshot);
        ``incident_dir``/``incident_min_interval_s`` place and throttle
        the ``incident_<ts>/`` bundles assembled on watchdog trips,
        replica deaths, deploy rollbacks and autoscaler repairs."""
        if not replicas:
            raise ValueError("router needs at least one replica")
        if unhealthy_after < 1:
            raise ValueError(
                f"unhealthy_after must be >= 1, got {unhealthy_after}"
            )
        urls = replica_urls or {}
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._replicas: Dict[str, Replica] = {
            name: Replica(name, srv, urls.get(name),
                          breaker=self._new_breaker())
            for name, srv in sorted(replicas.items())
        }
        roles = {r.role for r in self._replicas.values()}
        self.mode = "colocated" if roles == {"both"} else "disagg"
        engines = [r.server.engine for r in self._replicas.values()]
        e0 = engines[0]
        for name, rep in self._replicas.items():
            self._validate_geometry(name, rep.server)
        self.max_len = e0.max_len
        self.vocab_size = e0.vocab_size
        self._spec_slack = max(e.spec_k for e in engines)
        self._affinity_block = max(
            e0.kv_page_size, 1
        ) if e0.paged else 16
        self.max_redistributes = int(max_redistributes)
        self.admission_retry_s = float(admission_retry_s)
        self.max_inflight = (
            int(max_inflight) if max_inflight is not None
            else sum(
                r.server.scheduler.max_queue + r.server.engine.max_batch
                for r in self._replicas.values()
            )
        )
        self._own_servers = own_servers
        self.metrics = RouterMetrics()
        self.slo = SloTracker(policy=slo, keep_timelines=slo_timelines)
        self._log = get_logger("ml_trainer_tpu.serving.router")
        self._lock = threading.Lock()
        self._sessions: Dict[str, str] = {}
        self._inflight = 0
        # Deploy state (serving/deploy.py): the generation whose
        # replicas serve default traffic, the in-flight deployment's
        # target generation + tenant-hash fraction, an optional
        # finished-request tap (shadow replay sampling), and the fleet
        # launcher when one built this router (Router.deploy uses its
        # checkpoint-loading factory).
        self._serving_generation = 0
        self._deploy_generation: Optional[int] = None
        self._deploy_fraction = 0.0
        self._request_tap = None
        self.fleet = None
        self._stopping = False
        self._stop_event = threading.Event()
        self._httpd = None
        self._http_thread = None
        self.unhealthy_after = int(unhealthy_after)
        self.hedging = bool(hedging)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_s = float(hedge_min_s)
        # Rolling first-result latency (submit-attempt -> first token
        # or migration): the hedging clock.  Under overload the window
        # inflates with the queues, so hedges back off exactly when
        # duplicates would hurt most.
        self._first_result_lat = RollingQuantile(
            window=256, min_samples=8, default=1.0
        )
        # Fleet-wide degradation ladder: rungs apply to every replica
        # (current AND later-added) via Server.set_degradation.
        self.ladder = DegradationLadder(
            lambda: [r.server for r in self._replicas.values()],
            config=degradation, name="router",
        )
        # Fleet observability plane state: scrape pacing, incident
        # bundle placement + rate limit (one storm, one bundle).
        self.metrics_scrape_interval = float(metrics_scrape_interval)
        self.incident_dir = incident_dir
        self.incident_min_interval_s = float(incident_min_interval_s)
        self._incident_lock = threading.Lock()
        self._last_incident_at = 0.0
        self.last_incident_path: Optional[str] = None
        # Watchtower (telemetry/watchtower.py + alerts.py): the fleet
        # TSDB — every scraped worker exposition lands here with its
        # federation labels, beside the router's own registry sweep —
        # and the declarative alert engine evaluated on each poll tick.
        # Severity-`page` rules fire straight into trigger_incident, so
        # a rule firing assembles the same bundle a replica death does.
        self.watchtower = TimeSeriesStore()
        self.alerts = AlertEngine(
            alert_rules or (), store=self.watchtower,
            incident_trigger=self.trigger_incident,
        )
        self._wt_ingested: Dict[str, float] = {}
        self._wt_sampled_at = 0.0
        self._reindex_replicas()
        self._rebuild_ring()
        self._busy_polls = 0
        for rep in self._replicas.values():
            rep.last_health = rep.fetch_health()
            self.metrics.set_replica_health(rep.name, True)
        self._health_interval = float(health_interval)
        self._poller = threading.Thread(
            target=self._poll_health, daemon=True, name="router-health"
        )
        self._poller.start()

    def _new_breaker(self) -> CircuitBreaker:
        """A breaker per the router's config; threshold None = breakers
        disabled (a breaker that never opens)."""
        if self.breaker_threshold is None:
            return CircuitBreaker(threshold=10 ** 9, cooldown_s=1.0)
        return CircuitBreaker(
            threshold=self.breaker_threshold,
            cooldown_s=self.breaker_cooldown_s,
        )

    def _validate_geometry(self, name: str, server: Server) -> None:
        """One replica's engine against the fleet's reference geometry
        (the first replica's) — shared by __init__ and add_replica."""
        engines = [r.server.engine for r in self._replicas.values()]
        e0, e = engines[0], server.engine
        if e.max_len != e0.max_len or e.vocab_size != e0.vocab_size:
            raise ValueError(
                "replicas must share model geometry: got max_len "
                f"{e.max_len} vs {e0.max_len}, vocab {e.vocab_size} "
                f"vs {e0.vocab_size}"
            )
        if self.mode == "disagg":
            if not e.paged:
                raise ValueError(
                    f"disaggregated mode needs paged engines "
                    f"(kv_page_size > 0): replica '{name}' is "
                    "contiguous — pages are the migration unit"
                )
            if e.kv_page_size != e0.kv_page_size:
                raise ValueError(
                    "replicas must share kv_page_size for migration"
                )

    def _reindex_replicas(self) -> None:
        """Stable fleet indices (sorted-name order) — what the chaos
        faults' ``host=`` parameter names."""
        for i, name in enumerate(sorted(self._replicas)):
            self._replicas[name].server.replica_index = i

    def _rebuild_ring(self) -> None:
        prefill_names = [
            n for n, r in self._replicas.items()
            if r.role in ("prefill", "both")
        ] or list(self._replicas)
        self._ring = _HashRing(prefill_names)

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, model, variables: dict, roles: Sequence[str],
              max_batch: int = 4, kv_page_size: int = 16,
              router_kwargs: Optional[dict] = None,
              **server_kwargs) -> "Router":
        """Build an in-process replica fleet: one ``Server`` per entry
        of ``roles`` (named ``prefill0``/``decode0``/``rep0``...), all
        sharing ``model``/``variables`` (and therefore the process
        compile cache), plus the router in front.  The router OWNS the
        servers — ``close()`` closes them."""
        counts: Dict[str, int] = {}
        replicas: Dict[str, Server] = {}
        for role in roles:
            stem = {"prefill": "prefill", "decode": "decode"}.get(
                role, "rep"
            )
            i = counts.get(stem, 0)
            counts[stem] = i + 1
            replicas[f"{stem}{i}"] = Server(
                model, variables, max_batch=max_batch,
                kv_page_size=kv_page_size, role=role, **server_kwargs
            )
        return cls(replicas, own_servers=True, **(router_kwargs or {}))

    def replica(self, name: str) -> Replica:
        return self._replicas[name]

    @property
    def replicas(self) -> Dict[str, Replica]:
        return dict(self._replicas)

    # -- client surface ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, rng=None,
               eos_token_id: Optional[int] = None,
               deadline: Optional[float] = None,
               tenant: str = "default", priority: int = 0,
               session: Optional[str] = None,
               adapter: Optional[str] = None,
               trace: Optional[dict] = None) -> TokenStream:
        """Route one request (thread-safe).  The returned stream is the
        same surface ``Server.submit`` gives — tokens arrive as the
        serving replicas produce them, across migration and
        redistribution transparently.  ``session`` pins the request's
        decode to a sticky replica for multi-turn streams; ``adapter``
        names the LoRA adapter (the affinity hash includes it, so
        same-adapter traffic lands where the adapter is resident);
        ``trace`` is an inbound trace context (``X-Trace-Context``) —
        absent one, the router originates the context itself, so every
        request's cross-process spans share one trace id."""
        if self._stopping:
            raise RuntimeError("router is closed")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens + self._spec_slack > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + new tokens ({max_new_tokens}) "
                f"exceeds the fleet's max_len ({self.max_len})"
            )
        if eos_token_id is not None and not (
            0 <= eos_token_id < self.vocab_size
        ):
            raise ValueError(
                f"eos_token_id must be in [0, {self.vocab_size}), got "
                f"{eos_token_id}"
            )
        if not isinstance(tenant, str) or not tenant:
            raise ValueError(
                f"tenant must be a non-empty string, got {tenant!r}"
            )
        with self._lock:
            if self._inflight >= self.max_inflight:
                raise AdmissionError(
                    f"router at its in-flight watermark "
                    f"({self.max_inflight}); request rejected"
                )
            self._inflight += 1
        if adapter is not None and (
            not isinstance(adapter, str) or not adapter
        ):
            raise ValueError(
                f"adapter must be a non-empty string or None, got "
                f"{adapter!r}"
            )
        creq = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), rng=rng,
            eos_token_id=eos_token_id, deadline=deadline,
            tenant=tenant, priority=int(priority), adapter=adapter,
        )
        # Trace origin: the router's creq id is the fleet-wide trace id
        # unless the client already carries one — every shadow attempt,
        # migration hop and adoption stamps its spans with this context.
        ctx = dict(trace) if trace else {}
        ctx.setdefault("trace_id", creq.id)
        ctx.setdefault("origin_pid", os.getpid())
        creq.trace_ctx = ctx
        creq.observer = self.slo.observe
        self.slo.track(creq)
        threading.Thread(
            target=self._run_request, args=(creq, session), daemon=True,
            name=f"router-req-{creq.id}",
        ).start()
        return TokenStream(creq, prompt)

    def complete(self, prompt, max_new_tokens: int,
                 timeout: Optional[float] = None, **kwargs) -> np.ndarray:
        """Blocking one-shot through the router."""
        return self.submit(prompt, max_new_tokens, **kwargs).result(
            timeout=timeout
        )

    @staticmethod
    def _serving_replica(creq: Request) -> Optional[str]:
        """The replica that carried (or is carrying) the DECODE of this
        request — the most recent migration/adoption/placement mark on
        its event log; None before placement."""
        for ev in reversed(creq.events):
            kind = ev.get("event")
            if kind in ("kv_migrated", "evac_adopted"):
                return ev.get("to")
            if kind == "routed":
                return ev.get("decode")
        return None

    def kill_replica(self, name: str) -> None:
        """Kill a replica (tests/chaos): the replica fails its
        in-flight work with structured errors — which the router
        redistributes — and leaves the placement pool.  Against a fleet
        process (serving/fleet.py, ``kill_process``) this is a REAL
        ``SIGKILL`` — no goodbye, streams sever mid-flight; in-process
        replicas are marked unhealthy instead (the simulation)."""
        rep = self._replicas[name]
        rep.healthy = False
        self.metrics.set_replica_health(name, False)
        kill = getattr(rep.server, "kill_process", None)
        if kill is not None:
            kill()
        rep.server._mark_unhealthy(f"replica '{name}' killed")
        self.trigger_incident(f"replica_killed: {name}", dead=(name,))

    # -- fleet management (serving/autoscaler.py) -------------------------

    def add_replica(self, name: str, server: Server,
                    url: Optional[str] = None,
                    generation: Optional[int] = None) -> None:
        """Grow the fleet by one replica (thread-safe; the autoscaler's
        scale-up action).  The new replica inherits the fleet's current
        degradation rung, joins the affinity ring/placement pools, and
        shares the process compile cache — adding capacity under load
        mints no compiles when the geometry matches (enforced).
        ``generation`` defaults to the serving generation, so autoscaler
        scale-ups/repairs during a deploy grow the STABLE fleet; the
        deploy machinery passes its target generation explicitly."""
        if name in self._replicas:
            raise ValueError(f"replica '{name}' already exists")
        if server.role not in ("prefill", "decode", "both"):
            raise ValueError(f"bad role {server.role!r}")
        if self.mode == "colocated" and server.role != "both":
            raise ValueError(
                "a colocated fleet only takes role='both' replicas"
            )
        self._validate_geometry(name, server)
        if url is None:
            # A fleet RemoteServer (serving/fleet.py) carries its own
            # base URL — the autoscaler's factory path adds replicas
            # without threading one through.
            url = getattr(server, "url", None)
        if generation is None:
            generation = self._serving_generation
        rep = Replica(name, server, url, breaker=self._new_breaker(),
                      generation=generation)
        server.set_degradation(self.ladder.level, self.ladder.config)
        rep.last_health = rep.fetch_health()
        with self._lock:
            self._replicas = {
                **self._replicas, name: rep,
            }
        self._reindex_replicas()
        self._rebuild_ring()
        self.metrics.set_replica_health(name, True)
        from ml_trainer_tpu.telemetry.flight import get_recorder

        get_recorder().record(
            "fleet_change", action="add_replica", replica=name,
            role=server.role, fleet=len(self._replicas),
            generation=generation,
        )
        self._log.info(
            "router_replica_added", replica=name, role=server.role
        )

    def remove_replica(self, name: str, timeout: float = 30.0,
                       close: Optional[bool] = None) -> bool:
        """Shrink the fleet by one replica (the autoscaler's scale-down
        action): stop placing work on it, wait for it to drain
        naturally (bounded by ``timeout``), then detach it (closing its
        server when the router owns the fleet, or when ``close=True``).
        Returns True when the replica drained clean; a False return
        means in-flight work was failed-and-redistributed at detach —
        clients still finish via the redistribute path."""
        rep = self._replicas[name]
        rep.removing = True  # leaves every placement pool immediately
        deadline = time.monotonic() + timeout
        drained = False
        while time.monotonic() < deadline and not self._stopping:
            h = rep.server.health() if not rep.url else rep.fetch_health()
            if (
                not h.get("active_slots")
                and not h.get("queue_depth")
                and not h.get("adoptions_pending")
            ):
                drained = True
                break
            self._stop_event.wait(0.05)
        with self._lock:
            reps = dict(self._replicas)
            reps.pop(name, None)
            self._replicas = reps
            self._sessions = {
                s: n for s, n in self._sessions.items() if n != name
            }
        self._reindex_replicas()
        self._rebuild_ring()
        if not drained:
            # Detaching with work in flight: fail it structured so the
            # pumps redistribute — never strand a stream.
            rep.server._mark_unhealthy(
                f"replica '{name}' removed by the autoscaler"
            )
        if close if close is not None else self._own_servers:
            rep.server.close()
        self.metrics.set_replica_health(name, False)
        from ml_trainer_tpu.telemetry.flight import get_recorder

        get_recorder().record(
            "fleet_change", action="remove_replica", replica=name,
            drained=drained, fleet=len(self._replicas),
        )
        self._log.info(
            "router_replica_removed", replica=name, drained=drained
        )
        return drained

    def reassign_role(self, name: str, role: str,
                      timeout: float = 30.0) -> bool:
        """Flip a replica's role prefill<->decode (the autoscaler's
        rebalance action) by DRAINING it through the PR 13 migration
        machinery first: the replica leaves the placement pools, its
        active slots' KV is exported page-granular and adopted onto
        other decode replicas (streams keep flowing — no re-prefill),
        its queued requests redistribute, and only then does the role
        flip and the affinity ring rebuild.  Returns True on success;
        False when the drain timed out (role unchanged, replica back in
        its old pools — a flip must never half-happen)."""
        if role not in ("prefill", "decode"):
            raise ValueError(
                f"role must be 'prefill' or 'decode', got {role!r}"
            )
        if self.mode != "disagg":
            raise ValueError("role reassignment needs a disagg fleet")
        rep = self._replicas[name]
        if rep.role == role:
            return True
        rep.removing = True
        evacuated = rep.server.evacuate(
            lambda req, export: self._adopt_evacuated(req, export, rep),
            timeout=timeout,
        )
        if not evacuated:
            rep.removing = False
            self._log.error(
                "router_role_flip_timeout", replica=name, role=role
            )
            return False
        rep.role = role
        rep.server.role = role
        rep.removing = False
        with self._lock:
            self._sessions = {
                s: n for s, n in self._sessions.items() if n != name
            }
        self._rebuild_ring()
        from ml_trainer_tpu.telemetry.flight import get_recorder

        get_recorder().record(
            "fleet_change", action="reassign_role", replica=name,
            role=role,
        )
        self._log.info(
            "router_role_reassigned", replica=name, role=role
        )
        return True

    def deploy(self, ckpt: str, canary: float = 0.05,
               shadow: bool = False, *, factory=None, config=None):
        """Roll the fleet onto new base weights under live traffic
        (serving/deploy.py, docs/serving.md "Deploys"): spawn
        new-generation replicas from the ``ckpt`` export (sharing the
        fleet's on-disk compile cache — no recompile storm), route the
        deterministic tenant-hash slice ``[0, canary)`` at them, watch
        the canary slice's SLO burn, and either ramp 5% -> 50% -> 100%
        and retire the old generation, or auto-roll-back through the
        drain/evacuate machinery with zero dropped streams.  With
        ``shadow=True`` a sampled fraction of live requests is replayed
        against the new replicas OFF the serving path and diffed into
        ``Deployment.shadow_report()`` before any real traffic moves.

        ``factory`` (role -> server) defaults to the attached fleet's
        checkpoint-loading factory (``Fleet.make_router`` wires
        ``router.fleet``); in-process callers pass their own.  Returns
        the started :class:`~ml_trainer_tpu.serving.deploy.Deployment`
        — ``wait()`` for the verdict, ``close()`` to stop watching."""
        from ml_trainer_tpu.serving.deploy import DeployConfig, Deployment

        active = getattr(self, "_deployment", None)
        if active is not None and not active.finished():
            raise RuntimeError(
                f"a deployment is already {active.state}; wait for it "
                "or close() it before starting another"
            )
        if factory is None:
            if self.fleet is None:
                raise ValueError(
                    "Router.deploy needs a server factory: attach a "
                    "Fleet (Fleet.make_router) or pass factory="
                )
            factory = self.fleet.deploy_factory(ckpt)
        cfg = config if config is not None else DeployConfig()
        if canary is not None:
            cfg = dataclasses.replace(cfg, canary=float(canary))
        if shadow:
            cfg = dataclasses.replace(cfg, shadow=True)
        self._deployment = Deployment(self, ckpt, factory, config=cfg)
        return self._deployment.start()

    def _adopt_evacuated(self, req: Request, export, source: Replica
                         ) -> None:
        """Adoption sink for a role-flip evacuation: land the exported
        slot on any other decode candidate (CRC-verified, fresh
        serialization per candidate).  When nobody can take it, the
        request fails with a retryable ``draining`` error and its pump
        redistributes — byte-identical either way."""
        for rep in self._decode_candidates(generation=source.generation):
            if rep is source or not rep.try_place():
                continue
            payload = transfer.to_bytes(export)
            try:
                adopt_payload = getattr(
                    rep.server, "adopt_payload", None
                )
                if adopt_payload is not None:
                    # Fleet RPC (serving/fleet.py): ship the bytes —
                    # CRC verification happens in the RECEIVING
                    # process, structured verdicts map back here.
                    adopt_payload(req, payload)
                else:
                    incoming = transfer.from_bytes(payload)
                    rep.server.adopt(req, incoming)
            except MigrationCorrupt:
                self.metrics.record_corrupt_migration()
                continue
            except (AdmissionError, EngineUnhealthy, RuntimeError):
                continue
            self.metrics.record_migration(len(payload))
            req.mark("evac_adopted", to=rep.name)
            return
        req.finish(
            "error",
            "replica draining for role reassignment: no candidate "
            "could adopt the evacuated KV; request redistributed",
        )

    def health(self) -> dict:
        """The router ``/healthz`` payload: aggregate liveness plus
        every replica's last health snapshot."""
        reps = {
            name: {
                "healthy": rep.healthy,
                "role": rep.role,
                "breaker": rep.breaker.state,
                **{
                    k: rep.last_health.get(k)
                    for k in ("active_slots", "queue_depth",
                              "kv_pages_free", "adoptions_pending",
                              "adapters_resident",
                              "compile_events_post_warmup_total",
                              "degradation_level")
                },
            }
            for name, rep in self._replicas.items()
        }
        n_alive = sum(1 for r in self._replicas.values() if r.healthy)
        with self._lock:
            inflight = self._inflight
        return {
            "ok": n_alive > 0 and not self._stopping,
            "mode": self.mode,
            "replicas_alive": n_alive,
            "replicas_total": len(self._replicas),
            "inflight": inflight,
            "sessions": len(self._sessions),
            "degradation_level": self.ladder.level,
            "replicas": reps,
        }

    def snapshot(self) -> dict:
        """Router metrics + health in one JSON-safe dict (the bench
        artifact's router section)."""
        snap = self.metrics.snapshot()
        snap["mode"] = self.mode
        snap["degradation"] = self.ladder.snapshot()
        with self._lock:
            snap["inflight"] = self._inflight
            snap["sessions"] = len(self._sessions)
            snap["serving_generation"] = self._serving_generation
            snap["deploy_generation"] = self._deploy_generation
            snap["deploy_fraction"] = self._deploy_fraction
        return snap

    def close(self) -> None:
        self._stopping = True
        self._stop_event.set()
        deployment = getattr(self, "_deployment", None)
        if deployment is not None:
            deployment.close()
        if self._own_servers:
            for rep in self._replicas.values():
                rep.server.close()
        self._poller.join(timeout=10.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- placement --------------------------------------------------------

    def _alive(self) -> Dict[str, Replica]:
        return {
            n: r for n, r in self._replicas.items() if r.placeable()
        }

    # -- deploy traffic split (serving/deploy.py) --------------------------

    @staticmethod
    def tenant_slice(tenant: str) -> float:
        """Deterministic [0, 1) coordinate for a tenant: a deploy at
        fraction ``f`` routes exactly the tenants with
        ``tenant_slice(t) < f`` to the new generation — the same
        tenants on every poll, every process, every ramp stage (the
        canary slice is a stable cohort, not a coin flip per request)."""
        h = hashlib.sha1(b"deploy|" + tenant.encode()).hexdigest()[:8]
        return int(h, 16) / float(1 << 32)

    def set_deploy_split(self, generation: Optional[int],
                         fraction: float) -> None:
        """Point the tenant-hash slice ``[0, fraction)`` at
        ``generation`` (None tears the split down — all traffic back on
        the serving generation)."""
        with self._lock:
            self._deploy_generation = generation
            self._deploy_fraction = float(fraction)

    def promote_generation(self, generation: int) -> None:
        """Make ``generation`` the serving generation (deploy ramp
        completed): default traffic — and autoscaler-grown capacity —
        now lands there."""
        with self._lock:
            self._serving_generation = int(generation)
            self._deploy_generation = None
            self._deploy_fraction = 0.0

    def _target_generation(self, tenant: str) -> int:
        """Which generation serves this tenant right now."""
        gen, frac = self._deploy_generation, self._deploy_fraction
        if gen is not None and self.tenant_slice(tenant) < frac:
            return gen
        return self._serving_generation

    @staticmethod
    def _gen_pool(pool: Dict[str, Replica], generation: int
                  ) -> Dict[str, Replica]:
        """Restrict a placement pool to one deploy generation.  An
        empty restriction falls back to the full pool — serving
        somewhere beats refusing (the deploy monitors burn; it never
        relies on placement failing closed)."""
        sub = {
            n: r for n, r in pool.items() if r.generation == generation
        }
        return sub or pool

    def _affinity_key(self, tenant: str, prompt: np.ndarray,
                      adapter: Optional[str] = None) -> bytes:
        """Consistent-hash key on ``(tenant, adapter, first KV block)``:
        same-tenant shared prefixes keep hitting one prefill replica's
        prefix cache, and same-adapter traffic lands where the adapter
        is already resident (its pool slot warm, its prefix namespace
        populated) instead of minting a load on every replica."""
        block = np.asarray(
            prompt[: self._affinity_block], np.int32
        ).tobytes()
        return (
            tenant.encode() + b"\x1f" + (adapter or "").encode()
            + b"|" + block
        )

    def _place(self, creq: Request, session: Optional[str],
               exclude_prefill: Optional[str] = None
               ) -> Tuple[Replica, Replica]:
        """(prefill replica, decode replica) for this attempt, from live
        health, breaker-gated.  ``exclude_prefill`` skips the named
        replica (the hedging path never duplicates onto the replica it
        is hedging against).  Raises ``EngineUnhealthy`` when nothing
        is placeable."""
        alive = self._alive()
        if not alive:
            raise EngineUnhealthy("no healthy replica available")
        # Deploy split first: the whole attempt places within ONE
        # generation (prefill, decode, hedges, adoption candidates) —
        # KV never crosses a weights boundary mid-stream.
        alive = self._gen_pool(alive, self._target_generation(creq.tenant))
        key = self._affinity_key(creq.tenant, creq.prompt, creq.adapter)
        if self.mode == "colocated":
            pool = {
                n: r for n, r in alive.items() if n != exclude_prefill
            } or alive
            name = self._ring.place(key, pool) or sorted(pool)[0]
            rep = pool[name]
            if not rep.breaker.allow():
                others = sorted(
                    (r for r in pool.values()
                     if r is not rep and r.breaker.allow()),
                    key=Replica.load_score,
                )
                if not others:
                    raise EngineUnhealthy(
                        "no placeable replica: breakers open/probing"
                    )
                rep = others[0]
            return rep, rep
        prefill_pool = {
            n: r for n, r in alive.items()
            if r.role in ("prefill", "both")
        } or alive  # degraded: every engine CAN prefill
        decode_pool = {
            n: r for n, r in alive.items()
            if r.role in ("decode", "both")
        } or alive
        if exclude_prefill and len(prefill_pool) > 1:
            prefill_pool = {
                n: r for n, r in prefill_pool.items()
                if n != exclude_prefill
            }
        name = self._ring.place(key, prefill_pool) or sorted(prefill_pool)[0]
        prefill = prefill_pool[name]
        if not prefill.breaker.allow():
            others = sorted(
                (r for r in prefill_pool.values()
                 if r is not prefill and r.breaker.allow()),
                key=Replica.load_score,
            )
            if not others:
                raise EngineUnhealthy(
                    "no placeable prefill replica: breakers open/probing"
                )
            prefill = others[0]
        decode = None
        if session:
            with self._lock:
                sticky = self._sessions.get(session)
            if sticky in decode_pool and decode_pool[sticky].placeable():
                decode = decode_pool[sticky]
        if decode is None:
            decode = min(decode_pool.values(), key=Replica.load_score)
            if session:
                with self._lock:
                    self._sessions[session] = decode.name
        decode.pending += 1
        return prefill, decode

    def _decode_candidates(self, generation: Optional[int] = None
                           ) -> List[Replica]:
        alive = self._alive()
        if generation is not None:
            alive = {
                n: r for n, r in alive.items()
                if r.generation == generation
            }
        pool = [
            r for r in alive.values() if r.role in ("decode", "both")
        ] or list(alive.values())
        return sorted(pool, key=Replica.load_score)

    # -- the per-request state machine ------------------------------------

    def _run_request(self, creq: Request, session: Optional[str]) -> None:
        try:
            self._serve(creq, session)
        except Exception as e:  # noqa: BLE001 — never hang a client
            if creq.state in ("queued", "active"):
                self.metrics.record_error()
                creq.finish(
                    "error", f"router failure: {type(e).__name__}: {e}"
                )
        finally:
            with self._lock:
                self._inflight -= 1
            tap = self._request_tap
            if tap is not None:
                try:  # shadow-replay sampling (serving/deploy.py) —
                    # observability must never fail a served stream
                    tap(creq)
                except Exception:  # noqa: BLE001
                    pass

    def _remaining_deadline(self, creq: Request) -> Optional[float]:
        if creq.deadline is None:
            return None
        return creq.deadline - (time.monotonic() - creq.submitted_at)

    def _shadow(self, creq: Request, committed: List[int],
                deadline: Optional[float]) -> Request:
        """The per-attempt replica-local request: same prompt and
        sampling state, committed tokens preloaded (resume prefix), the
        remaining deadline budget, and the cumulative preemption count
        so engine give-ups stay structured across replicas."""
        shadow = Request(
            prompt=creq.prompt, max_new_tokens=creq.max_new_tokens,
            temperature=creq.temperature, rng=creq.rng,
            eos_token_id=creq.eos_token_id, deadline=deadline,
            tenant=creq.tenant, priority=creq.priority,
            adapter=creq.adapter,
        )
        shadow.tokens = [int(t) for t in committed]
        shadow.preemptions = creq.preemptions
        # The shadow gets a FRESH id per attempt; the trace context is
        # what keeps its spans on the originating request's causal
        # track across processes.
        if creq.trace_ctx:
            shadow.trace_ctx = dict(creq.trace_ctx)
        return shadow

    def _serve(self, creq: Request, session: Optional[str]) -> None:
        redistributes = 0
        while True:
            if self._stopping:
                creq.finish("error", "router is closed")
                return
            deadline = self._remaining_deadline(creq)
            if deadline is not None and deadline <= 0:
                creq.finish(
                    "expired",
                    f"deadline ({creq.deadline}s) passed while routing "
                    f"({redistributes} redistribution(s) consumed the "
                    "budget)",
                )
                return
            # Resume from what the CLIENT received, not what the shadow
            # recorded: a dying replica's last decode step can append a
            # token to the shadow after its stream was failed, and a
            # token the pump never forwarded must be recomputed (it is —
            # deterministically), never skipped.
            shadow = self._shadow(creq, list(creq.tokens), deadline)
            placed = self._submit_attempt(creq, shadow, session)
            if placed is None:
                return  # _submit_attempt finished creq with the reason
            prefill_rep, decode_rep = placed
            outcome, shadow, decode_rep = self._pump(
                creq, shadow, decode_rep, prefill_rep, session
            )
            if outcome == "done":
                creq.preemptions = shadow.preemptions
                decode_rep.breaker.record_success()
                creq.finish("done")
                return
            if outcome == "expired":
                creq.finish("expired", shadow.error)
                return
            if outcome == "shed":
                # A replica-side degradation rung shed the shadow: the
                # structured refusal propagates to the client verbatim
                # (503 + retry_after on the HTTP path).
                self.metrics.record_shed()
                creq.retry_after = shadow.retry_after
                creq.finish("shed", shadow.error)
                return
            if outcome == "retry":
                redistributes += 1
                self.metrics.record_redistribute()
                decode_rep.breaker.record_failure(
                    shadow.error or "stream failed"
                )
                creq.preemptions = shadow.preemptions + 1
                creq.mark(
                    "redistributed", attempt=redistributes,
                    committed_tokens=len(creq.tokens), error=shadow.error,
                )
                if redistributes > self.max_redistributes:
                    self.metrics.record_error()
                    creq.finish(
                        "error",
                        f"request {creq.id} (tenant '{creq.tenant}') "
                        f"redistributed {redistributes}x after replica "
                        f"failures; giving up after max_redistributes="
                        f"{self.max_redistributes} (last: {shadow.error})",
                    )
                    return
                continue
            self.metrics.record_error()
            creq.finish("error", shadow.error or "replica error")
            return

    def _submit_attempt(self, creq: Request, shadow: Request,
                        session: Optional[str],
                        exclude_prefill: Optional[str] = None,
                        quiet: bool = False
                        ) -> Optional[Tuple[Replica, Replica]]:
        """Place + submit one attempt.  Returns ``(prefill, decode)``
        replicas on success, or None after finishing ``creq`` with a
        structured error (placement/admission exhausted — unless
        ``quiet``, the hedging path, where failure just means no
        duplicate fires).  The retry window is capped by the request's
        remaining deadline: a 1-second-deadline request never spins the
        full admission retry budget."""
        give_up_at = time.monotonic() + self.admission_retry_s
        deadline_at = (
            creq.submitted_at + creq.deadline
            if creq.deadline is not None else None
        )
        if deadline_at is not None:
            give_up_at = min(give_up_at, deadline_at)
        last_err = "no healthy replica available"
        while not self._stopping:
            try:
                prefill_rep, decode_rep = self._place(
                    creq, session, exclude_prefill=exclude_prefill
                )
            except EngineUnhealthy as e:
                last_err = str(e)
                if time.monotonic() > give_up_at or quiet:
                    break
                self._stop_event.wait(0.05)
                continue
            disagg = prefill_rep is not decode_rep
            shadow.migration_sink = (
                (lambda r, exp: r._stream.put((_MIGRATE, exp)))
                if disagg else None
            )
            try:
                prefill_rep.server.submit_request(shadow)
            except OverloadShed as e:
                # The replica's degradation ladder refused it — a
                # structured terminal, not a placement failure.
                if quiet:
                    return None
                creq.retry_after = e.retry_after
                self.metrics.record_shed()
                creq.finish("shed", str(e))
                return None
            except AdmissionError as e:
                last_err = str(e)
                prefill_rep.breaker.record_success()  # alive, just full
                if time.monotonic() > give_up_at or quiet:
                    break
                self._stop_event.wait(0.02)
                continue
            except (EngineUnhealthy, RuntimeError) as e:
                # The poller will confirm, but don't wait for it.
                last_err = str(e)
                prefill_rep.breaker.record_failure(str(e))
                prefill_rep.healthy = False
                self.metrics.set_replica_health(prefill_rep.name, False)
                if time.monotonic() > give_up_at or quiet:
                    break
                continue
            creq.mark(
                "routed", prefill=prefill_rep.name,
                decode=decode_rep.name, disagg=disagg,
                hedge=bool(exclude_prefill),
            )
            self.metrics.record_request(
                prefill_rep.name, "prefill" if disagg else "colocated"
            )
            return prefill_rep, decode_rep
        if quiet:
            return None
        if (
            deadline_at is not None and time.monotonic() >= deadline_at
        ):
            creq.finish(
                "expired",
                f"deadline ({creq.deadline}s) passed while placing "
                f"request {creq.id}: {last_err}",
            )
            return None
        self.metrics.record_error()
        creq.finish(
            "error",
            f"router could not place request {creq.id} (tenant "
            f"'{creq.tenant}'): {last_err}",
        )
        return None

    def _hedge_after_s(self) -> float:
        """Seconds a request may wait for its first result before the
        router fires a duplicate prefill: ``hedge_factor`` x the
        rolling ``hedge_quantile`` first-result latency, floored."""
        return max(
            self.hedge_min_s,
            self.hedge_factor
            * self._first_result_lat.quantile(self.hedge_quantile),
        )

    def _hedge_eligible(self, creq: Request) -> bool:
        """Hedging duplicates work — it must never change bytes.  A
        greedy request is deterministic; a sampled request is only
        hedgeable when the caller pinned the seed (both replicas then
        compute the identical stream, so the race winner is
        irrelevant)."""
        return self.hedging and (
            creq.temperature == 0.0 or creq.rng is not None
        )

    def _pump(self, creq: Request, shadow: Request, decode_rep: Replica,
              prefill_rep: Replica, session: Optional[str]
              ) -> tuple:
        """Forward the shadow's stream to the client, adopting the KV
        export into the decode replica when it arrives, HEDGING the
        attempt onto another prefill replica when the first result is
        late.  Returns ``(outcome, winning_shadow)`` — outcome is
        ``done`` / ``expired`` / ``shed`` / ``retry`` (replica failure,
        redistribute) / ``error`` (structured terminal)."""
        t0 = time.monotonic()
        first_seen = False
        hedge_shadow: Optional[Request] = None
        hedge_pair: Optional[Tuple[Replica, Replica]] = None
        hedge_at = (
            t0 + self._hedge_after_s()
            if self._hedge_eligible(creq) else None
        )
        while True:
            # Before the first result arrives, poll at a cadence that
            # can notice the hedge deadline; afterwards the plain 0.5s
            # drain is enough.
            wait = 0.5
            if not first_seen and hedge_at is not None:
                wait = min(wait, max(hedge_at - time.monotonic(), 0.01))
            try:
                item = shadow._stream.get(timeout=wait)
            except _queue.Empty:
                if self._stopping:
                    shadow.error = shadow.error or "router is closed"
                    return "error", shadow, decode_rep
                if (
                    not first_seen and hedge_at is not None
                    and hedge_shadow is None
                    and time.monotonic() >= hedge_at
                ):
                    hedge_shadow, hedge_pair = self._fire_hedge(
                        creq, prefill_rep, session
                    )
                    if hedge_shadow is None:
                        # No idle capacity to duplicate onto right now;
                        # re-check at a gentle cadence — a slot may free
                        # up while this request is still stuck.
                        hedge_at = time.monotonic() + 0.25
                if hedge_shadow is not None and not first_seen:
                    # Race: whichever stream produces first wins.
                    try:
                        h_item = hedge_shadow._stream.get(timeout=0.02)
                    except _queue.Empty:
                        continue
                    # The hedge won: cancel the primary, swap streams.
                    self.metrics.record_hedge_win()
                    creq.mark(
                        "hedge_won", prefill=hedge_pair[0].name,
                        decode=hedge_pair[1].name,
                    )
                    self._cancel_attempt(prefill_rep, shadow)
                    shadow, hedge_shadow = hedge_shadow, None
                    prefill_rep, decode_rep = hedge_pair
                    item = h_item
                else:
                    continue
            if not first_seen:
                first_seen = True
                if hedge_at is None or time.monotonic() < hedge_at:
                    # Only un-hedged first results feed the hedge
                    # clock: a rescued attempt's (slow) latency would
                    # otherwise inflate the p99 and talk later hedges
                    # out of firing exactly while a replica is sick.
                    self._first_result_lat.observe(time.monotonic() - t0)
                if hedge_shadow is not None:
                    # The primary won the race: withdraw the duplicate.
                    self._cancel_attempt(hedge_pair[0], hedge_shadow)
                    hedge_shadow = None
            if item == _DONE:
                if shadow.state == "done":
                    return "done", shadow, decode_rep
                if shadow.state == "expired":
                    return "expired", shadow, decode_rep
                if shadow.state == "shed":
                    return "shed", shadow, decode_rep
                if self._stopping or not self._retryable(shadow.error):
                    return "error", shadow, decode_rep
                return "retry", shadow, decode_rep
            if isinstance(item, tuple) and item[0] == _MIGRATE:
                if not self._adopt(creq, shadow, decode_rep, item[1]):
                    return "retry", shadow, decode_rep
                continue
            creq.push_token(int(item))

    def _fire_hedge(self, creq: Request, primary_prefill: Replica,
                    session: Optional[str]):
        """Fire the duplicate prefill on a DIFFERENT prefill replica
        (quiet placement — no duplicate available just means no hedge).
        Returns ``(hedge_shadow, (prefill, decode))`` or ``(None,
        None)``.

        Hedges only target genuinely IDLE capacity: when every other
        replica is also loaded (uniform saturation), a duplicate just
        queues behind existing work and doubles the fleet's prefill
        load exactly when it can least afford it — the classic hedging
        anti-pattern.  The depth gate makes hedging self-throttling:
        it rescues requests stuck behind a sick replica while healthy
        capacity idles, and stands down when the whole fleet is the
        bottleneck (the degradation ladder's job, not hedging's)."""
        alive = self._alive()
        pool = [
            r for r in alive.values()
            if r.role in ("prefill", "both") and r is not primary_prefill
            and r.generation == primary_prefill.generation
        ]
        if not pool:
            return None, None
        best = min(pool, key=Replica.load_score)
        if best.load_score()[0] >= best.server.engine.max_batch:
            return None, None
        hedge_shadow = self._shadow(
            creq, list(creq.tokens), self._remaining_deadline(creq)
        )
        placed = self._submit_attempt(
            creq, hedge_shadow, session,
            exclude_prefill=primary_prefill.name, quiet=True,
        )
        if placed is None:
            return None, None
        if placed[0] is primary_prefill:
            # Only one prefill replica is placeable: a duplicate on the
            # same replica would just deepen its queue.
            self._cancel_attempt(placed[0], hedge_shadow)
            return None, None
        self.metrics.record_hedge()
        creq.mark(
            "hedged", prefill=placed[0].name, decode=placed[1].name,
            after_ms=round(self._hedge_after_s() * 1e3, 1),
        )
        return hedge_shadow, placed

    def _cancel_attempt(self, rep: Replica, shadow: Request) -> None:
        """Withdraw a raced attempt's losing shadow from its replica
        (best effort — the replica may already be failing it)."""
        try:
            rep.server.cancel(shadow)
        except Exception:  # noqa: BLE001 — the loser is abandoned anyway
            pass

    def _adopt(self, creq: Request, shadow: Request,
               decode_rep: Replica, export) -> bool:
        """Hand the exported KV to a decode replica — the placed one
        first, any healthy decode candidate as fallback.  Every
        candidate gets a FRESH serialization round-trip (the payload is
        transport-shaped and metered in real bytes), CRC32-verified on
        deserialization AND import: a corrupt payload (chaos
        ``migration_corrupt``, or a real transport flip) is refused
        with a structured error and the adoption retries on the next
        candidate instead of silently adopting garbage."""
        from ml_trainer_tpu.resilience.faults import active_plan

        # Fallback candidates stay within the exporting attempt's
        # generation: adopting onto other weights would be refused with
        # weights_mismatch anyway (transfer.import_kv_slot) — don't
        # burn serialization round-trips finding that out.
        candidates = [decode_rep] + [
            r for r in self._decode_candidates(
                generation=decode_rep.generation
            )
            if r is not decode_rep
        ]
        for rep in candidates:
            if not rep.try_place():
                continue
            wire_t0 = time.monotonic()
            payload = transfer.to_bytes(export)
            plan = active_plan()
            if plan is not None:
                fault = plan.fire("migration_corrupt")
                if fault is not None:
                    # One bit flipped in flight: the CRC gate below
                    # must catch it.
                    flipped = bytearray(payload)
                    flipped[len(flipped) // 2] ^= 0x40
                    payload = bytes(flipped)
            try:
                adopt_payload = getattr(
                    rep.server, "adopt_payload", None
                )
                if adopt_payload is not None:
                    # Fleet RPC (serving/fleet.py): POST the bytes to
                    # the replica PROCESS — the CRC gate runs at the
                    # receiving end (a bit flipped on this socket hop
                    # is caught there), and the structured verdict
                    # maps onto the same except arms below.
                    adopt_payload(shadow, payload)
                else:
                    incoming = transfer.from_bytes(payload)
                    rep.server.adopt(shadow, incoming)
            except MigrationCorrupt as e:
                self.metrics.record_corrupt_migration()
                self._log.error(
                    "router_migration_corrupt", replica=rep.name,
                    error=str(e),
                )
                creq.mark(
                    "migration_corrupt", to=rep.name, error=str(e),
                )
                continue  # fresh serialization for the next candidate
            except AdmissionError:
                continue
            except (EngineUnhealthy, RuntimeError) as e:
                rep.breaker.record_failure(str(e))
                rep.healthy = False
                self.metrics.set_replica_health(rep.name, False)
                continue
            self.metrics.record_migration(len(payload))
            self.metrics.record_request(rep.name, "decode")
            creq.mark(
                "kv_migrated", to=rep.name, kv_bytes=len(payload),
                pages=export.n_pages,
            )
            # The wire hop on the ROUTER's trace lane: serialize ->
            # adopted, bridging the prefill lane's span to the decode
            # lane's in the merged fleet timeline.
            ctx = creq.trace_ctx or {}
            spans.complete_event(
                f"kv_wire {ctx.get('trace_id', creq.id)}",
                wire_t0, time.monotonic(), category="router",
                request=creq.id,
                trace_id=ctx.get("trace_id", creq.id),
                to=rep.name, kv_bytes=len(payload),
            )
            return True
        shadow.error = (
            "serving engine unhealthy: no decode replica could adopt "
            "the migrated KV"
        )
        return False

    @staticmethod
    def _retryable(err: Optional[str]) -> bool:
        """Replica-level failures redistribute; the engine's structured
        give-ups (max_preemptions) and unknown errors surface to the
        client as-is."""
        if not err:
            return False
        if "max_preemptions" in err:
            return False
        return any(
            needle in err
            for needle in ("unhealthy", "server closed", "wedged",
                           "engine thread died", "killed", "draining")
        )

    # -- health polling ---------------------------------------------------

    def _poll_health(self) -> None:
        while not self._stopping:
            self._fire_chaos_kill()
            for rep in self._replicas.values():
                payload = rep.fetch_health()
                rep.last_health = payload
                rep.pending = 0
                ok = (
                    bool(payload.get("healthy"))
                    and not payload.get("draining")
                    and not payload.get("closed")
                )
                if ok:
                    rep.fail_polls = 0
                    if not rep.healthy:
                        # Recovered (or the flap cleared): rejoin the
                        # placement pool.
                        self._log.info(
                            "router_replica_recovered", replica=rep.name
                        )
                else:
                    rep.fail_polls += 1
                    if rep.fail_polls < self.unhealthy_after and rep.healthy:
                        # Flap damping: ONE dropped/failed poll is a
                        # transient until K consecutive confirm it —
                        # a spurious drain-and-redistribute costs far
                        # more than one poll interval of patience.
                        self.metrics.record_flap_damped()
                        self._log.info(
                            "router_healthz_flap_damped", replica=rep.name,
                            fail_polls=rep.fail_polls,
                            reason=payload.get("reason"),
                        )
                        continue
                if rep.healthy and not ok:
                    self._log.error(
                        "router_replica_unhealthy", replica=rep.name,
                        reason=payload.get("reason"),
                    )
                    # Watchdog trip / engine death / severed process:
                    # capture the fleet's state while it is still warm.
                    self.trigger_incident(
                        f"replica_unhealthy: {rep.name}: "
                        f"{payload.get('reason')}",
                        dead=(rep.name,),
                    )
                rep.healthy = ok
                self.metrics.set_replica_health(rep.name, ok)
            self.scrape_metrics()
            self._watchtower_tick()
            self._stop_event.wait(self._health_interval)

    def _fire_chaos_kill(self) -> None:
        """``replica_kill`` chaos hook (resilience/faults.py): at the
        matching BUSY poll (the fleet is serving traffic), kill the
        replica whose fleet index matches the fault's ``host`` — the
        real watchdog-death path, under real load."""
        from ml_trainer_tpu.resilience.faults import active_plan

        plan = active_plan()
        if plan is None:
            return
        with self._lock:
            busy = self._inflight > 0
        if not busy:
            return
        self._busy_polls += 1
        fault = plan.fire("replica_kill", step=self._busy_polls)
        if fault is None:
            return
        for name, rep in sorted(self._replicas.items()):
            if rep.server.replica_index == fault.host and rep.healthy:
                self._log.error(
                    "router_chaos_replica_kill", replica=name,
                    poll=self._busy_polls,
                )
                self.kill_replica(name)
                return

    # -- telemetry --------------------------------------------------------

    def _watchtower_tick(self) -> None:
        """One TSDB + alert sweep, riding the health poll: ingest every
        FRESH worker exposition (federation labels preserved), sample
        the router's own registry at the scrape cadence, then evaluate
        the declarative rules.  Best-effort — the poller never dies on
        observability work."""
        try:
            now = time.time()
            mono = time.monotonic()
            for name, rep in self._replicas.items():
                if rep.metrics_text is None:
                    continue
                # Only ingest a snapshot once: scrape pacing stamps
                # metrics_scraped_at, so an unchanged stamp means the
                # same bytes (replace, never re-append).
                if self._wt_ingested.get(name) == rep.metrics_scraped_at:
                    continue
                self._wt_ingested[name] = rep.metrics_scraped_at
                self.watchtower.ingest_exposition(
                    rep.metrics_text, t=now,
                    extra_labels={
                        "replica": name, "role": rep.role,
                        "generation": str(rep.generation),
                    },
                    force=True,
                )
            if mono - self._wt_sampled_at >= self.metrics_scrape_interval:
                self._wt_sampled_at = mono
                from ml_trainer_tpu.telemetry.registry import (
                    default_registry,
                )

                registry = default_registry()
                self.publish(registry)
                self.watchtower.sample_registry(
                    registry, t=now, force=True
                )
            self.alerts.evaluate(now=now)
        except Exception as e:  # noqa: BLE001 — poller survives anything
            self._log.info("router_watchtower_tick_failed", error=str(e))

    def add_alert_rule(self, rule: AlertRule) -> AlertRule:
        """Install one more declarative rule on the fleet engine (takes
        effect on the next poll tick)."""
        return self.alerts.add_rule(rule)

    def publish(self, registry=None) -> dict:
        """Mirror the router counters into the telemetry registry (and
        return the snapshot): ``router_requests_total{role=,replica=}``,
        ``router_kv_migrated_bytes_total``,
        ``router_replica_healthy{replica=}``, redistribution/migration
        totals, the router-level SLO attainment, and each replica's
        attainment re-labeled by replica through its existing
        SloTracker."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        snap = self.metrics.snapshot()
        req = r.gauge(
            "router_requests_total",
            "requests placed by the router, by role and replica",
            labelnames=("role", "replica"),
        )
        for key, n in snap["requests_total"].items():
            role, replica = key.split("/", 1)
            req.labels(role=role, replica=replica).set(float(n))
        r.gauge(
            "router_kv_migrated_bytes_total",
            "serialized KV payload bytes migrated prefill -> decode",
        ).set(float(snap["kv_migrated_bytes_total"]))
        r.gauge(
            "router_migrations_total",
            "KV migrations adopted by decode replicas",
        ).set(float(snap["migrations_total"]))
        r.gauge(
            "router_redistributes_total",
            "in-flight requests redistributed off a failed replica",
        ).set(float(snap["redistributes_total"]))
        r.gauge(
            "router_hedges_total",
            "duplicate prefills fired after the rolling-p99 hedge clock",
        ).set(float(snap["hedges_total"]))
        r.gauge(
            "router_hedge_wins_total",
            "hedged duplicates that beat the primary attempt",
        ).set(float(snap["hedge_wins_total"]))
        r.gauge(
            "router_migrations_corrupt_total",
            "KV migration payloads refused by the CRC32 verify",
        ).set(float(snap["migrations_corrupt_total"]))
        r.gauge(
            "router_shed_total",
            "requests shed by the degradation ladder at the router",
        ).set(float(snap["shed_total"]))
        r.gauge(
            "router_flaps_damped_total",
            "failed health polls absorbed by flap damping",
        ).set(float(snap["flaps_damped_total"]))
        scrape_err = r.gauge(
            "router_replica_scrape_errors_total",
            "federation /metrics scrapes that failed, by replica",
            labelnames=("replica",),
        )
        for name, n in snap["scrape_errors_total"].items():
            scrape_err.labels(replica=name).set(float(n))
        r.gauge(
            "router_incidents_total",
            "incident bundles assembled (throttled triggers excluded)",
        ).set(float(snap["incidents_total"]))
        clock = r.gauge(
            "router_replica_clock_shift_us",
            "per-replica trace-clock shift onto the router's clock "
            "(epoch-exact or NTP-handshake estimate)",
            labelnames=("replica", "method"),
        )
        for name, rep in self._replicas.items():
            shift, method = federation.resolve_clock_shift(
                rep.epoch_shift_us, rep.ntp_shift_us, rep.ntp_rtt_us
            )
            if shift is not None:
                clock.labels(replica=name, method=method).set(shift)
        breaker = r.gauge(
            "router_breaker_state",
            "per-replica circuit breaker (0 closed, 1 half-open, 2 open)",
            labelnames=("replica",),
        )
        for name, rep in self._replicas.items():
            breaker.labels(replica=name).set(float(rep.breaker.gauge_value()))
        self.ladder.publish(r)
        healthy = r.gauge(
            "router_replica_healthy",
            "1 while the replica is placeable, 0 once it left the pool",
            labelnames=("replica",),
        )
        for name, ok in snap["replica_healthy"].items():
            healthy.labels(replica=name).set(float(ok))
        att = r.gauge(
            "router_replica_slo_attainment",
            "per-replica SLO attainment (each replica's own SloTracker)",
            labelnames=("slo", "replica"),
        )
        for name, rep in self._replicas.items():
            rep_snap = rep.server.slo.snapshot()
            for k in ("ttft", "tpot"):
                att.labels(slo=k, replica=name).set(
                    rep_snap["attainment"][k]
                )
        self.slo.publish(r)
        return snap

    # -- fleet observability plane ----------------------------------------
    # (docs/observability.md "Fleet plane": metrics federation, merged
    # cross-process traces, incident bundles.)

    def scrape_metrics(self, force: bool = False) -> None:
        """One federation sweep: fetch each url-replica's raw
        ``/metrics`` text (paced by ``metrics_scrape_interval`` per
        replica unless ``force``).  A failed scrape bumps
        ``router_replica_scrape_errors_total{replica=}`` and keeps the
        last good snapshot — the poller never crashes on a dead
        process."""
        now = time.monotonic()
        for rep in self._replicas.values():
            if not rep.url:
                continue
            if (
                not force
                and now - rep.metrics_scraped_at
                < self.metrics_scrape_interval
            ):
                continue
            rep.metrics_scraped_at = now
            try:
                rep.metrics_text = rep.fetch_metrics_text()
            except Exception as e:  # noqa: BLE001 — scrape is best effort
                self.metrics.record_scrape_error(rep.name)
                self._log.info(
                    "router_metrics_scrape_failed", replica=rep.name,
                    error=str(e),
                )

    def federated_metrics_text(self,
                               base_text: Optional[str] = None) -> str:
        """ONE Prometheus exposition for the whole fleet: the router's
        own registry plus every worker's latest scraped snapshot, each
        worker series re-labeled ``replica=``/``role=``/``generation=``
        (telemetry/federation.py).  Rendering always starts from the
        latest snapshots — replace, never accumulate — so scraping the
        router twice between worker scrapes returns identical bytes
        (no histogram double-counting)."""
        if base_text is None:
            from ml_trainer_tpu.telemetry.registry import default_registry

            registry = default_registry()
            self.publish(registry)
            base_text = registry.prometheus_text()
        sections = []
        for name, rep in sorted(self._replicas.items()):
            if rep.metrics_text is None:
                continue
            sections.append((rep.metrics_text, {
                "replica": name, "role": rep.role,
                "generation": str(rep.generation),
            }))
        return federation.federate_exposition(base_text, sections)

    def fleet_trace(self) -> dict:
        """The merged, clock-aligned Perfetto document: the router's
        own span buffer plus every reachable url-replica's ``GET
        /trace`` payload, each worker lane shifted onto the router's
        trace clock by the health poller's handshake estimates.  An
        unreachable replica is skipped (its lane is simply absent);
        an in-process replica needs no fetch — its spans already live
        in the router's buffer."""
        remotes = []
        for name, rep in sorted(self._replicas.items()):
            if not rep.url:
                continue
            try:
                payload = rep.fetch_trace()
            except Exception:  # noqa: BLE001 — dead process, no lane
                continue
            remotes.append({
                "name": name, "payload": payload,
                "epoch_shift_us": rep.epoch_shift_us,
                "ntp_shift_us": rep.ntp_shift_us,
                "rtt_us": rep.ntp_rtt_us,
            })
        return federation.merge_fleet_trace(
            spans.trace_events(), "router", os.getpid(), remotes
        )

    def save_fleet_trace(self, path: str) -> str:
        """Write :meth:`fleet_trace` as a ``chrome://tracing`` /
        Perfetto JSON file (atomic)."""
        doc = self.fleet_trace()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fp:
            json.dump(doc, fp, default=str)
        os.replace(tmp, path)
        self._log.info(
            "router_fleet_trace_saved", path=path,
            events=len(doc["traceEvents"]),
        )
        return path

    def trigger_incident(self, reason: str,
                         dead: Sequence[str] = ()) -> None:
        """Fire-and-forget incident bundle assembly off the calling
        thread (the poller/kill paths must never block on N replica
        fetches).  Throttled inside :meth:`save_incident_bundle`."""
        threading.Thread(
            target=self._trigger_incident_body, args=(reason, tuple(dead)),
            daemon=True, name="router-incident",
        ).start()

    def _trigger_incident_body(self, reason: str,
                               dead: Tuple[str, ...]) -> None:
        try:
            self.save_incident_bundle(reason, dead=dead)
        except Exception as e:  # noqa: BLE001 — forensics never kill serving
            self._log.error("router_incident_failed", error=str(e))

    def save_incident_bundle(self, reason: str,
                             dead: Sequence[str] = (),
                             out_dir: Optional[str] = None,
                             force: bool = False) -> Optional[str]:
        """Assemble ``incident_<ts>_<pid>/`` — everything a post-mortem
        needs, captured while the fleet's state is still warm:

        * ``flight_router.json`` — the router process's flight payload;
        * ``flight_<replica>.json`` — each reachable url-replica's live
          flight payload (``GET /flight``; a dead process is skipped);
        * ``slo_timelines.json`` — the router tracker's last retained
          per-request timelines;
        * ``metrics.prom`` / ``router.json`` — the federated exposition
          and the router snapshot at capture time;
        * ``stderr_<replica>.txt`` — the dead workers' combined
          stdout+stderr tails (fleet workers only);
        * ``manifest.json`` — reason, trigger set, fleet health, files.

        Bundles are throttled (``incident_min_interval_s``) unless
        ``force`` — a flapping replica must not write one per poll.
        Directory resolves: ``out_dir`` arg, router ``incident_dir``,
        ``ML_TRAINER_TPU_INCIDENT_DIR``, the system temp dir.  Returns
        the bundle path, or None when throttled."""
        now = time.monotonic()
        with self._incident_lock:
            if (
                not force
                and now - self._last_incident_at
                < self.incident_min_interval_s
                and self._last_incident_at > 0.0
            ):
                return None
            self._last_incident_at = now
        d = (
            out_dir or self.incident_dir
            or os.environ.get(INCIDENT_DIR_ENV)
            or tempfile.gettempdir()
        )
        stem = os.path.join(
            d,
            f"incident_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}",
        )
        # Two incidents inside one wall-clock second (e.g. a forced
        # bundle right after a triggered one) must not overwrite each
        # other: uniquify with a suffix.
        bundle, n = stem, 1
        while True:
            try:
                os.makedirs(bundle, exist_ok=False)
                break
            except FileExistsError:
                bundle = f"{stem}_{n}"
                n += 1
        files: List[str] = []

        def _write(name: str, payload) -> None:
            try:
                path = os.path.join(bundle, name)
                with open(path, "w", encoding="utf-8") as fp:
                    if isinstance(payload, str):
                        fp.write(payload)
                    else:
                        json.dump(payload, fp, default=str)
                files.append(name)
            except Exception as e:  # noqa: BLE001 — partial bundle > none
                self._log.info(
                    "router_incident_artifact_failed", artifact=name,
                    error=str(e),
                )

        _write(
            "flight_router.json",
            get_recorder().payload(f"incident: {reason}"),
        )
        replica_flights: List[str] = []
        for name, rep in sorted(self._replicas.items()):
            try:
                payload = rep.fetch_flight()
            except Exception:  # noqa: BLE001 — dead process
                continue
            if payload is not None:
                _write(f"flight_{name}.json", payload)
                replica_flights.append(name)
        _write("slo_timelines.json", self.slo.timelines())
        _write("metrics.prom", self.federated_metrics_text())
        _write("router.json", self.snapshot())
        # Watchtower: the dashboard at capture time (the trend INTO the
        # incident, not just the instant) plus the full alert history.
        _write("dashboard.html", render_dashboard(
            self.watchtower, title=f"incident: {reason}",
            alerts=self.alerts.history(),
        ))
        _write("alerts.json", self.alerts.payload())
        for name in dead:
            rep = self._replicas.get(name)
            tail_fn = getattr(
                getattr(rep, "server", None), "stderr_tail", None
            )
            if tail_fn is None:
                continue
            try:
                tail = tail_fn()
            except Exception:  # noqa: BLE001
                tail = None
            if tail:
                _write(f"stderr_{name}.txt", tail)
        _write("manifest.json", {
            "reason": reason,
            "created_at": time.time(),
            "dead": list(dead),
            "replica_flights": replica_flights,
            "health": self.health(),
            "files": sorted(files),
        })
        self.metrics.record_incident()
        get_recorder().record(
            "incident_bundle", reason=reason, path=bundle,
            files=len(files),
        )
        self._log.error(
            "router_incident_bundle", reason=reason, path=bundle,
            files=sorted(files),
        )
        with self._incident_lock:
            self.last_incident_path = bundle
        return bundle

    # -- HTTP front end ---------------------------------------------------

    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """The router's stdlib HTTP front end (same contract as
        ``Server.serve_http``): POST ``/v1/generate`` (plus an optional
        ``"session"`` key for stickiness), GET ``/healthz`` /
        ``/metrics`` / ``/metrics.json`` / ``/slo``."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ml_trainer_tpu.serving.scheduler import DeadlineExceeded

        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: we have metrics
                pass

            def _send(self, code: int, payload: dict,
                      retry_after: Optional[float] = None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header(
                        "Retry-After",
                        str(max(1, int(round(retry_after)))),
                    )
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    payload = router.health()
                    self._send(200 if payload["ok"] else 503, payload)
                elif self.path == "/metrics":
                    # The FEDERATED exposition: the router's own
                    # registry plus every worker's latest scraped
                    # snapshot re-labeled replica=/role=/generation= —
                    # one scrape covers the whole fleet.
                    body = router.federated_metrics_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics.json":
                    self._send(200, router.snapshot())
                elif self.path == "/trace":
                    # The merged clock-aligned fleet timeline (load it
                    # straight into Perfetto / chrome://tracing).
                    self._send(200, router.fleet_trace())
                elif self.path == "/slo":
                    self._send(200, router.slo.snapshot())
                elif self.path == "/dash":
                    # Fleet-wide live dashboard: the router's TSDB holds
                    # every replica's series (replica=/role= labels) so
                    # one page shows the whole fleet's trends.
                    body = render_dashboard(
                        router.watchtower, title="router",
                        alerts=router.alerts.history(),
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/html; charset=utf-8"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/generate":
                    self._send(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    session = body.get("session")
                    deadline = body.get("deadline")
                    stream = router.submit(
                        np.asarray(body["prompt"], np.int32),
                        int(body.get("max_new_tokens", 16)),
                        temperature=float(body.get("temperature", 0.0)),
                        rng=body.get("seed"),
                        eos_token_id=body.get("eos_token_id"),
                        deadline=deadline,
                        tenant=str(body.get("tenant", "default")),
                        priority=int(body.get("priority", 0)),
                        session=str(session) if session else None,
                        adapter=body.get("adapter"),
                        trace=_trace_ctx_header(self.headers),
                    )
                    # The HTTP wait is capped by the client's own
                    # deadline (plus routing slack): a deadline'd
                    # request gets a timely 504, and the remaining
                    # budget decrements across every redistribute
                    # and hedge inside the router.
                    out = stream.result(timeout=(
                        float(deadline) + 30.0
                        if deadline is not None else None
                    ))
                    self._send(200, {
                        "tokens": [int(t) for t in out],
                        # Which replica actually served the decode —
                        # the last migration/placement mark on the
                        # request's event log (loadgen attributes its
                        # latency rows by this).
                        "replica": router._serving_replica(stream._req),
                    })
                except OverloadShed as e:
                    payload = {"error": str(e)}
                    if e.retry_after is not None:
                        payload["retry_after"] = e.retry_after
                    self._send(503, payload, retry_after=e.retry_after)
                except AdmissionError as e:
                    self._send(429, {"error": str(e)})
                except EngineUnhealthy as e:
                    self._send(503, {"error": str(e)})
                except (DeadlineExceeded, TimeoutError) as e:
                    self._send(504, {"error": str(e)})
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                except RuntimeError as e:
                    # Structured terminal errors (redistribution budget
                    # exhausted, engine give-ups) reach the client as
                    # JSON, never a stdlib 500 HTML page.
                    self._send(503, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http",
        )
        self._http_thread.start()
        return self._httpd.server_address
