"""SLO-burn autoscaler: the control loop that acts on the serving fleet.

The serving stack can now SEE overload (serving/slo.py publishes
attainment and burn rate; ``docs/serving_slo_cpu.json`` shows attainment
collapsing 1.0 -> 0.33 past the knee) — this module is the loop that
DOES something about it (ROADMAP items 2/3; the Gemma-on-TPU serving
paper's SLO/cost framing, PAPERS.md arXiv 2605.25645):

* **Signals.**  Each poll reads the router's windowed request timelines
  (TTFT burn rate over the last ``window_s`` — lifetime attainment is
  useless for control, old requests dominate it), per-role queue depth
  and free-KV pressure from the replicas' ``/healthz``/registry
  surfaces, and fleet liveness.

* **Actions**, in preference order when burn is high (every action a
  flight event + ``autoscaler_actions_total{action=}``):

  1. **Replace the dead** — a replica death drops the fleet below its
     role floor: add a replacement immediately (short cooldown, no
     hysteresis — this is repair, not scaling).
  2. **Scale up** — add an in-process ``Server`` replica (the
     ``Router.build`` idiom: same model/params, shared compile cache,
     so capacity arrives WITHOUT minting compiles) on the pressured
     role, bounded by ``max_replicas``.
  3. **Reassign roles** — when one role starves while the other idles
     (queue-pressure imbalance past ``imbalance_ratio``), flip an idle
     replica prefill<->decode by draining it through the PR 13
     migration machinery (``Router.reassign_role``: active KV exported
     page-granular and adopted elsewhere — streams keep flowing).
  4. **Degrade** — at ``max_replicas`` with burn still high, step the
     graceful-degradation ladder UP (serving/overload.py): clamp, spec
     off, hits-only, shed.  Brownout beats blackout.

  When burn stays low the loop walks back down: ladder rungs exit
  first, then surplus replicas drain and leave (never below the
  floors).

* **Hysteresis + cooldown.**  Burn must stay high/low for
  ``high_polls``/``low_polls`` CONSECUTIVE polls before any action, and
  ``cooldown_s`` must elapse between actions, so the loop never flaps —
  an autoscaler that oscillates is worse than none.

Host-only module: no jax — the servers own every device interaction.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional

from ml_trainer_tpu.serving.slo import aggregate_timelines
from ml_trainer_tpu.telemetry.alerts import AlertEngine, AlertRule
from ml_trainer_tpu.utils.logging import get_logger


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Control-loop knobs (hysteresis is the point: every threshold has
    a consecutive-poll requirement and every action a cooldown)."""

    poll_interval_s: float = 0.5
    window_s: float = 8.0            # burn measured over this window
    min_window_requests: int = 6     # below this the burn signal is noise
    burn_high: float = 2.0           # act when TTFT burn >= this...
    high_polls: int = 2              # ...for this many consecutive polls
    burn_low: float = 0.25           # recover when burn <= this...
    low_polls: int = 6               # ...for this many consecutive polls
    cooldown_s: float = 4.0          # between scale/flip/rung actions
    replace_cooldown_s: float = 1.0  # dead-replica repair is urgent
    max_replicas: int = 8
    min_prefill: int = 1             # role floors (disagg fleets)
    min_decode: int = 1
    min_replicas: int = 2            # total floor (colocated fleets)
    imbalance_ratio: float = 3.0     # queue-pressure ratio for a role flip
    role_flip: bool = True
    scale_down: bool = True

    def __post_init__(self):
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s must be > 0, got {self.poll_interval_s}"
            )
        if self.burn_high <= self.burn_low:
            raise ValueError(
                f"burn_high ({self.burn_high}) must exceed burn_low "
                f"({self.burn_low}) — the hysteresis band"
            )
        if self.high_polls < 1 or self.low_polls < 1:
            raise ValueError("high_polls/low_polls must be >= 1")


class Autoscaler:
    """The fleet control loop over a :class:`~...router.Router`.

    ``server_factory(role) -> Server`` builds a replica with the
    fleet's geometry (share the model/params so the compile cache
    covers the newcomer — ``Router.build``'s arrangement).  Use as a
    context manager, or ``start()``/``close()``.  ``tick()`` runs one
    control decision synchronously (tests drive it with a fake clock;
    the thread just calls it on a timer)."""

    def __init__(self, router, server_factory: Callable,
                 config: Optional[AutoscalerConfig] = None,
                 clock=time.monotonic):
        self.router = router
        self.factory = server_factory
        self.config = config if config is not None else AutoscalerConfig()
        self.ladder = router.ladder
        self._clock = clock
        self._log = get_logger("ml_trainer_tpu.serving.autoscaler")
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_action_at = -10.0 ** 9
        self._auto_seq = 0
        self.actions: List[dict] = []
        self.last_burn: Optional[float] = None
        # The hysteresis streaks, re-expressed as for_count alert rules
        # on the fleet's AlertEngine (ONE alerting path): the high/low
        # rules carry the consecutive-poll state the loop used to keep
        # by hand, firing = streak reached, and the post-action streak
        # reset is rule.reset().  Cooldown gating stays OUT here — a
        # rule keeps firing through a cooldown, exactly as the streak
        # kept growing.
        engine = getattr(router, "alerts", None)
        if engine is None:
            engine = AlertEngine(clock=self._clock)
        self.alerts = engine
        cfg = self.config
        self._rule_high = engine.add_rule(AlertRule(
            "autoscaler_burn_high", for_count=cfg.high_polls,
            severity="warn",
            description=(
                f"windowed TTFT burn >= {cfg.burn_high} for "
                f"{cfg.high_polls} consecutive polls"
            ),
        ))
        self._rule_low = engine.add_rule(AlertRule(
            "autoscaler_burn_low", for_count=cfg.low_polls,
            severity="info",
            description=(
                f"windowed TTFT burn <= {cfg.burn_low} for "
                f"{cfg.low_polls} consecutive polls (recovery)"
            ),
        ))

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="autoscaler"
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self._log.error("autoscaler_error", error=f"{e}")
            self._stop.wait(self.config.poll_interval_s)

    # -- signals ----------------------------------------------------------

    def _fleet(self) -> dict:
        """One poll's fleet view: alive replicas by capability, queue
        pressure by role, and the windowed TTFT burn (None while the
        window holds too few requests to mean anything)."""
        reps = list(self.router.replicas.values())
        alive = [r for r in reps if r.healthy and not r.removing]
        prefill = [r for r in alive if r.role in ("prefill", "both")]
        decode = [r for r in alive if r.role in ("decode", "both")]

        def _pressure(pool):
            return sum(
                int((r.last_health or {}).get("queue_depth") or 0)
                + int((r.last_health or {}).get("active_slots") or 0)
                for r in pool
            )

        now = self._clock()
        tls = self.router.slo.timelines(
            since=time.monotonic() - self.config.window_s
        )
        burn = None
        if len(tls) >= self.config.min_window_requests:
            agg = aggregate_timelines(tls, self.router.slo.policy)
            burn = agg["burn_rate"]["ttft"]
        self.last_burn = burn
        return {
            "now": now,
            "alive": alive,
            "total": len(alive),
            "prefill": prefill,
            "decode": decode,
            "prefill_pressure": _pressure(prefill),
            "decode_pressure": _pressure(decode),
            "burn": burn,
            "window_requests": len(tls),
        }

    # -- actions ----------------------------------------------------------

    def _record(self, action: str, cause: str, **extra) -> None:
        row = {
            "t": round(self._clock(), 3), "action": action,
            "cause": cause, **extra,
        }
        with self._lock:
            self.actions.append(row)
        from ml_trainer_tpu.telemetry.flight import get_recorder

        get_recorder().record("autoscaler", **row)
        self._log.info("autoscaler_action", **row)

    def _cooldown_ok(self, now: float, repair: bool = False) -> bool:
        gap = (
            self.config.replace_cooldown_s if repair
            else self.config.cooldown_s
        )
        return now - self._last_action_at >= gap

    def _dead_stderr(self) -> dict:
        """Bounded log tails of replicas whose worker PROCESS died after
        the readiness handshake ({name: tail}).  A post-ready crash
        loses its stderr otherwise — the process is gone, the socket
        just sever — so the replace-dead flight event carries the
        post-mortem (RemoteServer.stderr_tail; in-process replicas have
        no process to lose)."""
        tails = {}
        for rep in self.router.replicas.values():
            if rep.healthy:
                continue
            proc = getattr(rep.server, "proc", None)
            tail_fn = getattr(rep.server, "stderr_tail", None)
            if proc is None or tail_fn is None:
                continue
            if proc.poll() is None:  # still running: unhealthy != dead
                continue
            tail = tail_fn()
            if tail:
                tails[rep.name] = tail[-2048:]
        return tails

    def _scale_up(self, role: str, cause: str, now: float,
                  repair: bool = False) -> bool:
        self._auto_seq += 1
        name = f"auto{self._auto_seq}"
        extra = {}
        if repair:
            dead = self._dead_stderr()
            if dead:
                extra["dead_stderr"] = dead
            # Replace-dead is an incident: bundle the fleet's state
            # (throttled router-side) before the repair muddies it.
            trigger = getattr(self.router, "trigger_incident", None)
            if trigger is not None:
                try:
                    trigger(
                        f"autoscaler_replace_dead: {cause}",
                        dead=tuple(dead) if dead else (),
                    )
                except Exception:  # noqa: BLE001
                    pass
        try:
            server = self.factory(role)
            self.router.add_replica(name, server)
        except Exception as e:  # noqa: BLE001 — a failed add is an event
            self._record("scale_up_failed", f"{cause}: {e}", role=role,
                         **extra)
            return False
        self._last_action_at = now
        self._record("scale_up", cause, role=role, replica=name, **extra)
        return True

    def _scale_down(self, fleet: dict, cause: str, now: float) -> bool:
        cfg = self.config
        # Remove from the LESS pressured role, keeping the floors; the
        # least-loaded removable replica drains and leaves.
        candidates = []
        if self.router.mode == "disagg":
            if len(fleet["prefill"]) > cfg.min_prefill:
                candidates += [
                    r for r in fleet["prefill"] if r.role == "prefill"
                ]
            if len(fleet["decode"]) > cfg.min_decode:
                candidates += [
                    r for r in fleet["decode"] if r.role == "decode"
                ]
        elif fleet["total"] > cfg.min_replicas:
            candidates = list(fleet["alive"])
        if not candidates or fleet["total"] <= 1:
            return False
        victim = sorted(candidates, key=lambda r: r.load_score())[0]
        self._last_action_at = now
        drained = self.router.remove_replica(victim.name, timeout=20.0)
        self._record(
            "scale_down", cause, replica=victim.name, role=victim.role,
            drained=drained,
        )
        return True

    def _maybe_flip_role(self, fleet: dict, cause: str,
                         now: float) -> bool:
        """Queue-pressure imbalance: flip an idle replica onto the
        starving role (drain-through-migration first)."""
        cfg = self.config
        if not cfg.role_flip or self.router.mode != "disagg":
            return False
        pp, dp = fleet["prefill_pressure"], fleet["decode_pressure"]
        pure_prefill = [r for r in fleet["prefill"] if r.role == "prefill"]
        pure_decode = [r for r in fleet["decode"] if r.role == "decode"]
        if (
            pp >= cfg.imbalance_ratio * max(dp, 1)
            and len(pure_decode) > cfg.min_decode
        ):
            victim = sorted(pure_decode, key=lambda r: r.load_score())[0]
            new_role = "prefill"
        elif (
            dp >= cfg.imbalance_ratio * max(pp, 1)
            and len(pure_prefill) > cfg.min_prefill
        ):
            victim = sorted(pure_prefill, key=lambda r: r.load_score())[0]
            new_role = "decode"
        else:
            return False
        self._last_action_at = now
        ok = self.router.reassign_role(victim.name, new_role, timeout=20.0)
        self._record(
            "reassign_role" if ok else "reassign_role_failed", cause,
            replica=victim.name, role=new_role,
            prefill_pressure=pp, decode_pressure=dp,
        )
        return ok

    # -- the control decision ---------------------------------------------

    def tick(self) -> Optional[str]:
        """One control decision; returns the action taken (or None).
        Thread-safe with the router's own machinery; tests call it
        directly."""
        cfg = self.config
        fleet = self._fleet()
        now = fleet["now"]

        # 1. Repair: a death dropped a role below its floor.  No
        # hysteresis — waiting out a burn window while a quarter of the
        # fleet is missing just burns more budget.
        if self._cooldown_ok(now, repair=True):
            if self.router.mode == "disagg":
                if len(fleet["decode"]) < cfg.min_decode:
                    if self._scale_up(
                        "decode", "decode fleet below floor "
                        f"({len(fleet['decode'])} < {cfg.min_decode})",
                        now, repair=True,
                    ):
                        return "scale_up"
                if len(fleet["prefill"]) < cfg.min_prefill:
                    if self._scale_up(
                        "prefill", "prefill fleet below floor "
                        f"({len(fleet['prefill'])} < {cfg.min_prefill})",
                        now, repair=True,
                    ):
                        return "scale_up"
            elif fleet["total"] < cfg.min_replicas:
                if self._scale_up(
                    "both", f"fleet below floor ({fleet['total']} < "
                    f"{cfg.min_replicas})", now, repair=True,
                ):
                    return "scale_up"

        burn = fleet["burn"]
        if burn is None:
            return None  # too few requests: rules hold, nothing observed
        extra = {"window_requests": fleet["window_requests"]}
        high_firing = low_firing = False
        if burn >= cfg.burn_high:
            high_firing = self.alerts.observe(
                "autoscaler_burn_high", True, now=now, value=burn,
                extra=extra,
            )
            self.alerts.observe(
                "autoscaler_burn_low", False, now=now, value=burn,
            )
        elif burn <= cfg.burn_low:
            self.alerts.observe(
                "autoscaler_burn_high", False, now=now, value=burn,
            )
            low_firing = self.alerts.observe(
                "autoscaler_burn_low", True, now=now, value=burn,
                extra=extra,
            )
        else:
            # Inside the hysteresis band: streaks decay, nothing acts.
            self.alerts.observe(
                "autoscaler_burn_high", False, now=now, value=burn,
            )
            self.alerts.observe(
                "autoscaler_burn_low", False, now=now, value=burn,
            )
            return None

        cause = (
            f"ttft burn {burn} over {fleet['window_requests']} request(s)"
        )
        if high_firing and self._cooldown_ok(now):
            if fleet["total"] < cfg.max_replicas:
                role = "both"
                if self.router.mode == "disagg":
                    role = (
                        "prefill"
                        if fleet["prefill_pressure"]
                        >= fleet["decode_pressure"] else "decode"
                    )
                if self._scale_up(role, cause, now):
                    self._rule_high.reset()
                    return "scale_up"
            if self._maybe_flip_role(fleet, cause, now):
                self._rule_high.reset()
                return "reassign_role"
            # No capacity to add: brownout beats blackout.
            if self.ladder.level < 4:
                self._last_action_at = now
                self.ladder.step_up(cause)
                self._record(
                    "degrade", cause, level=self.ladder.level,
                    rung=self.ladder.rung,
                )
                self._rule_high.reset()
                return "degrade"
            return None
        if low_firing and self._cooldown_ok(now):
            recovery = f"ttft burn {burn} (recovered)"
            if self.ladder.level > 0:
                self._last_action_at = now
                self.ladder.step_down(recovery)
                self._record(
                    "undegrade", recovery, level=self.ladder.level,
                    rung=self.ladder.rung,
                )
                self._rule_low.reset()
                return "undegrade"
            if cfg.scale_down and self._scale_down(fleet, recovery, now):
                self._rule_low.reset()
                return "scale_down"
        return None

    # -- reading ----------------------------------------------------------

    def summary(self) -> dict:
        """The ``run_report``-style section the bench artifact embeds:
        every action with its cause, plus per-action counts."""
        with self._lock:
            actions = [dict(a) for a in self.actions]
        counts: dict = {}
        for a in actions:
            counts[a["action"]] = counts.get(a["action"], 0) + 1
        return {
            "actions": actions,
            "counts": counts,
            "last_burn": self.last_burn,
            "ladder": self.ladder.snapshot(),
        }

    def publish(self, registry=None) -> None:
        """``autoscaler_actions_total{action=}`` +
        ``autoscaler_replicas{role=}`` + the burn the loop last saw."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        counts = self.summary()["counts"]
        g = r.gauge(
            "autoscaler_actions_total",
            "autoscaler control actions, by kind",
            labelnames=("action",),
        )
        for action, n in sorted(counts.items()):
            g.labels(action=action).set(float(n))
        reps = r.gauge(
            "autoscaler_replicas",
            "alive replicas by role capability",
            labelnames=("role",),
        )
        alive = [
            rep for rep in self.router.replicas.values()
            if rep.healthy and not rep.removing
        ]
        reps.labels(role="prefill").set(float(sum(
            1 for rep in alive if rep.role in ("prefill", "both")
        )))
        reps.labels(role="decode").set(float(sum(
            1 for rep in alive if rep.role in ("decode", "both")
        )))
        if self.last_burn is not None:
            r.gauge(
                "autoscaler_last_burn",
                "windowed TTFT burn rate the control loop last measured",
            ).set(float(self.last_burn))
