"""Open-loop load generation for the serving stack.

A CLOSED-loop client (send, wait for the reply, send the next) measures
a different system than production traffic does: when the server slows
down, a closed-loop client slows its own arrivals, so the latency
numbers silently exclude exactly the overload the test was supposed to
find — **coordinated omission**.  This module generates OPEN-loop load:
the arrival schedule is fixed BEFORE the run (every request has an
absolute send time drawn from a Poisson process or replayed from a
recorded trace), and requests fire at their scheduled instant whether
or not earlier ones completed.  Queueing delay under saturation then
lands in the measured latencies instead of vanishing into the
generator.

Pieces:

* :class:`TenantLoad` — one tenant's traffic shape: arrival share,
  prompt/output length distributions, optional shared prefix (system
  prompt) so the prefix cache sees production-shaped reuse;
* :func:`poisson_schedule` — a seeded, deterministic schedule (same
  seed => byte-identical prompts and arrival times, test-pinned);
* :func:`schedule_from_trace` / :func:`schedule_to_records` — recorded
  traces as plain JSON-safe records, replayable as a schedule;
* :func:`run_open_loop` — drive the real HTTP ``Server`` (or the
  in-process API) to the schedule: one dispatcher thread sleeps to each
  absolute arrival and hands the request to a worker thread; results
  report client-side latency, scheduling fidelity (how late sends
  actually fired) and error counts.

Host-only module: no jax — prompts are numpy token ids, the server owns
every device interaction.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class TenantLoad:
    """One tenant's share of the offered load.

    ``weight``: fraction of arrivals (normalized across tenants).
    ``prompt_len``/``output_len``: inclusive ``(lo, hi)`` uniform
    ranges.  ``shared_prefix_len`` > 0 prepends a tenant-wide shared
    prefix (drawn once per schedule from the seed) to ``shared_frac``
    of the tenant's prompts — the system-prompt reuse pattern the radix
    prefix cache exists for.  ``adapters`` names the tenant's LoRA
    adapter mix (docs/serving.md "Batched LoRA adapters"): each request
    draws one entry uniformly — include ``None`` entries for base-model
    traffic interleaved with adapter traffic.  The draw rides the
    recorded trace, so a replay drives the same adapter per request."""

    weight: float = 1.0
    prompt_len: tuple = (8, 24)
    output_len: tuple = (4, 16)
    shared_prefix_len: int = 0
    shared_frac: float = 0.0
    adapters: tuple = ()

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        for name, rng in (("prompt_len", self.prompt_len),
                          ("output_len", self.output_len)):
            lo, hi = rng
            if lo < 1 or hi < lo:
                raise ValueError(f"{name} must be (lo>=1, hi>=lo), got {rng}")
        if not 0.0 <= self.shared_frac <= 1.0:
            raise ValueError(
                f"shared_frac must be in [0, 1], got {self.shared_frac}"
            )
        for a in self.adapters:
            if a is not None and (not isinstance(a, str) or not a):
                raise ValueError(
                    f"adapters entries must be names or None, got {a!r}"
                )


@dataclasses.dataclass
class ScheduledRequest:
    """One arrival in the fixed open-loop schedule.  ``session`` rides
    through to the router's sticky decode placement (ignored by a
    single-replica server — the field exists so ONE recorded trace can
    drive both topologies)."""

    arrival_s: float           # absolute offset from the run's t0
    tenant: str
    prompt: np.ndarray         # int32 token ids
    max_new_tokens: int
    session: Optional[str] = None
    adapter: Optional[str] = None  # LoRA adapter (None = base model)


def poisson_schedule(rate_rps: float, n_requests: int, vocab_size: int,
                     tenants: Optional[Dict[str, TenantLoad]] = None,
                     seed: int = 0) -> List[ScheduledRequest]:
    """A deterministic open-loop schedule: ``n_requests`` Poisson
    arrivals at ``rate_rps`` requests/second, tenants drawn by weight,
    prompts/budgets by each tenant's distributions.  The same seed
    yields a byte-identical schedule (test-pinned) — the property that
    makes a load sweep comparable across engines and rounds."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    tenants = tenants or {"default": TenantLoad()}
    rng = np.random.default_rng(seed)
    names = sorted(tenants)
    weights = np.asarray([tenants[n].weight for n in names], np.float64)
    weights = weights / weights.sum()
    # Tenant-wide shared prefixes, drawn once (stable within a seed).
    prefixes = {
        n: rng.integers(
            0, vocab_size, tenants[n].shared_prefix_len
        ).astype(np.int32)
        for n in names if tenants[n].shared_prefix_len > 0
    }
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, n_requests))
    out: List[ScheduledRequest] = []
    for i in range(n_requests):
        name = names[int(rng.choice(len(names), p=weights))]
        cfg = tenants[name]
        p_lo, p_hi = cfg.prompt_len
        o_lo, o_hi = cfg.output_len
        prompt = rng.integers(
            0, vocab_size, int(rng.integers(p_lo, p_hi + 1))
        ).astype(np.int32)
        if name in prefixes and rng.random() < cfg.shared_frac:
            prompt = np.concatenate([prefixes[name], prompt])
        adapter = None
        if cfg.adapters:
            adapter = cfg.adapters[int(rng.integers(len(cfg.adapters)))]
        out.append(ScheduledRequest(
            arrival_s=float(arrivals[i]), tenant=name, prompt=prompt,
            max_new_tokens=int(rng.integers(o_lo, o_hi + 1)),
            adapter=adapter,
        ))
    return out


def schedule_to_records(schedule: Sequence[ScheduledRequest]) -> list:
    """JSON-safe records of a schedule (a recorded trace)."""
    return [
        {
            "arrival_s": round(s.arrival_s, 6),
            "tenant": s.tenant,
            "prompt": [int(t) for t in s.prompt],
            "max_new_tokens": s.max_new_tokens,
            **({"session": s.session} if s.session else {}),
            **({"adapter": s.adapter} if s.adapter else {}),
        }
        for s in schedule
    ]


def schedule_from_trace(records) -> List[ScheduledRequest]:
    """A schedule from recorded-trace records — the list
    :func:`schedule_to_records` emits, or a path to a JSON file of it.
    Replay keeps the original absolute arrival offsets, so a production
    trace drives the harness with its real burstiness."""
    if isinstance(records, str):
        with open(records, encoding="utf-8") as fp:
            records = json.load(fp)
    out = []
    for r in records:
        out.append(ScheduledRequest(
            arrival_s=float(r["arrival_s"]),
            tenant=str(r.get("tenant", "default")),
            prompt=np.asarray(r["prompt"], np.int32),
            max_new_tokens=int(r["max_new_tokens"]),
            session=r.get("session"),
            adapter=r.get("adapter"),
        ))
    out.sort(key=lambda s: s.arrival_s)
    return out


def _percentile_ms(sorted_s: list, q: float) -> float:
    if not sorted_s:
        return 0.0
    i = min(len(sorted_s) - 1, int(q * (len(sorted_s) - 1) + 0.5))
    return round(sorted_s[i] * 1e3, 3)


def run_open_loop(schedule: Sequence[ScheduledRequest],
                  url: Optional[str] = None, server=None,
                  timeout: float = 300.0,
                  time_scale: float = 1.0,
                  collect_tokens: bool = False) -> dict:
    """Fire ``schedule`` open-loop at the real server and report.

    ``url`` is the explicit TARGET — a single replica's front end or
    the disaggregated router's, interchangeably (POST
    ``{url}/v1/generate`` per request: the full production path — JSON
    parse, admission/routing, engine, response), which is what lets
    ``bench.py --slo``/``--serve-disagg`` drive both topologies with
    the same recorded trace; ``server`` drives the in-process API
    (tests).  Exactly one must be given.  A dispatcher thread sleeps to
    each ABSOLUTE scheduled arrival and hands the request to its own
    worker thread — completions never gate arrivals (no coordinated
    omission), and the report's ``send_lag_ms`` records how faithfully
    the schedule fired.  ``time_scale`` stretches (>1) or compresses
    (<1) the schedule's arrival offsets without touching its content.
    ``collect_tokens`` keeps each request's full output ids on its
    per-request row — the byte-identity evidence a topology comparison
    needs."""
    if (url is None) == (server is None):
        raise ValueError("exactly one of url/server must be given")
    results = [None] * len(schedule)

    def _worker(i: int, s: ScheduledRequest, scheduled_at: float):
        sent_at = time.monotonic()
        row = {
            "tenant": s.tenant,
            **({"adapter": s.adapter} if s.adapter else {}),
            "scheduled_s": round(s.arrival_s * time_scale, 6),
            "send_lag_ms": round((sent_at - scheduled_at) * 1e3, 3),
            "ok": False, "error": None, "tokens": 0,
        }
        try:
            if url is not None:
                payload = {
                    "prompt": [int(t) for t in s.prompt],
                    "max_new_tokens": s.max_new_tokens,
                    "tenant": s.tenant,
                }
                if s.session:
                    payload["session"] = s.session
                if s.adapter:
                    payload["adapter"] = s.adapter
                body = json.dumps(payload).encode()
                req = urllib.request.Request(
                    f"{url}/v1/generate", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    out = json.loads(resp.read())
                row["tokens"] = len(out["tokens"]) - s.prompt.size
                # Which replica served the decode (router replies name
                # it; a single-replica server replies its own name or
                # None) — per-row attribution for fleet debugging.
                row["replica"] = out.get("replica")
                if collect_tokens:
                    row["output"] = [int(t) for t in out["tokens"]]
            else:
                out = server.complete(
                    s.prompt, s.max_new_tokens, tenant=s.tenant,
                    timeout=timeout,
                    **({"adapter": s.adapter} if s.adapter else {}),
                )
                row["tokens"] = int(np.asarray(out).size - s.prompt.size)
                if collect_tokens:
                    row["output"] = [int(t) for t in np.asarray(out)]
            row["ok"] = True
        except urllib.error.HTTPError as e:
            # A STRUCTURED refusal (shed/backpressure/deadline) carries
            # a JSON body naming the cause — keep it, plus the status
            # and retry_after, so the chaos harness can prove every
            # failed request got a structured error, not a hang or a
            # stdlib HTML page.
            row["status"] = e.code
            try:
                body = json.loads(e.read())
                row["error"] = body.get("error") or f"HTTP {e.code}"
                if "retry_after" in body:
                    row["retry_after"] = body["retry_after"]
                row["structured"] = bool(body.get("error"))
            except Exception:
                row["error"] = f"HTTPError: HTTP {e.code}"
                row["structured"] = False
        except Exception as e:  # the harness reports failures, it
            row["error"] = f"{type(e).__name__}: {e}"  # never dies on one
        row["latency_s"] = round(time.monotonic() - sent_at, 6)
        results[i] = row

    threads = []
    t0 = time.monotonic()
    for i, s in enumerate(schedule):
        target = t0 + s.arrival_s * time_scale
        wait = target - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        th = threading.Thread(
            target=_worker, args=(i, s, target), daemon=True,
            name=f"loadgen-{i}",
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout)
    makespan = time.monotonic() - t0
    done = [r for r in results if r is not None]
    ok = [r for r in done if r["ok"]]
    lat = sorted(r["latency_s"] for r in ok)
    total_tokens = sum(r["tokens"] for r in ok)
    return {
        "n_scheduled": len(schedule),
        "n_completed": len(ok),
        "n_errors": len(done) - len(ok),
        "errors": sorted({r["error"] for r in done if r["error"]})[:4],
        "makespan_s": round(makespan, 3),
        "offered_rps": round(
            len(schedule) / (schedule[-1].arrival_s * time_scale), 3
        ) if schedule and schedule[-1].arrival_s * time_scale > 0 else None,
        "tokens_per_sec": round(total_tokens / makespan, 1)
        if makespan > 0 else 0.0,
        "useful_tokens": total_tokens,
        "client_e2e_p50_ms": _percentile_ms(lat, 0.5),
        "client_e2e_p99_ms": _percentile_ms(lat, 0.99),
        "send_lag_p99_ms": _percentile_ms(
            sorted(r["send_lag_ms"] / 1e3 for r in done), 0.99
        ),
        "per_request": done,
    }
