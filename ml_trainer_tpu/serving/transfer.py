"""Block-granular KV migration between engine replicas.

Disaggregated serving (serving/router.py, docs/serving.md) runs a
request's prefill on one replica and its decode on another, which means
the request's KV cache must MOVE between two :class:`KVPagePool`-backed
engines mid-lifecycle.  This module is that transfer unit:

* **Export** (:func:`export_kv_slot`): gather one slot's page chain out
  of every layer's page pool — in LOGICAL order, padded to
  ``pages_per_slot`` rows so the gather/scatter programs compile ONCE
  per engine geometry (pad rows index the trash page on both sides, the
  same harmless-garbage idiom the device decode path already relies
  on) — plus the host continuation state the target engine needs to
  keep sampling byte-identically: consumed position, committed tokens,
  the pending input token, temperature, the normalized rng key and the
  per-token fold counter.
* **Import** (:func:`import_kv_slot`): allocate the same number of
  pages in the TARGET pool, bind the slot, scatter the exported rows in
  bit-for-bit, set the slot's index/token rows and host mirrors, and
  register the request as active — the target's next decode step
  continues exactly where the source's would have.  The migrated
  prompt's full blocks are donated to the target's prefix cache through
  the same radix-insert machinery that moves written blocks between
  owners on preemption, so affinity-routed followers hit on the decode
  side too.
* **Serialization** (:func:`to_bytes` / :func:`from_bytes`): the export
  as one self-describing byte payload (``np.savez`` + JSON meta), so
  the transfer is transport-ready (HTTP/IPC) and the router can meter
  ``router_kv_migrated_bytes_total`` honestly.

Byte-identity argument: the paged engine's prefill scatter-inserts the
CONTIGUOUS batch-1 prefill cache into pages bit-for-bit (the PR6
anchor), and this module copies those same page contents bit-for-bit
into the target pool while reproducing the per-slot sampling state
(rng, fold counter, temperature, pending token).  The target engine
therefore computes exactly the forward the source would have — pinned
by tests/test_router.py for greedy AND spec_k continuations.

Draft-model speculative caches are NOT migrated: verification makes
draft quality a performance knob, never a correctness one, so an
adopted slot simply re-drafts from a cold draft cache (the n-gram
drafter is host-side and needs nothing).
"""

from __future__ import annotations

import dataclasses
import io
import json
import time
import zlib
from typing import List, Optional

import jax
import numpy as np

from ml_trainer_tpu.generate import _COMPILED


class MigrationCorrupt(ValueError):
    """A KV migration payload failed its per-layer CRC32 check — the
    pages in flight are NOT the pages the source exported.  The router
    retries the adoption on a fallback decode candidate (a fresh
    serialization) instead of silently adopting garbage; a payload that
    stays corrupt falls back to requeue-and-reprefill.  Mirrors the
    checkpoint CRC discipline (checkpoint/: every restored leaf is
    CRC-verified, corrupt dirs are quarantined)."""


class WeightsMismatch(MigrationCorrupt):
    """The export was produced by an engine serving DIFFERENT weights
    than the adopting engine (checkpoint.weights_fingerprint recorded at
    export vs the target's own).  KV is not portable across weights — a
    cache built by model A decoded under model B yields plausible
    garbage, not an error — so adoption refuses structurally and the
    stream re-prefills on a same-generation replica instead.  Subclasses
    :class:`MigrationCorrupt` so every existing refuse-and-fall-back
    path treats it safely; the fleet wire reports it as the distinct
    ``weights_mismatch`` verdict."""


def _leaf_name(path):
    """Last dict key of a tree path (None for non-dict paths)."""
    return getattr(path[-1], "key", None) if path else None


@dataclasses.dataclass
class KVSlotExport:
    """One slot's migratable state: pool geometry, continuation state,
    and the page payload (one ``[pages_per_slot, H, page, D]`` array per
    K/V cache leaf, logical order, trash-padded past ``n_pages``)."""

    # -- geometry (validated against the target engine on import) -------
    page_size: int
    pages_per_slot: int
    max_len: int
    n_pages: int            # live pages in the chain (<= pages_per_slot)
    pos: int                # consumed positions (device cache_index mirror)
    # -- continuation state ---------------------------------------------
    prompt: np.ndarray      # the request's ORIGINAL prompt ids (int32)
    tokens: List[int]       # committed generated tokens so far
    last_token: int         # the pending decode input (the engine tok row)
    temperature: float
    rng_key: np.ndarray     # normalized uint32[2] PRNG key data
    step_counter: int       # per-token fold counter (_steps mirror)
    # -- payload ---------------------------------------------------------
    layers: List[np.ndarray]
    # Per-layer CRC32 of the page payload, computed at export.  None on
    # hand-built exports (unit tests); every real export carries them
    # and import/deserialization verify before any page is scattered.
    crc32s: Optional[List[int]] = None
    # Fingerprint of the weights the exporting engine serves
    # (engine.weights_fp).  None on hand-built exports; when both sides
    # carry one, import refuses a mismatch with WeightsMismatch before
    # any page allocates.
    weights_fp: Optional[str] = None

    def nbytes(self) -> int:
        """Device-payload bytes this migration moves (the metered
        quantity; host metadata is noise next to the K/V pages)."""
        return int(sum(a.nbytes for a in self.layers))

    def verify(self) -> None:
        """Recompute every layer's CRC32 against the export-time value;
        raises :class:`MigrationCorrupt` naming the first bad layer.
        No-op when the export carries no checksums."""
        if self.crc32s is None:
            return
        if len(self.crc32s) != len(self.layers):
            raise MigrationCorrupt(
                f"kv migration payload corrupt: {len(self.layers)} "
                f"layer(s) but {len(self.crc32s)} checksum(s)"
            )
        for i, (arr, want) in enumerate(zip(self.layers, self.crc32s)):
            got = zlib.crc32(np.ascontiguousarray(arr).tobytes())
            if got != want:
                raise MigrationCorrupt(
                    f"kv migration payload corrupt: layer {i} CRC32 "
                    f"{got:#010x} != exported {want:#010x} "
                    f"({arr.nbytes} bytes) — refusing to adopt"
                )


def _pool_leaf_paths(cache) -> list:
    """(path, leaf) for every K/V pool leaf (ndim 4), in the stable tree
    flatten order export and import both iterate."""
    from jax import tree_util

    return [
        (path, leaf)
        for path, leaf in tree_util.tree_flatten_with_path(cache)[0]
        if getattr(leaf, "ndim", 0) == 4
    ]


def _check_paged(engine) -> None:
    if not getattr(engine, "paged", False):
        raise ValueError(
            "KV migration needs a paged engine (kv_page_size > 0): the "
            "page chain is the transfer unit"
        )


def export_kv_slot(engine, slot: int) -> KVSlotExport:
    """Export ``slot``'s pages + continuation state from ``engine``.

    The slot must hold an active request (the engine's ``_active`` map
    is the source of the continuation metadata).  The engine keeps
    running afterwards — export only READS; the caller decides whether
    to release the slot (migration) or keep it (checkpoint/fork).
    """
    _check_paged(engine)
    req = engine._active.get(slot)
    if req is None:
        raise ValueError(f"slot {slot} holds no active request")
    pool = engine.pool
    row = engine._page_row(slot)            # [pages_per_slot], trash-padded
    leaves = [leaf for _, leaf in _pool_leaf_paths(engine.cache)]

    key = ("kv_export", engine._key_model, engine.max_batch)
    run = _COMPILED.get(key)
    if run is None:
        # One gather per pool leaf at a dynamic index vector: the padded
        # row keeps the shape static, so this compiles once per engine
        # geometry and the zero-recompile pin covers migration traffic.
        run = jax.jit(lambda ls, idx: [l[idx] for l in ls])
        _COMPILED[key] = run
    gathered = run(leaves, np.asarray(row, np.int32))
    # Migration fence: the payload must be host bytes before the source
    # slot is released.  # graft-lint: sync-ok
    layers = [np.asarray(g) for g in gathered]
    return KVSlotExport(
        page_size=pool.page_size,
        pages_per_slot=pool.pages_per_slot,
        max_len=engine.max_len,
        n_pages=pool.slot_page_count(slot),
        pos=int(engine._pos[slot]),
        prompt=np.asarray(req.prompt, np.int32).reshape(-1),
        tokens=[int(t) for t in req.tokens],
        last_token=int(np.asarray(engine.tok)[slot, 0]),
        temperature=float(engine._temps[slot]),
        rng_key=np.asarray(engine._rngs[slot], np.uint32).copy(),
        step_counter=int(engine._steps[slot]),
        layers=layers,
        crc32s=[
            zlib.crc32(np.ascontiguousarray(a).tobytes()) for a in layers
        ],
        weights_fp=getattr(engine, "weights_fp", None),
    )


def import_kv_slot(engine, req, slot: int, exp: KVSlotExport) -> str:
    """Scatter ``exp`` into ``engine``'s pool at ``slot`` and register
    ``req`` (the continuation request — same prompt, its ``tokens``
    already carrying the committed stream) as active.

    Returns ``"active"``, or ``"no_memory"`` when the target pool
    cannot hold the chain even after evicting cold prefix pages — the
    caller falls back to requeue-and-reprefill (the preempt-resume
    path), which stays byte-identical, just slower.
    """
    _check_paged(engine)
    if slot in engine._active:
        raise ValueError(f"slot {slot} is already occupied")
    pool = engine.pool
    if (pool.page_size != exp.page_size
            or pool.pages_per_slot != exp.pages_per_slot
            or engine.max_len != exp.max_len):
        raise ValueError(
            f"pool geometry mismatch: export is page_size="
            f"{exp.page_size} x {exp.pages_per_slot} (max_len "
            f"{exp.max_len}), target is {pool.page_size} x "
            f"{pool.pages_per_slot} (max_len {engine.max_len})"
        )
    # Fingerprint gate BEFORE any page allocates or scatters: KV built
    # by different weights would decode into plausible-looking wrong
    # tokens, so a cross-generation adoption refuses structurally
    # (deploys migrate sessions only at generation boundaries).
    target_fp = getattr(engine, "weights_fp", None)
    if (exp.weights_fp is not None and target_fp is not None
            and exp.weights_fp != target_fp):
        raise WeightsMismatch(
            f"weights_mismatch: export from weights {exp.weights_fp}, "
            f"adopting engine serves {target_fp} — KV is not portable "
            "across weights; re-prefill on a same-generation replica"
        )
    # CRC gate likewise before any allocation: a corrupt payload must
    # never become resident K/V.
    exp.verify()
    paths = _pool_leaf_paths(engine.cache)
    if len(paths) != len(exp.layers):
        raise ValueError(
            f"layer count mismatch: export has {len(exp.layers)} pool "
            f"leaves, target model has {len(paths)}"
        )
    for (_, leaf), arr in zip(paths, exp.layers):
        if tuple(leaf.shape[1:]) != tuple(arr.shape[1:]):
            raise ValueError(
                f"page geometry mismatch: export page rows "
                f"{arr.shape[1:]}, target pool {tuple(leaf.shape[1:])}"
            )

    pages = pool.allocate(exp.n_pages)
    if pages is None and engine._prefix is not None:
        engine._prefix.evict(exp.n_pages - pool.free_count())
        pages = pool.allocate(exp.n_pages)
    if pages is None:
        return "no_memory"
    pool.bind_slot(slot, pages)
    # The migrated stream keeps ITS adapter: bind it on the target (the
    # registry is fleet-shared, so a residency miss just uploads here).
    # Pool exhaustion degrades to the requeue path like page pressure;
    # an unregistered adapter is a structured terminal, never a hang.
    if engine.adapters is not None:
        from ml_trainer_tpu.serving.adapter_pool import (
            AdapterPoolExhausted,
            UnknownAdapter,
        )

        try:
            engine._bind_adapter(req, slot)
        except AdapterPoolExhausted:
            pool.reset_slot(slot)
            return "no_memory"
        except UnknownAdapter as e:
            pool.reset_slot(slot)
            req.finish("error", str(e))
            return "error"
    elif req.adapter:
        pool.reset_slot(slot)
        req.finish(
            "error",
            f"request {req.id} decodes with adapter '{req.adapter}' but "
            "the adopting replica has no adapter pool",
        )
        return "error"
    row = engine._page_row(slot)            # [pages_per_slot], trash-padded

    key = ("kv_import", engine._key_model, engine.max_batch)
    run = _COMPILED.get(key)
    if run is None:
        run = jax.jit(_build_import(), donate_argnums=(0, 1))
        _COMPILED[key] = run
    engine.cache, engine.tok = run(
        engine.cache, engine.tok, exp.layers,
        np.asarray(row, np.int32), np.int32(slot),
        np.int32(exp.pos), np.int32(exp.last_token),
    )
    # Host mirrors of the slot's sampling/position state — what keeps
    # the continuation byte-identical to the never-migrated run.
    engine._pos[slot] = exp.pos
    engine._temps[slot] = exp.temperature
    engine._rngs[slot] = exp.rng_key
    engine._steps[slot] = exp.step_counter
    if engine.spec_k:
        # The verify-window write cap, recomputed exactly as admit()
        # prices it (independent of how far the stream has advanced).
        engine._caps[slot] = min(
            int(exp.prompt.size) + int(req.max_new_tokens) - 1,
            engine.max_len - engine.spec_k - 1,
        )
    req.slot = slot
    req.state = "active"
    engine._active[slot] = req
    if engine._prefix is not None:
        # Donate the migrated FULL blocks (prompt + committed tokens
        # whose K/V is already written — everything before ``pos``) to
        # the target's prefix cache: the same radix-insert machinery
        # preemption uses to move written blocks between owners.
        seq = np.concatenate(
            [exp.prompt, np.asarray(exp.tokens, np.int32)]
        )[: exp.pos]
        blocks = exp.pos // pool.page_size
        if blocks:
            engine._prefix.insert(
                seq, pool.slot_pages[slot][:blocks],
                namespace=engine._prefix_ns(req),
            )
    engine._push_kv_metrics()
    return "active"


def _build_import():
    """The compiled import: scatter the padded page rows into every pool
    leaf, set the slot's index vector and pending-token row.  Page-table
    leaves pass through untouched — the host table (pool.bind_slot set
    it) uploads via the engine's ordinary dirty-sync before the next
    step, the same path every allocation takes."""
    import jax.numpy as jnp
    from jax import tree_util

    def run(cache, tok, layers, row, slot, pos, last_token):
        flat, treedef = tree_util.tree_flatten_with_path(cache)
        out, li = [], 0
        for path, leaf in flat:
            if leaf.ndim == 4:
                out.append(leaf.at[row].set(layers[li].astype(leaf.dtype)))
                li += 1
            elif _leaf_name(path) == "page_table":
                out.append(leaf)
            else:
                out.append(leaf.at[slot].set(jnp.asarray(pos, leaf.dtype)))
        cache = tree_util.tree_unflatten(treedef, out)
        tok = tok.at[slot, 0].set(last_token)
        return cache, tok

    return run


# ------------------------------------------------------- serialization

def to_bytes(exp: KVSlotExport) -> bytes:
    """One self-describing byte payload (transport-ready; what the
    router meters as migrated bytes)."""
    meta = {
        "page_size": exp.page_size,
        "pages_per_slot": exp.pages_per_slot,
        "max_len": exp.max_len,
        "n_pages": exp.n_pages,
        "pos": exp.pos,
        "tokens": list(exp.tokens),
        "last_token": exp.last_token,
        "temperature": exp.temperature,
        "step_counter": exp.step_counter,
        "n_layers": len(exp.layers),
        "weights_fp": exp.weights_fp,
        "crc32s": (
            list(exp.crc32s) if exp.crc32s is not None
            else [
                zlib.crc32(np.ascontiguousarray(a).tobytes())
                for a in exp.layers
            ]
        ),
    }
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        prompt=exp.prompt,
        rng_key=exp.rng_key,
        **{f"layer_{i}": a for i, a in enumerate(exp.layers)},
    )
    return buf.getvalue()


def from_bytes(payload: bytes, verify: bool = True) -> KVSlotExport:
    """Deserialize (and by default CRC-verify) a migration payload.
    Raises :class:`MigrationCorrupt` when the container is undecodable
    or a layer's bytes do not match the checksum the exporter wrote —
    the transport (or an injected ``migration_corrupt`` fault) damaged
    the pages in flight."""
    import zipfile

    try:
        exp = _from_bytes_unchecked(payload)
    except MigrationCorrupt:
        raise
    except (ValueError, OSError, KeyError, zipfile.BadZipFile,
            zlib.error, json.JSONDecodeError) as e:
        raise MigrationCorrupt(
            f"kv migration payload corrupt: undecodable container "
            f"({type(e).__name__}: {e})"
        ) from e
    if verify:
        exp.verify()
    return exp


def request_wire_meta(req) -> dict:
    """JSON-safe request identity for the fleet wire (serving/fleet.py):
    everything a remote replica needs to rebuild an EQUIVALENT
    :class:`~ml_trainer_tpu.serving.scheduler.Request` — prompt,
    sampling state (rng normalized to ``null | int | [u32, u32]``),
    committed tokens, and the deadline converted to REMAINING seconds
    (monotonic clocks do not cross process boundaries)."""
    rng = req.rng
    if rng is not None and not isinstance(rng, (int, np.integer)):
        rng = [int(x) for x in
               np.asarray(rng, np.uint32).reshape(-1)]
    elif rng is not None:
        rng = int(rng)
    deadline = None
    if req.deadline is not None:
        deadline = max(req.deadline_at - time.monotonic(), 0.001)
    meta = {
        "id": int(req.id),
        "prompt": [int(t) for t in np.asarray(req.prompt).reshape(-1)],
        "max_new_tokens": int(req.max_new_tokens),
        "temperature": float(req.temperature),
        "rng": rng,
        "eos_token_id": (
            int(req.eos_token_id) if req.eos_token_id is not None else None
        ),
        "deadline": deadline,
        "tenant": req.tenant,
        "priority": int(req.priority),
        "adapter": req.adapter,
        "tokens": [int(t) for t in req.tokens],
    }
    # Fleet trace context (docs/observability.md "Fleet plane"): the
    # origin request id + pid ride every hop, so the receiving process
    # stamps ITS retrospective spans with the same trace_id and the
    # merged fleet timeline correlates the fragments.
    trace = getattr(req, "trace_ctx", None)
    if trace:
        meta["trace"] = dict(trace)
    return meta


def request_from_wire(meta: dict):
    """Rebuild a request from :func:`request_wire_meta` output.  The
    fresh ``submitted_at`` makes the wire's remaining-seconds deadline
    correct on the receiving process's own monotonic clock; committed
    tokens ride as the resumable prefix, exactly like a router shadow."""
    from ml_trainer_tpu.serving.scheduler import Request

    rng = meta.get("rng")
    if isinstance(rng, (list, tuple)):
        rng = np.asarray(rng, np.uint32)
    req = Request(
        prompt=np.asarray(meta["prompt"], np.int32),
        max_new_tokens=int(meta["max_new_tokens"]),
        temperature=float(meta.get("temperature", 0.0)),
        rng=rng,
        eos_token_id=meta.get("eos_token_id"),
        deadline=meta.get("deadline"),
        tenant=meta.get("tenant", "default"),
        priority=int(meta.get("priority", 0)),
        adapter=meta.get("adapter"),
    )
    req.tokens = [int(t) for t in meta.get("tokens", [])]
    trace = meta.get("trace")
    if trace:
        req.trace_ctx = dict(trace)
    return req


def _from_bytes_unchecked(payload: bytes) -> KVSlotExport:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        return KVSlotExport(
            page_size=int(meta["page_size"]),
            pages_per_slot=int(meta["pages_per_slot"]),
            max_len=int(meta["max_len"]),
            n_pages=int(meta["n_pages"]),
            pos=int(meta["pos"]),
            prompt=np.asarray(z["prompt"], np.int32),
            tokens=[int(t) for t in meta["tokens"]],
            last_token=int(meta["last_token"]),
            temperature=float(meta["temperature"]),
            rng_key=np.asarray(z["rng_key"], np.uint32),
            step_counter=int(meta["step_counter"]),
            layers=[
                z[f"layer_{i}"] for i in range(int(meta["n_layers"]))
            ],
            crc32s=[int(c) for c in meta.get("crc32s", [])] or None,
            weights_fp=meta.get("weights_fp"),
        )
