"""Live base-model rollout: canary + shadow deploys with SLO-burn
auto-rollback under traffic (docs/serving.md "Deploys").

``Router.deploy(ckpt)`` builds a :class:`Deployment` — a small state
machine over the PR 14 fleet/autoscaler machinery:

    staging -> [shadowing] -> canary -> ramping -> done
                                  \\-> rolling_back -> rolled_back

* **staging** spawns a full new-generation replica set from the
  checkpoint export, mirroring the serving generation's role mix.  The
  newcomers share the fleet's on-disk compile cache, so a deploy mints
  no compiles on the steady fleet and none on the new one beyond its
  own warmup.  No traffic moves yet.
* **shadowing** (opt-in) replays a sampled fraction of live finished
  requests against the new replicas OFF the serving path and diffs
  tokens + latency into :meth:`Deployment.shadow_report`.  A greedy
  token mismatch rolls back before any real traffic moves.
* **canary** points the deterministic tenant-hash slice
  ``[0, canary)`` (``Router.tenant_slice``) at the new generation and
  watches that slice's SLO burn through the router's ``SloTracker``.
  The slice is a stable cohort — the same tenants on every poll — so
  the burn signal is attributable to the new weights, not churn.
* **ramping** advances the slice through ``DeployConfig.stages``
  (default 5% -> 50% -> 100%), holding each stage ``hold_s`` of clean
  burn before moving.  After the final stage holds, the new generation
  is promoted (``Router.promote_generation``) and the old replicas are
  retired through the drain path.
* **rolling_back** fires when the canary slice's burn sits at/over
  ``burn_threshold`` for ``high_polls`` consecutive polls (with enough
  window requests to mean anything): the split tears down first (new
  canary traffic lands back on stable instantly), then the new
  replicas drain/evacuate out.  In-flight canary streams either drain
  clean or fail-and-redistribute onto the stable fleet, which
  re-prefills them — KV is never adopted across weights (the
  ``WeightsMismatch`` fingerprint gate in serving/transfer.py), and no
  stream is dropped.

Every transition is a flight-recorder ``deploy`` event and the current
state is exported as ``serving_deploy_*`` gauges.  ``tick()`` runs one
state-machine step synchronously (tests drive it with a fake clock);
``start()`` runs it on a timer thread, autoscaler-style.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ml_trainer_tpu.serving.scheduler import Request
from ml_trainer_tpu.serving.slo import aggregate_timelines
from ml_trainer_tpu.telemetry.alerts import AlertEngine, AlertRule
from ml_trainer_tpu.utils.logging import get_logger

# Terminal states: the deployment thread exits, Router.deploy() will
# accept a new deployment.
TERMINAL_STATES = ("done", "rolled_back", "failed")


@dataclasses.dataclass(frozen=True)
class DeployConfig:
    """Knobs for one rollout (docs/serving.md "Deploys")."""

    # Traffic plan: first stage is the canary fraction; the ramp then
    # visits every stage above it, in order, ending at 1.0.
    canary: float = 0.05
    stages: tuple = (0.5, 1.0)
    # Shadow mode: replay `shadow_fraction` of live finished requests
    # against the new generation off the serving path; require
    # `shadow_min_requests` diffed replays (or give up after
    # `shadow_timeout_s` and proceed — shadowing needs live traffic).
    shadow: bool = False
    shadow_fraction: float = 0.25
    shadow_min_requests: int = 4
    shadow_timeout_s: float = 120.0
    shadow_replay_timeout_s: float = 60.0
    # Burn watch: roll back when the canary slice's windowed burn
    # (max of TTFT/TPOT) sits at/over `burn_threshold` for
    # `high_polls` consecutive polls with at least
    # `min_window_requests` finished requests in the window.
    burn_threshold: float = 2.0
    high_polls: int = 2
    window_s: float = 30.0
    min_window_requests: int = 3
    # Ramp pacing: a stage must hold `hold_s` without a high-burn poll
    # before the fraction advances (and before the final promote).
    # With `stage_min_requests` > 0 a stage additionally may not
    # advance until the canary window has REPORTED that many finished
    # requests — holding on "no data" instead of ramping past a slice
    # whose requests are all still in flight (a slow regression would
    # otherwise outrun the watch).  0 lets traffic-free deploys
    # promote on the hold timer alone.
    hold_s: float = 3.0
    stage_min_requests: int = 0
    poll_interval_s: float = 0.5
    # Staging warmup: run a few off-path greedy requests through every
    # new replica before any traffic moves, so the canary's first
    # clients never pay a cold compile (and the burn watch never
    # mistakes warmup latency for a weights regression).
    warmup: bool = True
    warmup_tokens: int = 4
    warmup_timeout_s: float = 120.0
    # Drain budget per replica when retiring a generation (either
    # direction — rollback or post-promote retirement).
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        if not 0.0 < self.canary <= 1.0:
            raise ValueError(f"canary must be in (0, 1], got {self.canary}")
        if any(not 0.0 < s <= 1.0 for s in self.stages):
            raise ValueError(f"stages must be in (0, 1], got {self.stages}")
        if self.burn_threshold <= 0 or self.high_polls < 1:
            raise ValueError(
                "burn_threshold must be > 0 and high_polls >= 1"
            )

    def fractions(self) -> tuple:
        """The full traffic plan: canary first, then every configured
        stage strictly above it (ascending), always ending at 1.0."""
        ramp = sorted({s for s in self.stages if s > self.canary} | {1.0})
        return (self.canary, *ramp)


class Deployment:
    """One live rollout of new base weights over a Router fleet.

    Built by ``Router.deploy()``; ``factory(role) -> server`` spawns a
    new-generation replica already loaded with the target checkpoint
    (``Fleet.deploy_factory`` for multi-process fleets; in-process
    callers pass their own).  Use ``wait()`` for the verdict, or drive
    ``tick()`` directly in tests."""

    def __init__(self, router, ckpt: str, factory: Callable,
                 config: Optional[DeployConfig] = None,
                 clock=time.monotonic):
        self.router = router
        self.ckpt = ckpt
        self.factory = factory
        self.config = config if config is not None else DeployConfig()
        self._clock = clock
        self._log = get_logger("ml_trainer_tpu.serving.deploy")
        self._lock = threading.Lock()       # state + event list
        self._tick_lock = threading.Lock()  # one tick at a time
        self._stop = threading.Event()
        self._finished = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self.state = "staging"
        self.generation = router._serving_generation + 1
        self.old_generation = router._serving_generation
        self.new_replicas: List[str] = []
        self.events: List[dict] = []
        self.last_burn: Optional[float] = None
        self.rollback_cause: Optional[str] = None
        self.weights_fp: Optional[str] = None
        self.old_weights_fp: Optional[str] = None

        self._stage_idx = -1               # index into config.fractions()
        self._stage_clean_since: Optional[float] = None
        self._split_since: Optional[float] = None  # time.monotonic stamp
        self._started_at = self._clock()

        # Shadow bookkeeping: the router's request tap feeds sampled
        # finished requests here; tick() replays and diffs them.
        self._shadow_pending: List[dict] = []
        self._shadow_rows: List[dict] = []
        self._shadow_since: Optional[float] = None
        self._installed_tap: Optional[Callable] = None

        # The canary burn watch, re-expressed as a for_count alert rule
        # on the fleet's AlertEngine (ONE alerting path): the rule keeps
        # the consecutive-high-poll streak, firing = rollback.  The rule
        # name carries the generation so back-to-back deployments over
        # one router never share state.
        engine = getattr(router, "alerts", None)
        if engine is None:
            engine = AlertEngine(clock=self._clock)
        self.alerts = engine
        self._burn_rule = engine.add_rule(AlertRule(
            f"deploy_canary_burn_gen{self.generation}",
            for_count=self.config.high_polls, severity="warn",
            description=(
                f"canary slice SLO burn >= {self.config.burn_threshold} "
                f"for {self.config.high_polls} consecutive polls"
            ),
        ))

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Deployment":
        if self._thread is None and not self.finished():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"deploy-gen{self.generation}",
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set() and not self.finished():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self._log.error("deploy_error", error=f"{e}")
            self._stop.wait(self.config.poll_interval_s)

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until the deployment reaches a terminal state (or the
        timeout passes); returns the state either way."""
        self._finished.wait(timeout)
        return self.state

    def finished(self) -> bool:
        return self.state in TERMINAL_STATES

    def close(self) -> None:
        """Stop watching.  An unfinished deployment tears its traffic
        split down first so no tenant is left routed at a generation
        nobody is steering (the replicas stay up; call ``wait()`` for a
        verdict instead when you want the rollout to finish)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._uninstall_tap()
        if not self.finished():
            self.router.set_deploy_split(None, 0.0)
            self._transition("failed", cause="closed before terminal")

    # -- event plumbing ---------------------------------------------------

    def _record(self, action: str, **extra) -> None:
        row = {
            "t": round(self._clock(), 3), "action": action,
            "state": self.state, "generation": self.generation, **extra,
        }
        with self._lock:
            self.events.append(row)
        from ml_trainer_tpu.telemetry.flight import get_recorder

        get_recorder().record("deploy", **row)
        self._log.info("deploy_event", **row)

    def _transition(self, state: str, **extra) -> None:
        prev = self.state
        self.state = state
        self._record("transition", frm=prev, to=state, **extra)
        self.publish()
        if state in TERMINAL_STATES:
            self._uninstall_tap()
            self._finished.set()

    def publish(self, registry=None) -> None:
        """``serving_deploy_*`` gauges: one-hot state, generation, the
        live traffic fraction, the last canary burn, shadow volume."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        st = r.gauge(
            "serving_deploy_state",
            "deploy state machine position (one-hot)",
            labelnames=("state",),
        )
        all_states = (
            "staging", "shadowing", "canary", "ramping", "rolling_back",
        ) + TERMINAL_STATES
        for s in all_states:
            st.labels(state=s).set(1.0 if s == self.state else 0.0)
        r.gauge(
            "serving_deploy_generation",
            "target generation of the active/last deployment",
        ).set(float(self.generation))
        r.gauge(
            "serving_deploy_fraction",
            "tenant-hash traffic fraction routed at the new generation",
        ).set(float(self.router._deploy_fraction))
        if self.last_burn is not None:
            r.gauge(
                "serving_deploy_canary_burn",
                "last windowed SLO burn measured on the canary slice",
            ).set(float(self.last_burn))
        r.gauge(
            "serving_deploy_shadow_replays",
            "shadow requests replayed against the new generation",
        ).set(float(len(self._shadow_rows)))

    # -- the state machine ------------------------------------------------

    def tick(self) -> str:
        """Run one state-machine step synchronously and return the
        (possibly new) state.  Thread-safe; the timer thread and tests
        share this entry point."""
        with self._tick_lock:
            if self.finished():
                return self.state
            step = {
                "staging": self._tick_staging,
                "shadowing": self._tick_shadowing,
                "canary": self._tick_watch,
                "ramping": self._tick_watch,
                "rolling_back": self._tick_rollback,
            }.get(self.state)
            if step is not None:
                step()
            self.publish()
            return self.state

    # -- staging ----------------------------------------------------------

    def _role_mix(self) -> List[str]:
        roles = [
            rep.role for rep in self.router.replicas.values()
            if rep.generation == self.old_generation and not rep.removing
        ]
        return roles or ["both"]

    def _tick_staging(self) -> None:
        roles = self._role_mix()
        self.old_weights_fp = next(
            (rep.weights_fp
             for rep in self.router.replicas.values()
             if rep.generation == self.old_generation and rep.weights_fp),
            None,
        )
        try:
            for i, role in enumerate(roles):
                name = f"deploy{self.generation}-{role}{i}"
                server = self.factory(role)
                self.router.add_replica(
                    name, server, generation=self.generation
                )
                self.new_replicas.append(name)
                if self.weights_fp is None:
                    self.weights_fp = getattr(
                        self.router.replicas[name], "weights_fp", None
                    )
            if self.config.warmup:
                self._warm_generation()
        except Exception as e:  # noqa: BLE001 — a failed spawn is a verdict
            self._record("staging_failed", error=f"{e}")
            self._teardown_generation(self.generation)
            self._transition("failed", cause=f"staging: {e}")
            return
        self._record(
            "staged", replicas=list(self.new_replicas), ckpt=self.ckpt,
            weights_fp=self.weights_fp, old_weights_fp=self.old_weights_fp,
        )
        if self.config.shadow:
            self._install_tap()
            self._shadow_since = self._clock()
            self._transition("shadowing")
        else:
            self._begin_stage(0)

    def _warm_generation(self) -> None:
        """Push one off-path greedy request through every new replica
        before any traffic moves.  Workers compile on first request,
        not at boot; warming here means the canary's first clients see
        steady-state latency (shared on-disk compile cache makes this a
        cache load on real fleets) and the burn watch never reads
        warmup latency as a weights regression."""
        deadline = self._clock() + self.config.warmup_timeout_s
        for name in self.new_replicas:
            rep = self.router.replicas.get(name)
            if rep is None or not rep.healthy:
                continue
            req = Request(
                prompt=np.zeros(8, dtype=np.int32),
                max_new_tokens=self.config.warmup_tokens,
            )
            t0 = self._clock()
            rep.server.submit_request(req)
            while req.finished_at is None and self._clock() < deadline:
                time.sleep(0.01)
            if req.finished_at is None:
                raise RuntimeError(
                    f"warmup timed out on {name} after "
                    f"{self.config.warmup_timeout_s:.0f}s"
                )
            self._record(
                "warmed", replica=name,
                seconds=round(self._clock() - t0, 3),
            )

    # -- shadowing --------------------------------------------------------

    def _install_tap(self) -> None:
        if self._installed_tap is None:
            self._installed_tap = self._tap
            self.router._request_tap = self._installed_tap

    def _uninstall_tap(self) -> None:
        if self._installed_tap is not None:
            if self.router._request_tap is self._installed_tap:
                self.router._request_tap = None
            self._installed_tap = None

    def _tap(self, creq: Request) -> None:
        """Router request tap: sample finished live requests for shadow
        replay.  Only replayable requests qualify — done, and greedy or
        seed-pinned, so the diff is meaningful (same bytes expected
        from same weights)."""
        if self.state != "shadowing" or creq.state != "done":
            return
        if creq.temperature != 0.0 and creq.rng is None:
            return
        if self.router.tenant_slice(
            f"shadow{creq.id}"
        ) >= self.config.shadow_fraction:
            return
        tl = creq.timeline()
        row = {
            "prompt": np.asarray(creq.prompt).copy(),
            "max_new_tokens": int(creq.max_new_tokens),
            "temperature": float(creq.temperature),
            "rng": creq.rng,
            "tenant": creq.tenant,
            "adapter": creq.adapter,
            "live_tokens": list(creq.tokens),
            "live_e2e_ms": tl.get("e2e_ms"),
        }
        with self._lock:
            if len(self._shadow_pending) < 64:
                self._shadow_pending.append(row)

    def _shadow_target(self):
        """A new-generation replica that can run a request end-to-end
        in place (no migration sink -> it decodes where it prefills)."""
        reps = [
            self.router.replicas[n] for n in self.new_replicas
            if n in self.router.replicas
            and self.router.replicas[n].healthy
        ]
        reps.sort(key=lambda r: (r.role == "decode", r.role != "both"))
        return reps[0] if reps else None

    def _replay(self, sample: dict) -> Optional[dict]:
        rep = self._shadow_target()
        if rep is None:
            return None
        req = Request(
            prompt=sample["prompt"],
            max_new_tokens=sample["max_new_tokens"],
            temperature=sample["temperature"],
            rng=sample["rng"],
            tenant=sample["tenant"],
            adapter=sample["adapter"],
        )
        t0 = time.monotonic()
        try:
            rep.server.submit_request(req)
        except Exception as e:  # noqa: BLE001 — shadow must never hurt live
            return {"state": "error", "error": f"{e}", "match": None}
        deadline = t0 + self.config.shadow_replay_timeout_s
        while req.finished_at is None and time.monotonic() < deadline:
            time.sleep(0.01)
        shadow_tokens = list(req.tokens)
        comparable = req.state == "done" and sample["temperature"] == 0.0
        return {
            "state": req.state,
            "replica": rep.name,
            "match": (
                shadow_tokens == sample["live_tokens"]
                if comparable else None
            ),
            "live_e2e_ms": sample["live_e2e_ms"],
            "shadow_e2e_ms": round((time.monotonic() - t0) * 1e3, 3),
            "n_tokens": len(shadow_tokens),
        }

    def _tick_shadowing(self) -> None:
        with self._lock:
            pending, self._shadow_pending = self._shadow_pending, []
        for sample in pending:
            row = self._replay(sample)
            if row is not None:
                self._shadow_rows.append(row)
        mismatches = [
            r for r in self._shadow_rows if r.get("match") is False
        ]
        if mismatches:
            self._record(
                "shadow_mismatch", n=len(mismatches),
                of=len(self._shadow_rows),
            )
            self._rollback(
                f"shadow diff: {len(mismatches)}/{len(self._shadow_rows)} "
                "replayed requests produced different tokens"
            )
            return
        enough = len(self._shadow_rows) >= self.config.shadow_min_requests
        timed_out = (
            self._clock() - self._shadow_since > self.config.shadow_timeout_s
        )
        if enough or timed_out:
            self._record(
                "shadow_done", n=len(self._shadow_rows),
                timed_out=bool(timed_out and not enough),
                report=self.shadow_report(),
            )
            self._uninstall_tap()
            self._begin_stage(0)

    def shadow_report(self) -> dict:
        """Tokens + latency diff of every shadow replay so far (the
        committed evidence that precedes any real traffic moving)."""
        rows = list(self._shadow_rows)
        compared = [r for r in rows if r.get("match") is not None]

        def _p50(vals):
            vals = sorted(v for v in vals if v is not None)
            return vals[len(vals) // 2] if vals else None

        return {
            "n_replayed": len(rows),
            "n_compared": len(compared),
            "n_token_mismatch": sum(
                1 for r in compared if r["match"] is False
            ),
            "live_e2e_ms_p50": _p50(r.get("live_e2e_ms") for r in rows),
            "shadow_e2e_ms_p50": _p50(
                r.get("shadow_e2e_ms") for r in rows
            ),
            "rows": rows[-32:],
        }

    # -- canary / ramping -------------------------------------------------

    def _begin_stage(self, idx: int) -> None:
        plan = self.config.fractions()
        self._stage_idx = idx
        fraction = plan[idx]
        self.router.set_deploy_split(self.generation, fraction)
        if self._split_since is None:
            self._split_since = time.monotonic()
        self._stage_clean_since = self._clock()
        self._burn_rule.reset()
        self._record("stage", fraction=fraction, stage=idx, plan=plan)
        self._transition("canary" if idx == 0 else "ramping",
                         fraction=fraction)

    def canary_burn(self) -> Optional[dict]:
        """The canary slice's windowed SLO aggregation (None while the
        window holds too few finished canary requests to mean
        anything).  The slice predicate is the same tenant-hash the
        placement path uses, so burn is measured on exactly the
        traffic the new generation served."""
        if self._split_since is None:
            return None
        fraction = self.router._deploy_fraction
        since = max(
            self._split_since, time.monotonic() - self.config.window_s
        )
        tls = self.router.slo.timelines(
            since=since,
            predicate=lambda tl: self.router.tenant_slice(
                tl.get("tenant") or "default"
            ) < fraction,
        )
        if len(tls) < self.config.min_window_requests:
            return None
        return aggregate_timelines(tls, self.router.slo.policy)

    def _tick_watch(self) -> None:
        agg = self.canary_burn()
        now = self._clock()
        if agg is not None:
            burn = max(agg["burn_rate"]["ttft"], agg["burn_rate"]["tpot"])
            self.last_burn = burn
            high = burn >= self.config.burn_threshold
            firing = self.alerts.observe(
                self._burn_rule.name, high, now=now, value=burn,
                extra={"window_requests": agg["n_requests"],
                       "generation": self.generation},
            )
            if high:
                streak = self._burn_rule.count()
                self._stage_clean_since = now
                self._record(
                    "burn_high", burn=burn, streak=streak,
                    window_requests=agg["n_requests"],
                )
                if firing:
                    self._rollback(
                        f"canary burn {burn:.2f} >= "
                        f"{self.config.burn_threshold} for "
                        f"{streak} polls "
                        f"({agg['n_requests']} requests in window)"
                    )
                return
        if now - self._stage_clean_since < self.config.hold_s:
            return
        if self.config.stage_min_requests and (
                agg is None
                or agg["n_requests"] < self.config.stage_min_requests):
            return  # hold: the slice has not reported yet
        plan = self.config.fractions()
        if self._stage_idx + 1 < len(plan):
            self._begin_stage(self._stage_idx + 1)
        else:
            self._promote()

    # -- terminal paths ---------------------------------------------------

    def _teardown_generation(self, generation: int) -> None:
        """Retire every replica of one generation through the drain
        path: each leaves the placement pools immediately, drains
        bounded, and anything still in flight at detach is
        failed-and-redistributed — the pumps re-place those streams on
        the surviving generation (re-prefill; KV never crosses weights)
        so no client stream drops.  Replicas this deployment spawned
        are always closed (it owns them even when the router doesn't
        own its seed fleet)."""
        victims = [
            name for name, rep in self.router.replicas.items()
            if rep.generation == generation
        ]
        for name in victims:
            try:
                drained = self.router.remove_replica(
                    name, timeout=self.config.drain_timeout_s,
                    close=True if name in self.new_replicas else None,
                )
            except KeyError:
                continue
            self._record("retire_replica", replica=name, drained=drained)

    def _rollback(self, cause: str) -> None:
        self.rollback_cause = cause
        self._uninstall_tap()
        self._transition("rolling_back", cause=cause)
        # Incident bundle BEFORE teardown: the canary replicas' flight
        # payloads and SLO timelines are the rollback's evidence, and
        # they vanish with the generation.
        trigger = getattr(self.router, "trigger_incident", None)
        if trigger is not None:
            try:
                trigger(f"deploy_rollback: {cause}")
            except Exception:  # noqa: BLE001 — forensics never block it
                pass
        # Split down FIRST: new canary arrivals land on stable before a
        # single replica starts draining.
        self.router.set_deploy_split(None, 0.0)
        self._tick_rollback()

    def _tick_rollback(self) -> None:
        self._teardown_generation(self.generation)
        self._transition("rolled_back", cause=self.rollback_cause)

    def _promote(self) -> None:
        self.router.promote_generation(self.generation)
        self._record("promoted", fraction=1.0)
        self._teardown_generation(self.old_generation)
        self._transition("done")

    # -- reporting --------------------------------------------------------

    def report(self) -> dict:
        """JSON-safe rollout record (the bench artifact's deploy
        section): verdict, traffic plan, fingerprints, burn, events,
        shadow diff."""
        with self._lock:
            events = list(self.events)
        return {
            "state": self.state,
            "ckpt": self.ckpt,
            "generation": self.generation,
            "old_generation": self.old_generation,
            "weights_fp": self.weights_fp,
            "old_weights_fp": self.old_weights_fp,
            "plan": list(self.config.fractions()),
            "last_burn": self.last_burn,
            "rollback_cause": self.rollback_cause,
            "new_replicas": list(self.new_replicas),
            "shadow": self.shadow_report() if self._shadow_rows else None,
            "events": events,
            "elapsed_s": round(self._clock() - self._started_at, 3),
        }

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
