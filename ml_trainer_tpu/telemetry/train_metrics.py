"""Training step telemetry: on-device stats + host-side emission.

The on-device half (:func:`step_stats`) runs INSIDE the compiled train
step — the same no-host-sync discipline as the all-finite guard: the
stats are pure functions of values the step already computes (grads,
updates, params, loss), they where-select nothing and branch on nothing,
so enabling them changes neither the trajectory nor the number of
compiled programs.  The trainer fetches the returned scalars at its
existing ``log_every`` sync cadence — by then the dispatch has long
retired, so the fetch is a ready-value read, not a stall.

The host half (:class:`TrainTelemetry`) turns one fetched stats dict
into: registry gauges/counters (``train_*``), a structured
``train_step_telemetry`` log event, a flight-recorder step record, and
throughput derived metrics — samples/s, tokens/s (LM models), and an
analytic MFU estimate (``flops.py``; TPU backend only — an MFU against
a CPU has no denominator worth printing).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

from ml_trainer_tpu.telemetry import flight as _flight
from ml_trainer_tpu.telemetry import flops as _flops
from ml_trainer_tpu.telemetry.registry import default_registry
from ml_trainer_tpu.utils.logging import get_logger

logger = get_logger("ml_trainer_tpu.telemetry")

STAT_KEYS = (
    "loss_raw", "grad_norm", "param_norm", "update_norm", "update_ratio",
)


def _global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )


def step_stats(loss, grads, updates, new_params) -> dict:
    """On-device per-step stats (float32 scalars; call inside the jitted
    step).  ``loss_raw`` is the PRE-guard loss, so a skipped step's NaN
    is visible to telemetry even though the accumulators zero it."""
    gn = _global_norm(grads)
    un = _global_norm(updates)
    pn = _global_norm(new_params)
    return {
        "loss_raw": jnp.asarray(loss, jnp.float32),
        "grad_norm": gn,
        "param_norm": pn,
        "update_norm": un,
        # The step-size-to-weight-scale ratio optimizer tuning watches;
        # eps guards a zero-param probe model, not a real run.
        "update_ratio": un / (pn + 1e-12),
    }


def zero_stats() -> dict:
    """Host-side stats placeholder with the same keys (pre-first-sync)."""
    return {k: jnp.zeros((), jnp.float32) for k in STAT_KEYS}


class TrainTelemetry:
    """Host-side emitter for one training run.

    Construct once per ``fit()`` with the model + batch geometry, then
    call :meth:`on_sync` at every host-sync point with the latest
    on-device stats and the counters the trainer already tracks.  All
    device values passed in are fetched here with ONE ``device_get``."""

    def __init__(self, model: Any = None, model_name: str = "",
                 global_batch: int = 0,
                 batch_shape: Optional[Sequence[int]] = None,
                 registry=None, flight=None, log=None, cluster=None,
                 compute_dtype: Optional[str] = None,
                 overlap_fraction: Optional[float] = None):
        self.registry = registry if registry is not None else default_registry()
        self.flight = flight if flight is not None else _flight.get_recorder()
        self.log = log if log is not None else logger
        self.model_name = model_name or (
            type(model).__name__ if model is not None else ""
        )
        self.global_batch = int(global_batch)
        # tokens/sample for LM-shaped batches ([B, S] integer inputs).
        self.tokens_per_sample = (
            int(batch_shape[1])
            if batch_shape is not None and len(batch_shape) == 2 else 0
        )
        self.flops_per_step = (
            _flops.train_step_flops(model, batch_shape)
            if model is not None and batch_shape is not None else None
        )
        self._on_tpu = jax.default_backend() == "tpu"
        # MFU divides by the peak of the ACTIVE compute dtype: an fp32
        # run's attainable ceiling is ~half the bf16 MXU peak (flops.py
        # dtype tables); None keeps the historical bf16 denominator.
        self.compute_dtype = compute_dtype
        self._peak = (
            _flops.chip_peak_flops(compute_dtype or "bf16")
            if self._on_tpu else None
        )
        self.overlap_fraction = overlap_fraction
        self._last_sync_t: Optional[float] = None
        self._last_sync_step = 0
        self._last_skipped = 0
        # Distributed-observability hooks (telemetry/cluster.py): a rolling
        # window of per-step ms (fenced at the sync cadence — the stats
        # fetch above anchors each window to real execution), the loader
        # wait accounting, and the optional cluster heartbeat target.
        self.cluster = cluster
        self._step_ms: collections.deque = collections.deque(maxlen=128)
        from ml_trainer_tpu.data.loader import loader_wait_snapshot

        self._loader_wait_snapshot = loader_wait_snapshot
        self._last_wait = loader_wait_snapshot()
        self.last_loader_wait_ms = 0.0
        self.last_sps = 0.0
        # Instruments (idempotent registration; shared default registry).
        r = self.registry
        self.g_loss = r.gauge("train_loss", "last fetched train-step loss")
        self.g_grad = r.gauge("train_grad_norm", "global gradient L2 norm")
        self.g_param = r.gauge("train_param_norm", "global parameter L2 norm")
        self.g_upd = r.gauge("train_update_norm", "global update L2 norm")
        self.g_ratio = r.gauge(
            "train_update_ratio", "update norm / param norm"
        )
        self.g_sps = r.gauge("train_samples_per_sec",
                             "throughput since the previous sync")
        self.g_tps = r.gauge("train_tokens_per_sec",
                             "token throughput (LM batches)")
        self.g_mfu = r.gauge("train_mfu",
                             "analytic model FLOPs utilization (TPU only)")
        self.g_lr_scale = r.gauge("train_lr_scale",
                                  "plateau/rollback LR backoff scale")
        self.c_steps = r.counter("train_steps_total", "optimizer steps run")
        self.c_skipped = r.counter(
            "train_skipped_steps_total",
            "steps skipped by the non-finite guard",
        )
        self.c_rollbacks = r.counter(
            "train_rollbacks_total", "rollback-to-last-good events"
        )
        self.g_step_p50 = r.gauge(
            "train_step_ms_p50",
            "median per-step ms (windows fenced at the sync cadence)",
        )
        self.g_step_p99 = r.gauge(
            "train_step_ms_p99", "p99 per-step ms (sync-fenced windows)"
        )
        self.g_loader_wait = r.gauge(
            "train_loader_wait_ms",
            "host ms blocked per batch in the input pipeline",
        )
        self.g_comm_bytes = r.gauge(
            "train_comm_bytes_per_step",
            "analytic explicit-collective bytes per compiled step "
            "(parallel/comm_stats.py; zero when only XLA-implied "
            "collectives run)",
        )
        self.g_comm_ratio = r.gauge(
            "train_comm_compute_ratio",
            "analytic collective bytes per training FLOP — the "
            "sharding-bug canary next to MFU",
        )
        self.g_overlap = r.gauge(
            "train_overlap_fraction",
            "analytic fraction of reduce-scatter bytes whose collectives "
            "can hide under remaining backward compute (bucketed sharded "
            "update; 0 = fused tail psum, nothing overlaps)",
        )
        if overlap_fraction is not None:
            self.g_overlap.set(float(overlap_fraction))
        self.g_loss_scale = r.gauge(
            "train_loss_scale",
            "current dynamic loss scale (mixed precision; 0 = scaling off)",
        )
        # Goodput ledger (telemetry/goodput.py): the trainer starts the
        # meter at fit() entry; every sync republishes the wall-clock
        # decomposition and the goodput fraction rides the heartbeat.
        from ml_trainer_tpu.telemetry.goodput import GoodputMeter

        self.goodput = GoodputMeter(registry=r)
        # Watchtower flight context: a crash dump carries the last-N
        # samples of the headline series (goodput, SLO burn, KV pages,
        # post-warmup compiles) — the trend INTO the crash, not just the
        # final values.  Idempotent by provider name.
        from ml_trainer_tpu.telemetry.watchtower import (
            install_flight_context,
        )

        install_flight_context(recorder=self.flight)
        # The per-schedule train_pipeline_bubble_fraction{schedule=}
        # gauge is owned by parallel/pipeline.py (set at trace time, the
        # comm_stats discipline); on_sync only folds the active
        # schedule's analytic bubble into the structured event.

    def on_sync(self, step: int, stats: dict, *, epoch: int = 0,
                skipped_total: int = 0, lr_scale: float = 1.0,
                loss_scale: Optional[float] = None) -> dict:
        """One sync point: fetch ``stats`` (device scalars), update the
        registry, emit the structured event + flight record.  Returns
        the fetched host-side dict (for the caller's own display)."""
        now = time.perf_counter()
        host = {
            k: float(v) for k, v in zip(
                stats.keys(), jax.device_get(list(stats.values()))
            )
        }
        steps_d = step - self._last_sync_step
        sps = tps = mfu = None
        if self._last_sync_t is not None and steps_d > 0:
            dt = max(now - self._last_sync_t, 1e-9)
            sps = steps_d * self.global_batch / dt
            self.last_sps = sps
            self.g_sps.set(sps)
            if self.tokens_per_sample:
                tps = sps * self.tokens_per_sample
                self.g_tps.set(tps)
            if self.flops_per_step is not None and self._peak:
                mfu = (steps_d * self.flops_per_step / dt) / self._peak
                self.g_mfu.set(mfu)
            # One window entry = mean per-step ms of this sync window; the
            # device fetch above fenced the window's work, so percentiles
            # over windows are honest (exact per-step at log_every=1).
            self._step_ms.append(dt / steps_d * 1e3)
            p50, p99 = self.step_ms_p50(), self.step_ms_p99()
            self.g_step_p50.set(p50)
            self.g_step_p99.set(p99)
        self._last_sync_t = now
        self._last_sync_step = step
        # Data-loader lag: host ms blocked per batch since the last sync.
        wait_s, wait_b = self._loader_wait_snapshot()
        batches_d = wait_b - self._last_wait[1]
        if batches_d > 0:
            self.last_loader_wait_ms = (
                (wait_s - self._last_wait[0]) / batches_d * 1e3
            )
            self.g_loader_wait.set(self.last_loader_wait_ms)
        self._last_wait = (wait_s, wait_b)
        # Analytic collective-comms accounting (trace-time, so the total
        # for a once-compiled step IS bytes-per-step) and the
        # comms/compute ratio beside MFU.
        from ml_trainer_tpu.parallel.comm_stats import comm_bytes_total

        comm_b = comm_bytes_total()
        comm_ratio = None
        self.g_comm_bytes.set(comm_b)
        if self.flops_per_step:
            comm_ratio = comm_b / self.flops_per_step
            self.g_comm_ratio.set(comm_ratio)
        skipped_d = skipped_total - self._last_skipped
        self._last_skipped = skipped_total
        # Goodput: cumulative wall-clock decomposition since fit() start
        # (gauges + the fraction for the event/heartbeat below).
        gp = self.goodput.report() if self.goodput.started else None
        self.g_loss.set(host["loss_raw"])
        self.g_grad.set(host["grad_norm"])
        self.g_param.set(host["param_norm"])
        self.g_upd.set(host["update_norm"])
        self.g_ratio.set(host["update_ratio"])
        self.g_lr_scale.set(lr_scale)
        if loss_scale is not None:
            self.g_loss_scale.set(float(loss_scale))
        if steps_d > 0:
            self.c_steps.inc(steps_d)
        if skipped_d > 0:
            self.c_skipped.inc(skipped_d)
        event = {
            "step": int(step),
            "epoch": int(epoch),
            "model": self.model_name,
            **{k: round(v, 6) for k, v in host.items()},
            "skipped_total": int(skipped_total),
            "skipped_delta": int(skipped_d),
            "lr_scale": float(lr_scale),
        }
        if sps is not None:
            event["samples_per_sec"] = round(sps, 1)
        if tps is not None:
            event["tokens_per_sec"] = round(tps, 1)
        if mfu is not None:
            event["mfu"] = round(mfu, 4)
        if self._step_ms:
            event["step_ms_p50"] = round(self.step_ms_p50(), 3)
            event["step_ms_p99"] = round(self.step_ms_p99(), 3)
        event["loader_wait_ms"] = round(self.last_loader_wait_ms, 3)
        if gp is not None:
            event["goodput_fraction"] = round(gp["goodput_fraction"], 4)
        if loss_scale is not None:
            event["loss_scale"] = float(loss_scale)
        if self.overlap_fraction is not None:
            event["overlap_fraction"] = round(self.overlap_fraction, 4)
        if comm_b:
            event["comm_bytes_per_step"] = round(comm_b, 1)
        if comm_ratio is not None:
            event["comm_compute_ratio"] = comm_ratio
        # Pipeline-parallel runs: surface the active schedule's analytic
        # bubble (recorded at trace time by parallel/pipeline.py) beside
        # the fenced step-time percentiles — the two halves of the
        # measured-vs-analytic bubble comparison.
        from ml_trainer_tpu.parallel.pipeline import pipeline_schedule_info

        pinfo = pipeline_schedule_info()
        if len(pinfo) == 1:  # exactly one schedule traced: unambiguous
            (sched, info), = pinfo.items()
            event["pipeline_schedule"] = sched
            event["pipeline_bubble_fraction"] = info["bubble_fraction"]
        self.log.info("train_step_telemetry", **event)
        self.flight.record("train_step", **event)
        if skipped_d > 0:
            # Non-finite steps landed in the window ending at ``step``
            # (exact step when the sync cadence is 1) — the record a
            # flight dump needs to name the offending step.
            self.flight.record(
                "nonfinite_steps",
                step=int(step),
                window_start=int(step - steps_d + 1) if steps_d else int(step),
                skipped_delta=int(skipped_d),
                loss_raw=host["loss_raw"],
                grad_norm=host["grad_norm"],
            )
        from ml_trainer_tpu.telemetry.export import default_sink

        sink = default_sink()
        if sink is not None:
            sink.write(event, kind="train_step")
        # Watchtower: the sync point IS the trainer's sample cadence —
        # every registry instrument gains history in the process-wide
        # TSDB (bounded rings, host-only, zero device work).
        from ml_trainer_tpu.telemetry.watchtower import default_store

        default_store().sample_registry(self.registry)
        if self.cluster is not None:
            # Host-local heartbeat refresh; the cross-host allgather stays
            # at the Trainer's epoch boundary (collective discipline).
            self.cluster.heartbeat(
                last_step=step,
                step_ms_p50=self.step_ms_p50(),
                step_ms_p99=self.step_ms_p99(),
                loader_wait_ms=self.last_loader_wait_ms,
                samples_per_sec=self.last_sps,
                skipped_steps_total=skipped_total,
                comm_bytes_total=comm_b,
                goodput_fraction=(
                    gp["goodput_fraction"] if gp is not None else 0.0
                ),
            )
        return host

    def _percentile(self, q: float) -> float:
        if not self._step_ms:
            return 0.0
        s = sorted(self._step_ms)
        return float(s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))])

    def step_ms_p50(self) -> float:
        """Median per-step ms over the recent sync-fenced windows (0.0
        before the first complete window)."""
        return self._percentile(0.5)

    def step_ms_p99(self) -> float:
        return self._percentile(0.99)
