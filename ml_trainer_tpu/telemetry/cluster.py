"""Distributed observability: cross-host aggregation + run reports.

PR 4's telemetry spine is strictly per-process — every host publishes
into its own registry and host 0's Prometheus endpoint shows one host of
an N-host pod.  This module closes the gap with the standard pod-scale
diagnosis loop (MegaScale/ORBIT-style fleet forensics, PAPERS.md):

* **per-host heartbeats** — each process keeps a tiny fixed-schema
  vector of its own health numbers (last step, fenced step-ms p50/p99,
  data-loader wait, throughput, skipped steps, collective bytes);
* **aggregation** — :meth:`ClusterTelemetry.sync` allgathers the
  heartbeat vectors (ONE small [F]-float64 array over DCN via
  ``multihost_utils.process_allgather``; a no-op reshape when
  single-process) and republishes every host's vector as
  ``cluster_<field>{host=h}`` gauges — so host 0's ``/metrics`` scrape
  and JSONL sink cover the whole pod;
* **straggler detection** — a host whose fenced step-ms p50 exceeds the
  cluster median by ``straggler_factor`` fires
  ``cluster_straggler_events_total{host=h}`` and a flight-recorder
  ``straggler`` event naming the host and step.  The median is the
  LOWER median, so on a 2-host cluster the slow host is compared
  against the fast one rather than against their midpoint;
* **run report** — :func:`write_run_report` distills the registry, the
  comm accounting, the span buffer and the flight ring into
  ``run_report.json`` + ``run_report.md``: throughput, MFU, per-host
  step percentiles, comm bytes by op, the skipped-steps/rollback
  ledger, checkpoint write times, and every straggler/desync event.

``sync()`` is a COLLECTIVE whenever ``jax.process_count() > 1``: every
process must call it at the same point (the Trainer calls it at epoch
boundaries, right after ``check_desync`` — same discipline).  Heartbeat
updates are host-local and lock-cheap; call them as often as you like.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from ml_trainer_tpu.telemetry import flight as _flight
from ml_trainer_tpu.telemetry.alerts import AlertEngine, AlertRule
from ml_trainer_tpu.telemetry.registry import default_registry
from ml_trainer_tpu.utils.logging import get_logger

logger = get_logger("ml_trainer_tpu.telemetry")

# One fixed, ordered schema: every host ships exactly this vector, so the
# cross-host gather is a tiny static-shape array (no ragged dict sync).
HEARTBEAT_FIELDS = (
    "last_step",
    "step_ms_p50",
    "step_ms_p99",
    "loader_wait_ms",
    "samples_per_sec",
    "skipped_steps_total",
    "comm_bytes_total",
    # Per-host goodput (telemetry/goodput.py): the fraction of this
    # host's wall-clock spent in productive train compute — a pod host
    # whose goodput sags while its step p50 holds is stalling OUTSIDE
    # the step (input, checkpoints, compiles), which the step
    # percentiles alone cannot show.
    "goodput_fraction",
)


def _lower_median(vals) -> float:
    """Median that never interpolates: with an even host count the lower
    middle value is returned, so a 2-host cluster compares the slow host
    against the FAST one (the midpoint would hide a 2x straggler)."""
    s = sorted(vals)
    return float(s[(len(s) - 1) // 2])


class ClusterTelemetry:
    """Per-host heartbeat + cross-host aggregation + straggler detector."""

    def __init__(self, registry=None, flight=None,
                 straggler_factor: float = 2.0, on_straggler=None):
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must be > 1, got {straggler_factor}"
            )
        import jax

        self.registry = registry if registry is not None else default_registry()
        self.flight = flight if flight is not None else _flight.get_recorder()
        self.straggler_factor = float(straggler_factor)
        # Straggler VERDICT hook: called as (host=, factor=, step=) after
        # the gauge/flight forensics land.  The elastic controller
        # (resilience/elastic.py) subscribes here to turn a persistent
        # straggler into a drain→reshape request; detection stays pure
        # telemetry with or without a subscriber.
        self.on_straggler = on_straggler
        self.host = int(jax.process_index())
        self.n_hosts = int(jax.process_count())
        self._lock = threading.Lock()
        self._local: Dict[str, float] = {f: 0.0 for f in HEARTBEAT_FIELDS}
        r = self.registry
        self._gauges = {
            f: r.gauge(
                f"cluster_{f}",
                f"per-host {f.replace('_', ' ')} (aggregated heartbeat)",
                ("host",),
            )
            for f in HEARTBEAT_FIELDS
        }
        self.g_hosts = r.gauge(
            "cluster_hosts", "hosts seen in the last aggregation"
        )
        self.g_sync_age = r.gauge(
            "cluster_last_sync_unixtime", "wall time of the last aggregation"
        )
        self.c_syncs = r.counter(
            "cluster_syncs_total", "cross-host aggregation rounds"
        )
        self.c_straggler = r.counter(
            "cluster_straggler_events_total",
            "aggregation rounds in which this host exceeded "
            "straggler_factor x the cluster-median step time",
            ("host",),
        )
        # The straggler verdict, re-expressed as an event-mode alert
        # rule (ONE alerting path): every true evaluation fires — no
        # latched state, the legacy re-fire-per-round behavior — and the
        # legacy side effects (counter, flight `straggler` forensics,
        # warning log, on_straggler hook) ride along as the rule's
        # action.
        self.alerts = AlertEngine(registry=self.registry, flight=self.flight)
        self._straggler_rule = self.alerts.add_rule(AlertRule(
            "cluster_straggler", mode="event", severity="warn",
            actions=(self._straggler_fired,),
            description=(
                f"host step-ms p50 above {self.straggler_factor:g}x the "
                "cluster lower-median"
            ),
        ))

    # -- host-local -----------------------------------------------------
    def heartbeat(self, **fields) -> None:
        """Update this host's heartbeat values (any subset of
        ``HEARTBEAT_FIELDS``).  Host-local, lock-cheap — safe at the
        trainer's per-sync cadence."""
        unknown = set(fields) - set(HEARTBEAT_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown heartbeat fields {sorted(unknown)}; "
                f"expected a subset of {HEARTBEAT_FIELDS}"
            )
        with self._lock:
            for k, v in fields.items():
                self._local[k] = float(v)

    def local_vector(self) -> np.ndarray:
        with self._lock:
            return np.asarray(
                [self._local[f] for f in HEARTBEAT_FIELDS], np.float64
            )

    # -- cross-host -----------------------------------------------------
    def sync(self, step: Optional[int] = None) -> np.ndarray:
        """Gather every host's heartbeat and republish the cluster view.

        COLLECTIVE when multi-process (every process must call it at the
        same program point); a pure local publish when single-process.
        Returns the gathered ``[n_hosts, len(HEARTBEAT_FIELDS)]`` matrix.
        """
        vec = self.local_vector()
        if self.n_hosts > 1:
            from jax.experimental import multihost_utils

            all_vecs = np.asarray(
                multihost_utils.process_allgather(vec), np.float64
            ).reshape(self.n_hosts, len(HEARTBEAT_FIELDS))
        else:
            all_vecs = vec[None, :]
        self._ingest(all_vecs, step=step)
        return all_vecs

    def _ingest(self, all_vecs: np.ndarray, step: Optional[int] = None) -> None:
        """Publish one gathered heartbeat matrix as ``cluster_*{host=}``
        gauges and run straggler detection over it.  Split from ``sync``
        so single-process tests can inject a fabricated pod."""
        all_vecs = np.asarray(all_vecs, np.float64)
        for h in range(all_vecs.shape[0]):
            for i, f in enumerate(HEARTBEAT_FIELDS):
                self._gauges[f].labels(host=h).set(float(all_vecs[h, i]))
        self.g_hosts.set(all_vecs.shape[0])
        self.g_sync_age.set(time.time())
        self.c_syncs.inc()
        self._detect_stragglers(all_vecs, step=step)

    def _detect_stragglers(self, all_vecs: np.ndarray,
                           step: Optional[int] = None) -> None:
        col = HEARTBEAT_FIELDS.index("step_ms_p50")
        times = all_vecs[:, col]
        live = [float(t) for t in times if t > 0]
        if len(live) < 2:
            return  # one host (or no data): no cluster to straggle behind
        median = _lower_median(live)
        if median <= 0:
            return
        for h, t in enumerate(times):
            self.alerts.observe(
                "cluster_straggler",
                float(t) > self.straggler_factor * median,
                value=float(t),
                labels={"host": str(int(h))},
                extra={
                    "host": int(h),
                    "step": int(step) if step is not None else None,
                    "step_ms_p50": round(float(t), 3),
                    "cluster_median_ms": round(median, 3),
                    "factor": round(float(t) / median, 2),
                    # Unrounded, for the on_straggler verdict hook (the
                    # elastic controller thresholds on it).
                    "factor_raw": float(t) / median,
                },
            )

    def _straggler_fired(self, ev: dict) -> None:
        """The rule's action: the legacy straggler side effects, fed the
        emitted alert event (which carries the detection forensics as
        ``extra`` fields)."""
        h = int(ev["host"])
        self.c_straggler.labels(host=h).inc()
        self.flight.record(
            "straggler",
            host=h,
            step=ev["step"],
            step_ms_p50=ev["step_ms_p50"],
            cluster_median_ms=ev["cluster_median_ms"],
            factor=ev["factor"],
        )
        logger.warning(
            f"straggler: host {h} step p50 {ev['step_ms_p50']:.1f}ms vs "
            f"cluster median {ev['cluster_median_ms']:.1f}ms "
            f"(>{self.straggler_factor:g}x, step {ev['step']})"
        )
        if self.on_straggler is not None:
            self.on_straggler(
                host=h, factor=float(ev["factor_raw"]), step=ev["step"],
            )

    def cluster_view(self) -> Dict[str, Dict[str, float]]:
        """The last published cluster state, host -> field -> value (from
        the registry — available on any host after a ``sync``)."""
        out: Dict[str, Dict[str, float]] = {}
        for f, g in self._gauges.items():
            for key, v in g.series().items():
                out.setdefault(key[0], {})[f] = float(v)
        return out


# ---------------------------------------------------------------- report
def _labeled(snap: dict, prefix: str) -> Dict[str, float]:
    """Parse ``name{label=value}`` gauge keys back into value -> number."""
    out: Dict[str, float] = {}
    head = prefix + "{"
    for k, v in snap.items():
        if k.startswith(head) and k.endswith("}"):
            label = k[len(head):-1].split("=", 1)[-1]
            out[label] = v
    return out


def _goodput_section(snap: dict) -> dict:
    buckets = {
        b: round(v, 3)
        for b, v in _labeled(snap, "train_goodput_seconds_total").items()
    }
    out = {"buckets_secs": buckets}
    for key, name in (
        ("train_goodput_fraction", "goodput_fraction"),
        ("train_goodput_compute_seconds_total", "compute_secs"),
    ):
        if key in snap:
            out[name] = round(snap[key], 4)
    return out


def _memory_section(snap: dict) -> dict:
    out: dict = {}
    comp = _labeled(snap, "mem_analytic_bytes")
    if comp:
        out["analytic_components"] = {k: int(v) for k, v in comp.items()}
    for key in ("mem_analytic_resident_bytes", "mem_analytic_peak_bytes"):
        if key in snap:
            out[key[4:]] = int(snap[key])
    live = _labeled(snap, "mem_live_bytes")
    if live:
        out["live_bytes_by_device"] = {k: int(v) for k, v in live.items()}
    peak = _labeled(snap, "mem_live_peak_bytes")
    if peak:
        out["live_peak_bytes_by_device"] = {
            k: int(v) for k, v in peak.items()
        }
    return out


def _compile_section(snap: dict) -> dict:
    out: dict = {
        "by_fn": {
            k: int(v) for k, v in _labeled(snap, "compile_events_total").items()
        },
    }
    out["total"] = int(sum(out["by_fn"].values()))
    if "compile_events_post_warmup_total" in snap:
        out["post_warmup"] = int(snap["compile_events_post_warmup_total"])
    return out


def _ckpt_write_stats() -> dict:
    """Checkpoint write-time stats harvested from the span buffer."""
    from ml_trainer_tpu.telemetry.spans import trace_events

    durs = {}
    for ev in trace_events():
        if ev.get("name") in ("ckpt_write", "ckpt_write_io") and "dur" in ev:
            durs.setdefault(ev["name"], []).append(ev["dur"] / 1e3)  # ms
    out = {}
    for name, ms in durs.items():
        s = sorted(ms)
        out[name] = {
            "count": len(s),
            "total_ms": round(sum(s), 3),
            "p50_ms": round(s[(len(s) - 1) // 2], 3),
            "max_ms": round(s[-1], 3),
        }
    return out


def _markdown_report(report: dict) -> str:
    lines = [
        "# Run report",
        "",
        f"* **reason**: {report['reason']}",
        f"* **written at**: {report['written_at_iso']}",
        f"* **hosts**: {report.get('n_hosts', 1)}",
        "",
        "## Throughput",
        "",
    ]
    thr = report.get("throughput", {})
    for k in sorted(thr):
        lines.append(f"* {k}: {thr[k]}")
    hosts = report.get("hosts", {})
    if hosts:
        lines += ["", "## Per-host heartbeat", ""]
        fields = list(HEARTBEAT_FIELDS)
        lines.append("| host | " + " | ".join(fields) + " |")
        lines.append("|---" * (len(fields) + 1) + "|")
        for h in sorted(hosts, key=lambda x: int(x)):
            row = hosts[h]
            lines.append(
                f"| {h} | "
                + " | ".join(str(row.get(f, "")) for f in fields)
                + " |"
            )
    comm = report.get("comm_bytes_by_op", {})
    lines += ["", "## Collective comms (analytic, trace-time)", ""]
    if comm:
        lines.append("| op | bytes |")
        lines.append("|---|---|")
        for op in sorted(comm):
            lines.append(f"| {op} | {int(comm[op]):,} |")
    else:
        lines.append("no explicit collectives traced")
    gp = report.get("goodput", {})
    if gp.get("buckets_secs") or "goodput_fraction" in gp:
        lines += ["", "## Goodput", ""]
        if "goodput_fraction" in gp:
            lines.append(f"* goodput fraction: {gp['goodput_fraction']}")
        if "compute_secs" in gp:
            lines.append(f"* compute seconds: {gp['compute_secs']}")
        for b, v in sorted(gp.get("buckets_secs", {}).items()):
            lines.append(f"* {b}: {v}s")
    mem = report.get("memory", {})
    if mem.get("analytic_components"):
        lines += ["", "## Memory ledger (analytic, per device)", ""]
        lines.append("| component | bytes |")
        lines.append("|---|---|")
        for c, b in sorted(mem["analytic_components"].items()):
            lines.append(f"| {c} | {int(b):,} |")
        for key in ("analytic_resident_bytes", "analytic_peak_bytes"):
            if key in mem:
                lines.append(f"| {key} | {int(mem[key]):,} |")
    comp = report.get("compiles", {})
    if comp.get("total"):
        lines += [
            "", "## Compiles", "",
            f"* total: {comp['total']}"
            + (f", post-warmup: {comp['post_warmup']}"
               if comp.get("post_warmup") else ""),
        ]
    res = report.get("resilience", {})
    lines += [
        "",
        "## Resilience ledger",
        "",
        f"* skipped steps per epoch: {res.get('skipped_steps', [])}",
        f"* rollbacks: {res.get('rollbacks', 0)}",
        f"* straggler events: {res.get('straggler_events', 0)}",
        f"* desync events: {res.get('desync_events', 0)}",
        f"* elastic reshapes: {len(res.get('reshapes', []))}",
    ]
    for r in res.get("reshapes", []):
        lines.append(f"  * `{json.dumps(r, default=str)}`")
    ckpt = report.get("checkpoint_writes", {})
    if ckpt:
        lines += ["", "## Checkpoint writes", ""]
        for name in sorted(ckpt):
            c = ckpt[name]
            lines.append(
                f"* {name}: {c['count']} write(s), p50 {c['p50_ms']}ms, "
                f"max {c['max_ms']}ms"
            )
    events = report.get("events", [])
    if events:
        lines += ["", "## Straggler / desync / rollback events", ""]
        for ev in events:
            lines.append(f"* `{json.dumps(ev, default=str)}`")
    return "\n".join(lines) + "\n"


def write_run_report(out_dir: str, *, history: Optional[dict] = None,
                     registry=None, flight=None, reason: str = "completed",
                     extra: Optional[dict] = None) -> dict:
    """Distill the telemetry spine into ``run_report.json`` + a markdown
    twin and return the report dict (paths under ``report['paths']``).

    Called by the Trainer at the end of ``fit()`` (and best-effort on a
    crash, right after the flight-recorder dump) — but freestanding, so
    any driver that populated the registry can emit one.  Writes are
    atomic (tmp + rename) and never raise: a report must not take down
    the run it is documenting.
    """
    registry = registry if registry is not None else default_registry()
    flight = flight if flight is not None else _flight.get_recorder()
    snap = registry.snapshot()

    def pick(prefix: str) -> dict:
        return {
            k: v for k, v in snap.items()
            if k.startswith(prefix) and "{" not in k
        }

    # Per-host cluster view, parsed back from the labeled gauge snapshot.
    hosts: Dict[str, dict] = {}
    for f in HEARTBEAT_FIELDS:
        key_prefix = f"cluster_{f}{{host="
        for k, v in snap.items():
            if k.startswith(key_prefix):
                h = k[len(key_prefix):-1]
                hosts.setdefault(h, {})[f] = v

    from ml_trainer_tpu.parallel.comm_stats import (
        comm_bucket_bytes,
        comm_bytes,
        comm_calls,
        comm_hop_bytes,
    )
    from ml_trainer_tpu.parallel.pipeline import pipeline_schedule_info

    event_kinds = ("straggler", "desync", "rollback", "preemption",
                   "nonfinite_steps", "reshape")
    events = [r for r in flight.records() if r.get("kind") in event_kinds]
    straggler_events = int(sum(
        v for k, v in snap.items()
        if k.startswith("cluster_straggler_events_total")
    ))
    desync_events = int(snap.get("cluster_desync_events_total", 0))
    history = history or {}
    report = {
        "reason": reason,
        "written_at": time.time(),
        "written_at_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "n_hosts": max(len(hosts), 1),
        "throughput": {
            k: snap[k]
            for k in (
                "train_samples_per_sec", "train_tokens_per_sec", "train_mfu",
                "train_steps_total", "train_step_ms_p50", "train_step_ms_p99",
                "train_comm_bytes_per_step", "train_comm_compute_ratio",
                "train_overlap_fraction",
            )
            if k in snap
        },
        "train_gauges": pick("train_"),
        "hosts": hosts,
        "comm_bytes_by_op": {k: round(v, 1) for k, v in comm_bytes().items()},
        "comm_calls_by_op": comm_calls(),
        # Per-bucket breakdown of the bucketed collectives (empty unless
        # the sharded-update path ran): {op: {bucket: bytes}}.
        "comm_bytes_by_bucket": {
            op: {b: round(v, 1) for b, v in bs.items()}
            for op, bs in comm_bucket_bytes().items()
        },
        # Per-hop breakdown of the pipeline schedules (empty unless a
        # pipelined model ran): {schedule: {fwd|bwd|fwd_recompute|
        # output_broadcast|grad_input_broadcast: bytes}}.
        "comm_bytes_by_hop": {
            schedule: {h: round(v, 1) for h, v in hs.items()}
            for schedule, hs in comm_hop_bytes().items()
        },
        # Analytic tick-table facts per traced pipeline schedule (bubble
        # fractions, stash sizing — parallel/pipeline.py).
        "pipeline_schedules": pipeline_schedule_info(),
        "resilience": {
            "skipped_steps": history.get("skipped_steps", []),
            "rollbacks": history.get("rollbacks", 0),
            "straggler_events": straggler_events,
            "desync_events": desync_events,
            # Elastic mesh reshapes this run survived (old/new topology,
            # trigger, rescaled batch/LR — resilience/elastic.py).
            "reshapes": history.get("reshapes", []),
        },
        # Wall-clock decomposition (telemetry/goodput.py): where the
        # run's time went, and the goodput fraction that summarizes it.
        "goodput": _goodput_section(snap),
        # HBM ledger (telemetry/memory.py): analytic per-component bytes
        # beside the live per-device view.
        "memory": _memory_section(snap),
        # Recompile forensics (telemetry/compile_watch.py): compile
        # counts by function; post-warmup compiles are incidents.
        "compiles": _compile_section(snap),
        "checkpoint_writes": _ckpt_write_stats(),
        "history": {
            k: history[k]
            for k in ("epochs", "train_loss", "val_loss")
            if k in history
        },
        "events": events[-64:],
    }
    if extra:
        report.update(extra)
    json_path = os.path.join(out_dir, "run_report.json")
    md_path = os.path.join(out_dir, "run_report.md")
    try:
        os.makedirs(out_dir, exist_ok=True)
        tmp = json_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fp:
            json.dump(report, fp, indent=1, default=str)
        os.replace(tmp, json_path)
        tmp = md_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fp:
            fp.write(_markdown_report(report))
        os.replace(tmp, md_path)
        report["paths"] = {"json": json_path, "md": md_path}
        # Watchtower snapshot: when the process-wide store holds history
        # (the trainer sampled at its sync cadence), the report gains
        # the dashboard the metrics LOOKED like over the run — numbers
        # age out of gauges, the rings keep the trend.
        from ml_trainer_tpu.telemetry.watchtower import (
            default_store, save_dashboard,
        )

        store = default_store()
        if len(store):
            dash_path = os.path.join(out_dir, "dashboard.html")
            save_dashboard(store, dash_path, title=f"run report: {reason}")
            report["paths"]["dashboard"] = dash_path
        logger.info(f"run report written: {json_path}")
    except OSError as e:
        logger.error(f"run report write failed ({json_path}): {e}")
        report["paths"] = {}
    return report
