"""Metrics registry: thread-safe counters / gauges / histograms with labels.

The in-process analog of a Prometheus client library, dependency-free
(the container is zero-egress): every layer registers its instruments
against one :class:`MetricsRegistry` — usually the process-wide
:func:`default_registry` — and the exporters (``export.py``) turn the
whole registry into Prometheus text exposition or one JSONL record.

Design points:

* **Idempotent registration.**  ``registry.counter("x", ...)`` returns
  the existing instrument when ``x`` is already registered (with a type
  check), so the trainer, the serving engine, and tests can all say
  "give me the counter" without coordinating creation order.
* **Labels are call-site cheap.**  ``c.labels(model="gpt2").inc()``
  resolves to a child keyed by the label values; unlabeled instruments
  skip the child map entirely.
* **One lock per instrument**, not a global registry lock, so the
  serving engine's per-step ``inc`` never contends with the trainer's
  epoch-end gauge writes.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

# Prometheus-ish default latency buckets (seconds), wide enough to cover
# both a CPU LeNet step (~ms) and a remote-tunnel compile (~minutes).
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _validate_name(name: str) -> str:
    if not name or not all(c.isalnum() or c in "_:" for c in name):
        raise ValueError(
            f"metric name must be non-empty [a-zA-Z0-9_:]+, got {name!r}"
        )
    return name


class _Child:
    """One (instrument, label-values) time series."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "_Metric", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._key, amount)

    def set(self, value: float) -> None:
        self._metric._set(self._key, value)

    def observe(self, value: float) -> None:
        self._metric._observe(self._key, value)

    def get(self):
        return self._metric._get(self._key)


class _Metric:
    """Base instrument: a dict of label-values -> series under one lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = _validate_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            _validate_name(ln)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # Pre-create the single unlabeled series so reads never miss.
            self._series[()] = self._new_series()

    def _new_series(self):
        return 0.0

    def labels(self, **labelvalues) -> _Child:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels() needs exactly {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            if key not in self._series:
                self._series[key] = self._new_series()
        return _Child(self, key)

    def _require_unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} carries labels {self.labelnames}; "
                "use .labels(...) first"
            )

    # Unlabeled conveniences -------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        self._require_unlabeled()
        self._inc((), amount)

    def set(self, value: float) -> None:
        self._require_unlabeled()
        self._set((), value)

    def observe(self, value: float) -> None:
        self._require_unlabeled()
        self._observe((), value)

    def get(self):
        self._require_unlabeled()
        return self._get((), )

    # Series ops (overridden per kind) ---------------------------------
    def _inc(self, key, amount):
        raise NotImplementedError

    def _set(self, key, value):
        raise NotImplementedError

    def _observe(self, key, value):
        raise NotImplementedError

    def _get(self, key):
        with self._lock:
            return self._series.get(key, 0.0)

    def series(self) -> Dict[Tuple[str, ...], object]:
        """Point-in-time copy of every (label-values -> value) series."""
        with self._lock:
            return dict(self._series)


class Counter(_Metric):
    """Monotonic count.  ``inc`` only; negative increments are rejected."""

    kind = "counter"

    def _inc(self, key, amount):
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up "
                             f"(inc {amount})")
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def _set(self, key, value):
        raise TypeError(f"{self.name} is a counter; use inc()")

    def _observe(self, key, value):
        raise TypeError(f"{self.name} is a counter; use inc()")


class Gauge(_Metric):
    """A value that can go anywhere: set() or inc() (either sign)."""

    kind = "gauge"

    def _inc(self, key, amount):
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def _set(self, key, value):
        with self._lock:
            self._series[key] = float(value)

    def _observe(self, key, value):
        raise TypeError(f"{self.name} is a gauge; use set()/inc()")


class _HistSeries:
    __slots__ = ("counts", "total", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # cumulative at exposition time
        self.total = 0.0
        self.count = 0


class Histogram(_Metric):
    """Bucketed distribution (Prometheus ``le`` semantics: each bucket
    counts observations <= its upper bound, plus the implicit +Inf)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(
                f"{name}: buckets must be a non-empty ascending sequence"
            )
        self.buckets = tuple(float(b) for b in buckets)
        super().__init__(name, help, labelnames)

    def _new_series(self):
        return _HistSeries(len(self.buckets))

    def _inc(self, key, amount):
        raise TypeError(f"{self.name} is a histogram; use observe()")

    def _set(self, key, value):
        raise TypeError(f"{self.name} is a histogram; use observe()")

    def _observe(self, key, value):
        value = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = self._new_series()
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    s.counts[i] += 1
                    break
            s.total += value
            s.count += 1

    def _get(self, key):
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return None
            return {"count": s.count, "sum": s.total,
                    "buckets": list(s.counts)}


class MetricsRegistry:
    """A named collection of instruments with idempotent registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name, help, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or (
                    tuple(labelnames) != existing.labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def collect(self):
        """Instruments in registration order (stable exposition)."""
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> dict:
        """JSON-safe flat view: ``name`` (or ``name{a=b}``) -> value.
        Histograms flatten to ``name_count`` / ``name_sum``."""
        out: dict = {}
        for m in self.collect():
            for key, _ in sorted(m.series().items()):
                suffix = (
                    "{" + ",".join(
                        f"{ln}={lv}" for ln, lv in zip(m.labelnames, key)
                    ) + "}" if key else ""
                )
                if m.kind == "histogram":
                    h = m._get(key)
                    out[f"{m.name}_count{suffix}"] = h["count"]
                    out[f"{m.name}_sum{suffix}"] = round(h["sum"], 9)
                else:
                    out[f"{m.name}{suffix}"] = m._get(key)
        return out

    def prometheus_text(self) -> str:
        from ml_trainer_tpu.telemetry.export import prometheus_text

        return prometheus_text(self)


# -- process-wide default registry --------------------------------------
_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every layer publishes into by default."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def reset_default_registry() -> None:
    """Drop the process-wide registry (tests only — live handles held by
    long-lived objects keep publishing into the old one)."""
    global _default
    with _default_lock:
        _default = None
