"""Flight recorder: the last N step records and events, dumped on crash.

Logs scroll away and metrics aggregate; what a post-mortem needs is the
exact sequence of steps right before the failure.  The recorder is a
bounded ring buffer of small dicts — ``record()`` is a locked deque
append, cheap enough for per-step use — and ``dump()`` writes the whole
ring plus the triggering reason to ``flight_<timestamp>.json``.

Dump sites (wired by the owning layers):

* trainer: NaN-rollback (``rollback_bad_steps`` tripped), preemption
  exit, and unhandled exceptions escaping ``fit()``;
* serving: watchdog trip and engine-thread death
  (``Server._mark_unhealthy``).

The dump directory resolves, in order: the ``out_dir`` argument, the
``ML_TRAINER_TPU_FLIGHT_DIR`` env var, the recorder's ``default_dir``
(the trainer sets its ``model_dir``), then the system temp dir — so
chaos tests point everything at a tmpdir with one env var.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Optional

from ml_trainer_tpu.utils.logging import get_logger

logger = get_logger("ml_trainer_tpu.telemetry")

FLIGHT_DIR_ENV = "ML_TRAINER_TPU_FLIGHT_DIR"


class FlightRecorder:
    """Bounded ring of telemetry records with crash-dump-to-JSON."""

    def __init__(self, capacity: int = 256,
                 default_dir: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.default_dir = default_dir
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._context_providers: dict = {}
        self.last_dump_path: Optional[str] = None

    def register_context_provider(self, name: str, fn) -> None:
        """Attach ``fn()``'s JSON-safe payload to every future dump under
        ``context[name]`` — how the memory ledger and compile watch ride
        along on OOM/wedge forensics without the dump sites knowing them.
        Idempotent by name (latest wins); a provider that raises at dump
        time contributes its error string instead of killing the dump."""
        with self._lock:
            self._context_providers[name] = fn

    def _collect_context(self) -> dict:
        with self._lock:
            providers = dict(self._context_providers)
        out = {}
        for name, fn in providers.items():
            try:
                out[name] = fn()
            except Exception as e:  # forensics must never kill the dump
                out[name] = f"context provider failed: {e}"
        return out

    def record(self, kind: str, **data) -> None:
        """Append one record (thread-safe, O(1), never raises on data —
        non-JSON values are stringified at dump time)."""
        with self._lock:
            self._seq += 1
            self._ring.append(
                {"seq": self._seq, "t": round(time.time(), 6),
                 "kind": kind, **data}
            )

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def _resolve_dir(self, out_dir: Optional[str]) -> str:
        return (
            out_dir
            or os.environ.get(FLIGHT_DIR_ENV)
            or self.default_dir
            or tempfile.gettempdir()
        )

    def payload(self, reason: str, **extra) -> dict:
        """The full dump payload (ring + context providers) WITHOUT
        writing it — what ``dump()`` serializes, and what the fleet
        plane's ``GET /flight`` endpoint (serving/api.py) serves so a
        router can pull a live worker's forensics into an incident
        bundle without the worker touching its own disk."""
        payload = {
            "reason": reason,
            "dumped_at": time.time(),
            "pid": os.getpid(),
            **extra,
            "records": self.records(),
        }
        context = self._collect_context()
        if context:
            payload["context"] = context
        return payload

    def dump(self, reason: str, out_dir: Optional[str] = None,
             **extra) -> Optional[str]:
        """Write ``flight_<ts>.json`` with the ring + reason; returns the
        path, or None when the write itself fails (a dump must never
        take down the process it is documenting)."""
        payload = self.payload(reason, **extra)
        d = self._resolve_dir(out_dir)
        path = os.path.join(
            d, f"flight_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}.json"
        )
        try:
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fp:
                json.dump(payload, fp, default=str)
            os.replace(tmp, path)
        except OSError as e:
            logger.error(f"flight dump failed ({path}): {e}")
            return None
        self.last_dump_path = path
        logger.warning(f"flight recorder dumped: {path} (reason: {reason})")
        return path


# -- process-wide default recorder --------------------------------------
_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder()
        return _default


def reset_recorder() -> None:
    """Tests only: drop the process-wide recorder (long-lived holders of
    the old handle keep recording into it)."""
    global _default
    with _default_lock:
        _default = None
