"""Declarative alert rules over the Watchtower TSDB — ONE alerting path.

Before this module the stack had three ad-hoc watchers, each with its
own streak counter and firing side effects: the autoscaler's burn watch
(serving/autoscaler.py), the deploy canary burn watch
(serving/deploy.py), and the cluster straggler detector
(telemetry/cluster.py).  They now all run as :class:`AlertRule`
instances on an :class:`AlertEngine`, alongside fully declarative rules
evaluated against a :class:`~.watchtower.TimeSeriesStore` — so every
alert, whatever its origin, takes the same path: a flight ``alert``
event, ``alert_active{rule=}`` / ``alerts_fired_total{rule=}``
instruments, the rule's action callbacks, and (severity ``page``) the
router's incident-bundle trigger.

Rule grammar (``expr``)::

    serving_queue_depth > 8                      # threshold on last value
    serving_queue_depth{replica=decode0} > 8     # label-filtered
    rate(requests_total[30s]) < 0.1              # rate of change
    avg(serving_slo_burn_rate{slo=ttft}[60s]) >= 2.0   # windowed burn
    max(train_step_ms_p99[120s]) > 500
    delta(kv_pages_free[60s]) < -100
    quantile(0.5, serving_ttft_seconds[5s]) > 0.2      # histogram window
    absent(cluster_heartbeat_age_s[30s])         # missing / stale series

A selector matching several series evaluates per label group and holds
independent pending/firing state per group (the Prometheus model) —
one rule watches every replica.  ``for_s`` holds a rule in ``pending``
until the predicate stays true that long; ``for_count`` requires that
many CONSECUTIVE true evaluations (the poll-streak semantics the
pre-existing watchers pinned); ``mode="event"`` fires on every true
evaluation with no latched state (the straggler detector's re-fire
behavior).  Watcher-hosted rules skip ``expr`` entirely and are driven
through :meth:`AlertEngine.observe` with an externally supplied clock,
which keeps the existing fake-clock tests pinning them intact.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ml_trainer_tpu.telemetry.watchtower import (
    TimeSeriesStore, bucket_quantile, render_series_key,
)

SEVERITIES = ("info", "warn", "page")

_SEL = (
    r"(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"(?:\[(?P<window>[0-9.]+)s\])?"
)
_OP = r"(?P<op>>=|<=|==|!=|>|<)"
_NUM = r"(?P<threshold>[-+0-9.eE]+)"
_ABSENT_RE = re.compile(rf"^absent\(\s*{_SEL}\s*\)$")
_FUNC_RE = re.compile(
    rf"^(?P<fn>rate|avg|max|min|delta)\(\s*{_SEL}\s*\)\s*{_OP}\s*{_NUM}$"
)
_QUANT_RE = re.compile(
    rf"^quantile\(\s*(?P<q>[0-9.]+)\s*,\s*{_SEL}\s*\)\s*{_OP}\s*{_NUM}$"
)
_LAST_RE = re.compile(rf"^{_SEL}\s*{_OP}\s*{_NUM}$")

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def _parse_labels(text: Optional[str]) -> dict:
    out: dict = {}
    for pair in filter(None, (p.strip() for p in (text or "").split(","))):
        key, sep, value = pair.partition("=")
        if not sep:
            raise ValueError(f"malformed label matcher {pair!r}")
        out[key.strip()] = value.strip().strip('"')
    return out


def _win(points: list, window_s: Optional[float],
         now: Optional[float]) -> list:
    return TimeSeriesStore._window(points, window_s, now)


def _rate_of(points: list) -> Optional[float]:
    if len(points) < 2:
        return None
    span = points[-1][0] - points[0][0]
    if span <= 0:
        return None
    increase = 0.0
    for (_, prev), (_, cur) in zip(points, points[1:]):
        increase += cur - prev if cur >= prev else cur
    return increase / span


def _compile_expr(expr: str) -> Callable:
    """``expr`` -> ``fn(store, now) -> [(labels, ok, value), ...]``.

    Per matched label group: ``ok`` is the predicate verdict, ``None``
    when the window holds no data (the caller decides whether no-data
    resolves or holds the rule)."""
    expr = expr.strip()

    m = _ABSENT_RE.match(expr)
    if m is not None:
        name = m.group("name")
        labels = _parse_labels(m.group("labels"))
        window = float(m.group("window")) if m.group("window") else None

        def _eval_absent(store, now):
            ok = store.absent(name, labels, within_s=window, now=now)
            return [(dict(labels), bool(ok), None)]

        return _eval_absent

    m = _QUANT_RE.match(expr)
    if m is not None:
        name = m.group("name")
        labels = _parse_labels(m.group("labels"))
        window = float(m.group("window")) if m.group("window") else None
        q = float(m.group("q"))
        cmp = _OPS[m.group("op")]
        threshold = float(m.group("threshold"))

        def _eval_quantile(store, now):
            out = []
            groups = store.bucket_deltas(name, labels, window, now)
            for gkey, deltas in sorted(groups.items()):
                value = bucket_quantile(deltas, q)
                ok = cmp(value, threshold) if value is not None else None
                out.append((dict(gkey), ok, value))
            return out

        return _eval_quantile

    m = _FUNC_RE.match(expr) or _LAST_RE.match(expr)
    if m is None:
        raise ValueError(f"unparseable alert expr {expr!r}")
    fn = m.groupdict().get("fn") or "last"
    name = m.group("name")
    labels = _parse_labels(m.group("labels"))
    window = float(m.group("window")) if m.group("window") else None
    cmp = _OPS[m.group("op")]
    threshold = float(m.group("threshold"))

    def _eval_series(store, now):
        out = []
        for slabels, points in store.select(name, labels):
            pts = _win(points, window, now)
            if fn == "last":
                value = pts[-1][1] if pts else None
            elif fn == "rate":
                value = _rate_of(pts)
            elif fn == "delta":
                value = (
                    pts[-1][1] - pts[0][1] if len(pts) >= 2 else None
                )
            elif fn == "avg":
                value = (
                    sum(v for _, v in pts) / len(pts) if pts else None
                )
            elif fn == "max":
                value = max((v for _, v in pts), default=None)
            else:  # min
                value = min((v for _, v in pts), default=None)
            ok = cmp(value, threshold) if value is not None else None
            out.append((slabels, ok, value))
        return out

    return _eval_series


class _GroupState:
    __slots__ = ("state", "count", "since", "value", "fired_at")

    def __init__(self):
        self.state = "inactive"  # inactive | pending | firing
        self.count = 0
        self.since: Optional[float] = None
        self.value: Optional[float] = None
        self.fired_at: Optional[float] = None


class AlertRule:
    """One declarative (``expr``) or externally-driven (``observe``)
    alert rule, with per-label-group pending/firing state."""

    def __init__(self, name: str, expr: Optional[str] = None, *,
                 for_s: float = 0.0, for_count: int = 1,
                 severity: str = "warn", mode: str = "level",
                 labels: Optional[dict] = None,
                 actions: Sequence[Callable] = (),
                 on_no_data: str = "resolve",
                 description: str = ""):
        if severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got {severity!r}"
            )
        if mode not in ("level", "event"):
            raise ValueError(f"mode must be level|event, got {mode!r}")
        if on_no_data not in ("resolve", "skip"):
            raise ValueError(
                f"on_no_data must be resolve|skip, got {on_no_data!r}"
            )
        if for_count < 1:
            raise ValueError(f"for_count must be >= 1, got {for_count}")
        self.name = name
        self.expr = expr
        self._eval = _compile_expr(expr) if expr is not None else None
        self.for_s = float(for_s)
        self.for_count = int(for_count)
        self.severity = severity
        self.mode = mode
        self.labels = dict(labels or {})
        self.actions = list(actions)
        self.on_no_data = on_no_data
        self.description = description
        self._lock = threading.Lock()
        self._groups: Dict[tuple, _GroupState] = {}

    # -- state ------------------------------------------------------------

    def _group(self, labels: Optional[dict]) -> Tuple[tuple, _GroupState]:
        gkey = tuple(sorted(
            (str(k), str(v)) for k, v in (labels or {}).items()
        ))
        with self._lock:
            st = self._groups.get(gkey)
            if st is None:
                st = self._groups[gkey] = _GroupState()
        return gkey, st

    def firing(self, labels: Optional[dict] = None) -> bool:
        """True when the (label group's) state is ``firing``."""
        if labels is None:
            with self._lock:
                return any(
                    st.state == "firing" for st in self._groups.values()
                )
        _, st = self._group(labels)
        return st.state == "firing"

    def n_firing(self) -> int:
        with self._lock:
            return sum(
                1 for st in self._groups.values() if st.state == "firing"
            )

    def count(self, labels: Optional[dict] = None) -> int:
        """Consecutive true evaluations of the group — the poll streak
        the pre-engine watchers kept by hand."""
        _, st = self._group(labels)
        return st.count

    def reset(self, labels: Optional[dict] = None) -> None:
        """Forget state (all groups, or one) WITHOUT a resolved event —
        the watchers' post-action streak reset."""
        with self._lock:
            if labels is None:
                self._groups.clear()
            else:
                gkey = tuple(sorted(
                    (str(k), str(v)) for k, v in labels.items()
                ))
                self._groups.pop(gkey, None)

    def summary(self) -> dict:
        with self._lock:
            groups = {
                render_series_key("", dict(g)) or "<all>": {
                    "state": st.state, "count": st.count,
                    "since": st.since, "value": st.value,
                }
                for g, st in self._groups.items()
            }
        return {
            "name": self.name, "expr": self.expr,
            "severity": self.severity, "mode": self.mode,
            "for_s": self.for_s, "for_count": self.for_count,
            "description": self.description, "groups": groups,
        }


class AlertEngine:
    """Evaluates rules and owns the one firing path: flight ``alert``
    events, ``alert_active{rule=}`` / ``alerts_fired_total{rule=}``,
    rule actions, and the severity-``page`` incident trigger."""

    def __init__(self, rules: Sequence[AlertRule] = (), *,
                 store: Optional[TimeSeriesStore] = None,
                 registry=None, flight=None,
                 incident_trigger: Optional[Callable] = None,
                 history_cap: int = 256,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.rules: "collections.OrderedDict[str, AlertRule]" = (
            collections.OrderedDict()
        )
        self._registry = registry
        self._flight = flight
        self.incident_trigger = incident_trigger
        self._clock = clock
        self._lock = threading.Lock()
        self._history: collections.deque = collections.deque(
            maxlen=history_cap
        )
        self._active_g = None
        self._fired_c = None
        for rule in rules:
            self.add_rule(rule)

    # -- wiring -----------------------------------------------------------

    def add_rule(self, rule: AlertRule) -> AlertRule:
        with self._lock:
            self.rules[rule.name] = rule
        return rule

    def rule(self, name: str) -> AlertRule:
        return self.rules[name]

    def _instruments(self):
        if self._active_g is None:
            if self._registry is None:
                from ml_trainer_tpu.telemetry.registry import (
                    default_registry,
                )

                self._registry = default_registry()
            self._active_g = self._registry.gauge(
                "alert_active",
                "label groups currently firing, by rule",
                labelnames=("rule",),
            )
            self._fired_c = self._registry.counter(
                "alerts_fired_total",
                "alert firings (incl. event-mode re-fires), by rule",
                labelnames=("rule",),
            )
        return self._active_g, self._fired_c

    def _recorder(self):
        if self._flight is not None:
            return self._flight
        from ml_trainer_tpu.telemetry.flight import get_recorder

        return get_recorder()

    # -- the one firing path ----------------------------------------------

    def _emit(self, rule: AlertRule, state: str, value, labels: dict,
              now: float, extra: dict) -> dict:
        ev = {
            "t": round(float(now), 6), "rule": rule.name,
            "severity": rule.severity, "state": state,
            "value": value, "labels": dict(rule.labels, **labels),
        }
        if extra:
            ev.update(extra)
        with self._lock:
            self._history.append(ev)
        active_g, fired_c = self._instruments()
        if state in ("firing", "event"):
            fired_c.labels(rule=rule.name).inc()
        active_g.labels(rule=rule.name).set(float(rule.n_firing()))
        self._recorder().record("alert", **{
            k: v for k, v in ev.items() if k != "t"
        })
        for fn in rule.actions:
            try:
                fn(ev)
            except Exception:  # noqa: BLE001 — actions never kill the tick
                pass
        if (
            state in ("firing", "event")
            and rule.severity == "page"
            and self.incident_trigger is not None
        ):
            try:
                self.incident_trigger(
                    f"alert: {rule.name}"
                    + (f" {render_series_key('', ev['labels'])}"
                       if ev["labels"] else "")
                )
            except Exception:  # noqa: BLE001
                pass
        return ev

    def _transition(self, rule: AlertRule, ok: Optional[bool],
                    now: float, value, labels: dict,
                    extra: dict) -> bool:
        """Advance one label group's state machine; returns True when
        the group is firing after this evaluation."""
        if ok is None:
            if rule.on_no_data == "skip":
                return rule.firing(labels)
            ok = False
        if rule.mode == "event":
            if ok:
                self._emit(rule, "event", value, labels, now, extra)
            return bool(ok)
        _, st = rule._group(labels)
        if ok:
            st.count += 1
            st.value = value
            if st.since is None:
                st.since = now
            held = now - st.since >= rule.for_s
            if st.state == "inactive":
                st.state = "pending"
            if (
                st.state == "pending"
                and st.count >= rule.for_count
                and held
            ):
                st.state = "firing"
                st.fired_at = now
                self._emit(rule, "firing", value, labels, now, extra)
        else:
            was_firing = st.state == "firing"
            st.count = 0
            st.since = None
            st.state = "inactive"
            st.value = value
            if was_firing:
                self._emit(rule, "resolved", value, labels, now, extra)
        return st.state == "firing"

    def observe(self, rule_name: str, ok: bool,
                now: Optional[float] = None,
                value: Optional[float] = None,
                labels: Optional[dict] = None,
                extra: Optional[dict] = None) -> bool:
        """Externally-driven evaluation — how the autoscaler / deploy /
        straggler watchers feed their rules (their own clocks, their own
        predicates); returns True while the group is firing."""
        rule = self.rules[rule_name]
        if now is None:
            now = self._clock()
        return self._transition(
            rule, bool(ok), now, value, dict(labels or {}),
            dict(extra or {}),
        )

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One declarative tick: every ``expr`` rule against the store.
        A label group that vanished from the selector resolves (its
        series aged out or the replica left).  Returns the events
        emitted this tick."""
        if self.store is None:
            return []
        if now is None:
            now = self._clock()
        emitted_before = len(self._history)
        for rule in list(self.rules.values()):
            if rule._eval is None:
                continue
            try:
                results = rule._eval(self.store, now)
            except ValueError:
                continue
            seen = set()
            for labels, ok, value in results:
                gkey = tuple(sorted(
                    (str(k), str(v)) for k, v in labels.items()
                ))
                seen.add(gkey)
                self._transition(rule, ok, now, value, labels, {})
            with rule._lock:
                stale = [
                    g for g in rule._groups
                    if g not in seen and rule._groups[g].state != "inactive"
                ]
            for g in stale:
                self._transition(rule, False, now, None, dict(g), {})
        with self._lock:
            return list(self._history)[
                emitted_before - len(self._history):
            ] if len(self._history) > emitted_before else []

    # -- views ------------------------------------------------------------

    def history(self) -> List[dict]:
        with self._lock:
            return list(self._history)

    def active(self) -> List[dict]:
        out = []
        for rule in self.rules.values():
            with rule._lock:
                for g, st in rule._groups.items():
                    if st.state == "firing":
                        out.append({
                            "rule": rule.name,
                            "severity": rule.severity,
                            "labels": dict(g),
                            "since": st.since,
                            "value": st.value,
                        })
        return out

    def payload(self) -> dict:
        """JSON artifact for incident bundles (``alerts.json``)."""
        return {
            "rules": [r.summary() for r in self.rules.values()],
            "active": self.active(),
            "history": self.history(),
        }


def default_fleet_rules() -> List[AlertRule]:
    """A starter rule pack for the router's fleet store: not installed
    by default (existing tests pin the bare router), opt-in via
    ``Router(alert_rules=default_fleet_rules())``."""
    return [
        AlertRule(
            "replica_unreachable",
            'absent(serving_requests_completed[10s])',
            severity="warn",
            description="no fresh samples scraped from any replica",
        ),
        AlertRule(
            "slo_burn_high",
            'avg(serving_slo_burn_rate[60s]) >= 2.0',
            for_s=5.0, severity="page",
            description="fleet SLO burn sustained above budget",
        ),
        AlertRule(
            "kv_pool_exhausted",
            'serving_kv_pages_free < 1',
            for_count=3, severity="warn",
            description="paged KV pool fully allocated",
        ),
        AlertRule(
            "post_warmup_recompile",
            'delta(compile_events_post_warmup_total[300s]) > 0',
            severity="page",
            description="a compiled program changed after warmup",
        ),
    ]
