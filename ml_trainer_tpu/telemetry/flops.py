"""Analytic per-model FLOPs accounting + chip peak tables.

One place owns the numbers two kinds of math previously duplicated:

* **chip peaks** — published per-chip bf16 FLOP/s and HBM bandwidth by
  TPU generation (previously private to ``bench.py`` /
  ``scripts/mfu_ledger.py``);
* **per-step FLOPs** — analytical training-step FLOPs for every
  north-star family (mlmodel / resnet / vit / bert / gpt2 / llama),
  computed from the registry configs' module attributes, so an MFU
  estimate is available where XLA cost analysis is not (the trainer's
  live telemetry, CPU smoke runs, remote-tunnel sessions whose
  ``cost_analysis()`` is unavailable).

Conventions (documented in docs/observability.md):

* matmul/conv FLOPs are ``2 * MACs`` (one multiply + one add);
* a training step is ``3x`` the forward (backward ≈ 2x: grads w.r.t.
  both activations and weights) — the standard MFU bookkeeping
  (PaLM appendix B); optimizer/elementwise work is ignored;
* attention scores count the FULL ``S x S`` interaction for causal and
  bidirectional models alike (the PaLM ``12 * L * d * S`` convention —
  causal masking halves the useful work but not the launched MACs).

These are ESTIMATES for MFU lines and dashboards.  Where a compiled
executable is at hand, XLA's measured ``cost_analysis()`` stays the
source of truth (``bench.py`` prefers it and falls back here).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

# Published peak numbers per chip, keyed by compute dtype.  The bf16 rows
# are the marketed MXU peaks; fp32 matmuls run through the same MXU at
# half rate (multi-pass accumulation), so an fp32 training run's
# attainable ceiling — and therefore an honest MFU denominator — is half
# the bf16 number.  Using the bf16 peak for an fp32 run understates MFU;
# using an fp32 peak for a bf16 run overstates it.
PEAK_FLOPS_BY_DTYPE = {
    "bf16": {
        "v6e": 918e12, "v6": 918e12,
        "v5p": 459e12,
        "v5e": 197e12, "v5 lite": 197e12, "v5lite": 197e12,
        "v4": 275e12,
    },
    "fp32": {
        "v6e": 459e12, "v6": 459e12,
        "v5p": 229.5e12,
        "v5e": 98.5e12, "v5 lite": 98.5e12, "v5lite": 98.5e12,
        "v4": 137.5e12,
    },
    # Int8 matmul peaks (the quantized-decode path's honest MFU
    # denominator, ops/kernels/int8_matmul.py): 2x the bf16 MXU rate on
    # generations with native int8 MACs; v4 has none and runs int8
    # operands through the bf16 pipeline at the bf16 rate.
    "int8": {
        "v6e": 1836e12, "v6": 1836e12,
        "v5p": 918e12,
        "v5e": 394e12, "v5 lite": 394e12, "v5lite": 394e12,
        "v4": 275e12,
    },
}
_DTYPE_ALIASES = {
    "bf16": "bf16", "bfloat16": "bf16",
    "fp32": "fp32", "float32": "fp32", "f32": "fp32",
    "int8": "int8", "i8": "int8",
}
# Back-compat alias (pre-dtype-keyed callers read the bf16 table).
PEAK_FLOPS = PEAK_FLOPS_BY_DTYPE["bf16"]
PEAK_HBM_BYTES = {
    "v6e": 1640e9, "v6": 1640e9,
    "v5p": 2765e9,
    "v5e": 819e9, "v5 lite": 819e9, "v5lite": 819e9,
    "v4": 1228e9,
}
# HBM *capacity* per chip (bytes) — the denominator of the fit-or-OOM
# planner (telemetry/memory.py), next to the bandwidth table above.
HBM_CAPACITY_BYTES = {
    "v6e": 32 * 2 ** 30, "v6": 32 * 2 ** 30,
    "v5p": 95 * 2 ** 30,
    "v5e": 16 * 2 ** 30, "v5 lite": 16 * 2 ** 30, "v5lite": 16 * 2 ** 30,
    "v4": 32 * 2 ** 30,
}
_FALLBACK_GEN = "v5e"


def _match_generation() -> Optional[str]:
    """The TPU generation of the local chip (device kind or the tunnel's
    ``PALLAS_AXON_TPU_GEN`` env), or None when unrecognized."""
    kind = ""
    try:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "").lower()
    except Exception:
        pass
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for key in PEAK_FLOPS:
        if key in gen or key in kind:
            return key
    return None


def chip_peak_flops(dtype: str = "bf16") -> float:
    """Peak FLOP/s of one local chip for ``dtype`` compute ('bf16' /
    'fp32', aliases accepted; v5e fallback generation).  MFU must divide
    by the peak of the dtype the matmuls actually run in."""
    key = _DTYPE_ALIASES.get(str(dtype).lower())
    if key is None:
        raise ValueError(
            f"unknown compute dtype {dtype!r}; expected one of "
            f"{sorted(_DTYPE_ALIASES)}"
        )
    return PEAK_FLOPS_BY_DTYPE[key][_match_generation() or _FALLBACK_GEN]


def chip_peak_hbm_bytes() -> float:
    """Peak HBM bytes/s of one local chip (v5e fallback)."""
    return PEAK_HBM_BYTES[_match_generation() or _FALLBACK_GEN]


def chip_hbm_capacity_bytes() -> float:
    """HBM capacity in bytes of one local chip (v5e fallback) — what an
    analytic memory ledger's peak prediction is judged against."""
    return float(HBM_CAPACITY_BYTES[_match_generation() or _FALLBACK_GEN])


def chip_generation_label() -> str:
    """The matched generation, or an explicit unknown-fallback label so
    artifacts record when the peak tables guessed."""
    m = _match_generation()
    if m is not None:
        return m
    return f"unknown-default-{_FALLBACK_GEN}"


# -- forward-pass FLOPs per model family --------------------------------

def _transformer_fwd(batch: int, seq: int, depth: int, d: int,
                     mlp_dim: int, *, q_heads: int = 0, kv_heads: int = 0,
                     head_dim: int = 0, vocab_head: int = 0,
                     embed_gather: bool = False) -> float:
    """Forward FLOPs of a standard pre-norm transformer trunk.

    Projections: q (+out) at full width, k/v possibly narrower (GQA);
    attention: QK^T + AV over the full S x S window; MLP: in + out
    matmuls; head: one ``d x vocab_head`` matmul when > 0.  Embedding
    lookups are gathers (0 matmul FLOPs)."""
    if not head_dim:
        head_dim = d // max(q_heads or 1, 1)
    q_width = (q_heads or (d // head_dim)) * head_dim
    kv_width = (kv_heads or (q_heads or (d // head_dim))) * head_dim
    per_token = 0.0
    # q, out projections: d -> q_width and q_width -> d.
    per_token += 2.0 * d * q_width * 2
    # k, v projections: d -> kv_width each.
    per_token += 2.0 * d * kv_width * 2
    # attention scores + weighted sum: q_width MACs per (token, key) x2.
    per_token += 2.0 * seq * q_width * 2
    # MLP in + out.
    per_token += 2.0 * d * mlp_dim * 2
    trunk = batch * seq * depth * per_token
    head = batch * seq * 2.0 * d * vocab_head if vocab_head else 0.0
    return trunk + head


def _conv_fwd(h: int, w: int, c_in: int, c_out: int, k: int,
              stride: int = 1, padding: str = "SAME") -> tuple:
    """(FLOPs, h_out, w_out) of one conv on an ``h x w x c_in`` input."""
    if padding == "SAME":
        h_out = -(-h // stride)
        w_out = -(-w // stride)
    else:  # VALID
        h_out = (h - k) // stride + 1
        w_out = (w - k) // stride + 1
    return 2.0 * k * k * c_in * c_out * h_out * w_out, h_out, w_out


def _resnet_fwd(model, batch: int, h: int, w: int, c: int) -> float:
    """Stage-by-stage conv accounting from the module's config
    (stage_sizes + block class), mirroring models/resnet.py exactly."""
    total = 0.0
    if getattr(model, "cifar_stem", False):
        f, h, w = _conv_fwd(h, w, c, 64, 3)
        total += f
    else:
        f, h, w = _conv_fwd(h, w, c, 64, 7, stride=2)
        total += f
        h, w = -(-h // 2), -(-w // 2)  # 3x3/2 maxpool, SAME-ish padding
    c = 64
    bottleneck = model.block.__name__ == "BottleneckBlock"
    expansion = 4 if bottleneck else 1
    for stage, num_blocks in enumerate(model.stage_sizes):
        filters = 64 * 2 ** stage
        out_c = filters * expansion
        for b in range(num_blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            if bottleneck:
                f1, _, _ = _conv_fwd(h, w, c, filters, 1)
                f2, h2, w2 = _conv_fwd(h, w, filters, filters, 3,
                                       stride=stride)
                f3, _, _ = _conv_fwd(h2, w2, filters, out_c, 1)
                total += f1 + f2 + f3
            else:
                f1, h2, w2 = _conv_fwd(h, w, c, filters, 3, stride=stride)
                f2, _, _ = _conv_fwd(h2, w2, filters, filters, 3)
                total += f1 + f2
            if c != out_c or stride != 1:
                fd, _, _ = _conv_fwd(h, w, c, out_c, 1, stride=stride)
                total += fd
            h, w, c = h2, w2, out_c
    total += 2.0 * c * int(model.num_classes)  # head after global pool
    return batch * total


def _mlmodel_fwd(model, batch: int, h: int, w: int, c: int) -> float:
    """The reference LeNet (models/mlmodel.py), conv + dense, VALID."""
    total = 0.0
    f, h, w = _conv_fwd(h, w, c, 6, 5, padding="VALID")
    total += f
    h, w = h // 2, w // 2
    f, h, w = _conv_fwd(h, w, 6, 16, 5, padding="VALID")
    total += f
    h, w = h // 2, w // 2
    flat = h * w * 16
    total += 2.0 * (flat * 120 + 120 * 84 + 84 * int(model.num_classes))
    return batch * total


def fwd_flops(model, batch_shape: Sequence[int]) -> Optional[float]:
    """Analytic forward-pass FLOPs of ``model`` on one ``batch_shape``
    batch, from the module's registry config.  ``model`` may be a module
    instance or a registry name (built with defaults).  Returns None for
    families without an accounting rule — callers must treat that as
    "no MFU estimate", never as zero."""
    if isinstance(model, str):
        from ml_trainer_tpu.models.registry import get_model

        model = get_model(model)
    name = type(model).__name__
    batch = int(batch_shape[0])
    if name == "MLModel":
        _, h, w, c = batch_shape
        return _mlmodel_fwd(model, batch, h, w, c)
    if name == "ResNet":
        _, h, w, c = batch_shape
        return _resnet_fwd(model, batch, h, w, c)
    if name == "VisionTransformer":
        _, h, w, _c = batch_shape
        p = int(model.patch_size)
        seq = (h // p) * (w // p) + 1  # patches + cls token
        d = int(model.embed_dim)
        patch_proj = batch * 2.0 * (h // p) * (w // p) * (p * p *
                                                          batch_shape[3]) * d
        return patch_proj + _transformer_fwd(
            batch, seq, int(model.depth), d, int(model.mlp_dim),
            q_heads=int(model.num_heads),
            vocab_head=0,
        ) + batch * 2.0 * d * int(model.num_classes)
    if name == "BertEncoder":
        _, seq = batch_shape
        d = int(model.embed_dim)
        ncls = int(model.num_classes or 0)
        f = _transformer_fwd(
            batch, int(seq), int(model.depth), d, int(model.mlp_dim),
            q_heads=int(model.num_heads),
        )
        return f + (batch * 2.0 * (d * d + d * ncls) if ncls else 0.0)
    if name in ("GPT2", "GPT2Pipelined"):
        _, seq = batch_shape
        d = int(model.embed_dim)
        depth = int(getattr(model, "depth", 0))
        if not depth:  # pipelined trunk sizes by stages
            depth = int(getattr(model, "n_stages", 0)) * int(
                getattr(model, "blocks_per_stage", 1)
            )
        return _transformer_fwd(
            batch, int(seq), depth, d, 4 * d,
            q_heads=int(model.num_heads),
            vocab_head=int(model.vocab_size),  # tied LM head
        )
    if name == "LlamaLM":
        _, seq = batch_shape
        d = int(model.embed_dim)
        head_dim = d // int(model.num_heads)
        hidden = int(model.hidden_dim) or int(
            ((8 * d // 3) + 127) // 128 * 128
        )
        # SwiGLU MLP: three matmuls (gate, up, down) = 1.5x the pair.
        f = _transformer_fwd(
            batch, int(seq), int(model.depth), d, hidden,
            q_heads=int(model.num_heads),
            kv_heads=int(model.num_kv_heads), head_dim=head_dim,
            vocab_head=int(model.vocab_size),
        )
        extra_gate = (batch * int(seq) * int(model.depth)
                      * 2.0 * d * hidden)
        return f + extra_gate
    return None


def train_step_flops(model, batch_shape: Sequence[int]) -> Optional[float]:
    """Analytic FLOPs of ONE full training step (fwd + bwd ~= 3x fwd)
    on a ``batch_shape`` batch; None when the family has no rule."""
    f = fwd_flops(model, batch_shape)
    return 3.0 * f if f is not None else None
