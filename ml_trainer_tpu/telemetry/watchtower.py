"""Watchtower TSDB: bounded in-process time series behind every gauge.

The registry (``registry.py``) and the fleet federation (``federation.py``)
expose point-in-time values; Watchtower is the history behind them — the
fourth observability pillar (docs/observability.md "Watchtower").  A
:class:`TimeSeriesStore` keeps a bounded ring of ``(t, value)`` points
per series, fed from the cadences the stack already has:

* the trainer's log-sync (``train_metrics.TrainTelemetry.on_sync``)
  samples the process registry;
* a server's ``/metrics`` hit samples its registry right after publish
  (so the router's federation scrape doubles as the worker's sampler);
* the router's health poller ingests every worker's scraped exposition
  (``ingest_exposition``) with ``replica=``/``role=``/``generation=``
  labels, so fleet-level series get history too.

Sampling is pure host work — no device calls, no compiled programs —
and the zero-recompile / byte-identity pins in tests/test_watchtower.py
hold with the store enabled.  Histograms are stored the way Prometheus
exposes them: cumulative ``name_bucket{le=...}`` series plus
``name_sum`` / ``name_count``, so :meth:`quantile_over_time` can diff
the cumulative vectors across a window and interpolate inside the
winning bucket (the ``histogram_quantile`` arithmetic).

The alert engine (``alerts.py``) evaluates declarative rules over this
store; the dashboard (:func:`render_dashboard`) renders it as one
self-contained HTML page of stat tiles + SVG sparklines — stdlib only,
served on ``GET /dash`` by Server and Router and snapshotted into
incident bundles and ``run_report``.
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

CAPACITY_ENV = "ML_TRAINER_TPU_WATCHTOWER_CAP"
DEFAULT_CAPACITY = 512

# Series prefixes every flight dump carries (the `watchtower` context
# provider): the trend INTO a failure, not just the instant.
DEFAULT_FLIGHT_SERIES = (
    "train_goodput_fraction",
    "serving_slo_burn_rate",
    "serving_kv_pages_free",
    "compile_events_post_warmup_total",
)

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace(r"\"", '"').replace(r"\n", "\n").replace(r"\\", "\\")


def _fmt_le(v: float) -> str:
    """Bucket bound rendered the way export.py renders ``le=`` values,
    so registry-sampled and exposition-ingested series share keys."""
    if math.isinf(v):
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _key(name: str, labels: Optional[dict]) -> tuple:
    return (
        name,
        tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items())),
    )


def render_series_key(name: str, labels: dict) -> str:
    """``name{a=b,c=d}`` — the human/JSON spelling of one series."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class TimeSeriesStore:
    """Bounded per-series rings of ``(t, value)`` samples (thread-safe).

    ``capacity`` bounds every series ring (oldest point evicted first);
    ``min_interval_s`` throttles :meth:`sample_registry` /
    :meth:`ingest_exposition` sweeps so a hammered ``/metrics`` endpoint
    cannot grow the store faster than the configured cadence."""

    def __init__(self, capacity: Optional[int] = None,
                 min_interval_s: float = 0.0):
        if capacity is None:
            capacity = int(os.environ.get(CAPACITY_ENV, DEFAULT_CAPACITY))
        if capacity < 2:
            # rate()/quantile_over_time() diff the window's first and
            # last points — a 1-point ring can never answer them.
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.capacity = int(capacity)
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._data: Dict[tuple, collections.deque] = {}
        self._kinds: Dict[str, str] = {}
        self._last_sweep: Dict[str, float] = {}

    # -- ingestion --------------------------------------------------------

    def append(self, name: str, value: float,
               labels: Optional[dict] = None,
               t: Optional[float] = None) -> None:
        """One point on one series (ring-bounded, O(1))."""
        if t is None:
            t = time.time()
        key = _key(name, labels)
        with self._lock:
            ring = self._data.get(key)
            if ring is None:
                ring = self._data[key] = collections.deque(
                    maxlen=self.capacity
                )
            ring.append((float(t), float(value)))

    def _sweep_ok(self, source: str, t: float) -> bool:
        if self.min_interval_s <= 0.0:
            return True
        with self._lock:
            last = self._last_sweep.get(source)
            if last is not None and t - last < self.min_interval_s:
                return False
            self._last_sweep[source] = t
            return True

    def sample_registry(self, registry, t: Optional[float] = None,
                        extra_labels: Optional[dict] = None,
                        force: bool = False) -> int:
        """One sweep over every registry instrument; returns the number
        of points appended.  Histogram series are stored CUMULATIVE per
        ``le`` (exposition shape) beside ``_sum`` / ``_count``."""
        if t is None:
            t = time.time()
        if not force and not self._sweep_ok("registry", t):
            return 0
        extra = dict(extra_labels or {})
        appended = 0
        for m in registry.collect():
            self._kinds.setdefault(m.name, m.kind)
            for key, _ in sorted(m.series().items()):
                labels = dict(zip(m.labelnames, key))
                labels.update(extra)
                if m.kind == "histogram":
                    h = m._get(key)
                    if h is None:
                        continue
                    self.append(f"{m.name}_count", h["count"], labels, t)
                    self.append(f"{m.name}_sum", h["sum"], labels, t)
                    cum = 0
                    for ub, c in zip(m.buckets, h["buckets"]):
                        cum += c
                        self.append(
                            f"{m.name}_bucket", cum,
                            dict(labels, le=_fmt_le(ub)), t,
                        )
                    self.append(
                        f"{m.name}_bucket", h["count"],
                        dict(labels, le="+Inf"), t,
                    )
                    appended += 3 + len(m.buckets)
                else:
                    self.append(m.name, float(m._get(key)), labels, t)
                    appended += 1
        return appended

    def ingest_exposition(self, text: str, t: Optional[float] = None,
                          extra_labels: Optional[dict] = None,
                          source: str = "exposition",
                          force: bool = False) -> int:
        """One Prometheus text exposition (a worker's scraped
        ``/metrics`` bytes) appended as points; ``extra_labels`` are
        merged in (existing labels win) — the federation relabeling
        applied to history.  Returns the number of points appended."""
        from ml_trainer_tpu.telemetry.federation import (
            _SAMPLE_RE, parse_exposition,
        )

        if t is None:
            t = time.time()
        if not force and not self._sweep_ok(source, t):
            return 0
        extra = {
            str(k): str(v) for k, v in (extra_labels or {}).items()
        }
        appended = 0
        for fam in parse_exposition(text):
            if fam.get("type"):
                self._kinds.setdefault(fam["name"], fam["type"])
            for line in fam["samples"]:
                m = _SAMPLE_RE.match(line)
                if m is None:
                    continue
                try:
                    value = float(m.group("rest").split()[0])
                except (ValueError, IndexError):
                    continue
                if math.isnan(value):
                    continue
                labels = {
                    k: _unescape(v)
                    for k, v in _LABEL_RE.findall(m.group("labels") or "")
                }
                for k, v in extra.items():
                    labels.setdefault(k, v)
                self.append(m.group("name"), value, labels, t)
                appended += 1
        return appended

    # -- selection --------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in self._data})

    def kind(self, name: str) -> Optional[str]:
        return self._kinds.get(name)

    def select(self, name: str,
               labels: Optional[dict] = None) -> List[Tuple[dict, list]]:
        """Every series named ``name`` whose labels are a superset of
        ``labels``: ``[(labels_dict, [(t, v), ...]), ...]``."""
        want = {
            (str(k), str(v)) for k, v in (labels or {}).items()
        }
        out = []
        with self._lock:
            for (n, lk), ring in self._data.items():
                if n == name and want <= set(lk):
                    out.append((dict(lk), list(ring)))
        out.sort(key=lambda p: sorted(p[0].items()))
        return out

    def _one(self, name: str, labels: Optional[dict]) -> Optional[list]:
        matched = self.select(name, labels)
        if not matched:
            return None
        if len(matched) > 1:
            raise ValueError(
                f"{render_series_key(name, labels or {})} matches "
                f"{len(matched)} series — add labels to disambiguate"
            )
        return matched[0][1]

    def last(self, name: str, labels: Optional[dict] = None,
             n: int = 1) -> List[Tuple[float, float]]:
        """The last ``n`` points of ONE series (ambiguity raises)."""
        points = self._one(name, labels)
        return list(points[-n:]) if points else []

    def last_value(self, name: str,
                   labels: Optional[dict] = None) -> Optional[float]:
        points = self.last(name, labels, n=1)
        return points[-1][1] if points else None

    def absent(self, name: str, labels: Optional[dict] = None,
               within_s: Optional[float] = None,
               now: Optional[float] = None) -> bool:
        """True when no matching series exists — or, with ``within_s``,
        when none has a sample newer than ``now - within_s`` (a stale
        feed is as alarming as a missing one)."""
        matched = self.select(name, labels)
        if not matched:
            return True
        if within_s is None:
            return False
        now = time.time() if now is None else now
        return all(
            not points or points[-1][0] < now - within_s
            for _, points in matched
        )

    # -- windowed arithmetic ----------------------------------------------

    @staticmethod
    def _window(points: list, window_s: Optional[float],
                now: Optional[float]) -> list:
        if window_s is None or not points:
            return points
        end = points[-1][0] if now is None else now
        lo = end - window_s
        return [p for p in points if lo <= p[0] <= end]

    def rate(self, name: str, labels: Optional[dict] = None,
             window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        """Counter increase per second over the window, reset-aware
        (a decrease — process restart — contributes the new value, the
        Prometheus ``rate()`` convention).  None without >= 2 points."""
        points = self._one(name, labels)
        points = self._window(points or [], window_s, now)
        if len(points) < 2:
            return None
        span = points[-1][0] - points[0][0]
        if span <= 0:
            return None
        increase = 0.0
        for (_, prev), (_, cur) in zip(points, points[1:]):
            increase += cur - prev if cur >= prev else cur
        return increase / span

    def delta(self, name: str, labels: Optional[dict] = None,
              window_s: Optional[float] = None,
              now: Optional[float] = None) -> Optional[float]:
        """last - first over the window (gauge movement)."""
        points = self._one(name, labels)
        points = self._window(points or [], window_s, now)
        if len(points) < 2:
            return None
        return points[-1][1] - points[0][1]

    def avg(self, name: str, labels: Optional[dict] = None,
            window_s: Optional[float] = None,
            now: Optional[float] = None) -> Optional[float]:
        points = self._one(name, labels)
        points = self._window(points or [], window_s, now)
        if not points:
            return None
        return sum(v for _, v in points) / len(points)

    def minmax(self, name: str, fn, labels: Optional[dict] = None,
               window_s: Optional[float] = None,
               now: Optional[float] = None) -> Optional[float]:
        points = self._one(name, labels)
        points = self._window(points or [], window_s, now)
        if not points:
            return None
        return fn(v for _, v in points)

    def bucket_deltas(self, name: str, labels: Optional[dict] = None,
                      window_s: Optional[float] = None,
                      now: Optional[float] = None) -> Dict[tuple, dict]:
        """Per-group cumulative observation counts accumulated INSIDE
        the window, from the stored ``name_bucket{le=}`` series:
        ``{group_labels_tuple: {le_float: cum_count}}`` — the input
        :func:`bucket_quantile` interpolates over.  Groups are the
        non-``le`` label sets (one per tenant/replica/...)."""
        groups: Dict[tuple, dict] = {}
        for slabels, points in self.select(f"{name}_bucket", labels):
            le = slabels.get("le")
            if le is None:
                continue
            le_f = float("inf") if le == "+Inf" else float(le)
            gkey = tuple(sorted(
                (k, v) for k, v in slabels.items() if k != "le"
            ))
            points = self._window(points, window_s, now)
            if len(points) < 2:
                continue
            d = points[-1][1] - points[0][1]
            groups.setdefault(gkey, {})[le_f] = max(d, 0.0)
        return {g: d for g, d in groups.items() if d}

    def quantile_over_time(self, name: str, q: float,
                           labels: Optional[dict] = None,
                           window_s: Optional[float] = None,
                           now: Optional[float] = None) -> Optional[float]:
        """``histogram_quantile(q, increase(name_bucket[window]))`` for
        ONE label group (ambiguity raises; None when the window holds
        no new observations)."""
        groups = self.bucket_deltas(name, labels, window_s, now)
        if not groups:
            return None
        if len(groups) > 1:
            raise ValueError(
                f"quantile_over_time({name}) matches {len(groups)} "
                "label groups — add labels to disambiguate"
            )
        (deltas,) = groups.values()
        return bucket_quantile(deltas, q)

    # -- persistence ------------------------------------------------------

    def dump(self) -> dict:
        """JSON-safe snapshot of every series (perf_diff input)."""
        with self._lock:
            series = [
                {
                    "name": name,
                    "labels": dict(lk),
                    "points": [[round(t, 6), v] for t, v in ring],
                }
                for (name, lk), ring in sorted(self._data.items())
            ]
        return {
            "version": 1,
            "capacity": self.capacity,
            "kinds": dict(self._kinds),
            "series": series,
        }

    def save(self, path: str) -> str:
        payload = self.dump()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fp:
            json.dump(payload, fp, default=str)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, payload: dict) -> "TimeSeriesStore":
        store = cls(capacity=int(payload.get("capacity",
                                             DEFAULT_CAPACITY)))
        store._kinds.update(payload.get("kinds", {}))
        for s in payload.get("series", []):
            for t, v in s.get("points", []):
                store.append(s["name"], v, s.get("labels") or {}, t)
        return store

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._last_sweep.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def total_points(self) -> int:
        with self._lock:
            return sum(len(r) for r in self._data.values())


def bucket_quantile(deltas: Dict[float, float], q: float) -> Optional[float]:
    """``histogram_quantile`` over one cumulative ``{le: count}`` vector:
    linear interpolation inside the winning bucket, the highest finite
    bound when the quantile lands in ``+Inf``."""
    if not deltas:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    les = sorted(deltas)
    total = deltas[les[-1]] if math.isinf(les[-1]) else max(
        deltas[le] for le in les
    )
    if total <= 0:
        return None
    target = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le in les:
        cum = deltas[le]
        if cum >= target:
            if math.isinf(le):
                finite = [x for x in les if not math.isinf(x)]
                return finite[-1] if finite else None
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return le
            frac = (target - prev_cum) / in_bucket
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = (0.0 if math.isinf(le) else le), cum
    finite = [x for x in les if not math.isinf(x)]
    return finite[-1] if finite else None


# -- flight-recorder context ---------------------------------------------


def watch_context(store: TimeSeriesStore,
                  series: Sequence[str] = DEFAULT_FLIGHT_SERIES,
                  n: int = 32) -> dict:
    """The last-``n`` points of every series matching the allowlist
    (prefix match, so ``serving_slo_burn_rate`` covers its labeled
    children) — what the flight recorder's ``watchtower`` context
    provider attaches to every dump."""
    out: dict = {}
    for prefix in series:
        for name in store.names():
            if not name.startswith(prefix):
                continue
            for labels, points in store.select(name):
                out[render_series_key(name, labels)] = [
                    [round(t, 3), v] for t, v in points[-n:]
                ]
    return out


def install_flight_context(store: Optional[TimeSeriesStore] = None,
                           series: Sequence[str] = DEFAULT_FLIGHT_SERIES,
                           n: int = 32, recorder=None) -> None:
    """Register the ``watchtower`` flight-recorder context provider:
    every future flight dump carries the trend into the failure."""
    from ml_trainer_tpu.telemetry.flight import get_recorder

    rec = recorder if recorder is not None else get_recorder()
    rec.register_context_provider(
        "watchtower",
        lambda: watch_context(
            store if store is not None else default_store(), series, n
        ),
    )


# -- dashboard ------------------------------------------------------------

_DASH_CSS = """
body{background:#101418;color:#d8dee4;font:13px/1.45 system-ui,sans-serif;
     margin:0;padding:18px}
h1{font-size:16px;margin:0 0 2px}
.meta{color:#7d8590;margin:0 0 14px}
.tiles{display:flex;flex-wrap:wrap;gap:10px}
.tile{background:#161b22;border:1px solid #2d333b;border-radius:6px;
      padding:8px 10px;min-width:180px}
.tile .name{color:#7d8590;font-size:11px;overflow-wrap:anywhere}
.tile .value{font-size:18px;font-weight:600;margin:2px 0}
.spark{display:block}
.spark polyline{fill:none;stroke:#58a6ff;stroke-width:1.5}
.alerts{margin-top:18px}
table{border-collapse:collapse;margin-top:6px}
td,th{border:1px solid #2d333b;padding:3px 8px;text-align:left}
.sev-page{color:#ff7b72}.sev-warn{color:#d29922}
.state-firing{color:#ff7b72;font-weight:600}
.state-resolved{color:#3fb950}
""".strip()


def _fmt_stat(v: float) -> str:
    if v != v:
        return "NaN"
    if abs(v) >= 1e6 or (0 < abs(v) < 1e-3):
        return f"{v:.3g}"
    if v == int(v):
        return str(int(v))
    return f"{v:.4g}"


def _sparkline(points: list, width: int = 160, height: int = 36) -> str:
    """One series as an inline SVG polyline (self-contained HTML)."""
    if len(points) < 2:
        return (
            f'<svg class="spark" width="{width}" height="{height}"></svg>'
        )
    ts = [t for t, _ in points]
    vs = [v for _, v in points]
    t0, t1 = ts[0], ts[-1]
    lo, hi = min(vs), max(vs)
    tspan = (t1 - t0) or 1.0
    vspan = (hi - lo) or 1.0
    coords = " ".join(
        f"{(t - t0) / tspan * (width - 4) + 2:.1f},"
        f"{height - 2 - (v - lo) / vspan * (height - 4):.1f}"
        for t, v in points
    )
    return (
        f'<svg class="spark" width="{width}" height="{height}" '
        f'viewBox="0 0 {width} {height}">'
        f'<polyline points="{coords}"/></svg>'
    )


def render_dashboard(store: TimeSeriesStore, title: str = "watchtower",
                     alerts: Optional[Sequence[dict]] = None,
                     max_points: int = 120,
                     max_series: int = 400) -> str:
    """The whole store as ONE self-contained HTML page: a stat tile
    (latest value + sparkline) per series, bucket series folded away,
    plus the alert history table when ``alerts`` is given.  Stdlib
    only — no external assets, safe to drop into an incident bundle."""
    import html as _html

    tiles = []
    n_series = 0
    for name in store.names():
        if name.endswith("_bucket"):
            continue
        for labels, points in store.select(name):
            if n_series >= max_series:
                break
            n_series += 1
            key = render_series_key(name, labels)
            points = points[-max_points:]
            value = points[-1][1] if points else float("nan")
            tiles.append(
                f'<div class="tile" data-series="{_html.escape(key)}">'
                f'<div class="name">{_html.escape(key)}</div>'
                f'<div class="value">{_fmt_stat(value)}</div>'
                f"{_sparkline(points)}</div>"
            )
    alert_html = ""
    if alerts:
        rows = []
        for a in alerts:
            value = a.get("value")
            value_cell = _fmt_stat(float(value)) if value is not None else ""
            label_cell = _html.escape(",".join(
                f"{k}={v}" for k, v in sorted((a.get("labels")
                                               or {}).items())
            ))
            rows.append(
                "<tr>"
                f'<td>{_html.escape(str(a.get("rule", "")))}</td>'
                f'<td class="sev-{_html.escape(str(a.get("severity")))}">'
                f'{_html.escape(str(a.get("severity", "")))}</td>'
                f'<td class="state-{_html.escape(str(a.get("state")))}">'
                f'{_html.escape(str(a.get("state", "")))}</td>'
                f"<td>{value_cell}</td>"
                f"<td>{label_cell}</td>"
                f'<td>{round(float(a.get("t", 0.0)), 3)}</td>'
                "</tr>"
            )
        alert_html = (
            '<section class="alerts"><h1>alerts</h1><table>'
            "<tr><th>rule</th><th>severity</th><th>state</th>"
            "<th>value</th><th>labels</th><th>t</th></tr>"
            + "".join(rows) + "</table></section>"
        )
    rendered_at = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    return (
        "<!doctype html><html><head><meta charset=\"utf-8\">"
        f"<title>{_html.escape(title)}</title>"
        f"<style>{_DASH_CSS}</style></head><body>"
        f"<h1>watchtower &middot; {_html.escape(title)}</h1>"
        f'<p class="meta">{n_series} series &middot; '
        f"{store.total_points()} points &middot; {rendered_at}</p>"
        f'<section class="tiles">{"".join(tiles)}</section>'
        f"{alert_html}</body></html>"
    )


def save_dashboard(store: TimeSeriesStore, path: str,
                   title: str = "watchtower",
                   alerts: Optional[Sequence[dict]] = None) -> str:
    """Atomic HTML snapshot — what incident bundles and run_report
    embed."""
    html = render_dashboard(store, title=title, alerts=alerts)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        fp.write(html)
    os.replace(tmp, path)
    return path


# -- process-wide default store -------------------------------------------
_default: Optional[TimeSeriesStore] = None
_default_lock = threading.Lock()


def default_store() -> TimeSeriesStore:
    """The process-wide store the trainer's log-sync and the flight
    context provider share (servers and routers hold their own)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = TimeSeriesStore()
        return _default


def reset_default_store() -> None:
    """Tests only: drop the process-wide store."""
    global _default
    with _default_lock:
        _default = None
