"""Fleet observability plane: metrics federation + cross-process trace
merge (docs/observability.md "Fleet plane").

A multi-process serving fleet (serving/fleet.py) has N worker processes,
each with its OWN metrics registry, span buffer, and flight recorder —
process-local instruments the router cannot see.  This module is the
pure, process-free core of the fleet plane:

* **Metrics federation** (:func:`relabel_exposition`,
  :func:`federate_exposition`): rewrite a worker's Prometheus text
  exposition (the exact 0.0.4 bytes its ``/metrics`` served) so every
  sample line carries ``replica=``/``role=``/``generation=`` labels,
  then merge the rewritten sections with the router's own exposition
  into ONE valid document — ``# HELP``/``# TYPE`` deduplicated, all
  samples of a metric family grouped.  The router re-renders from each
  replica's LATEST scraped snapshot on every ``/metrics`` hit (replace,
  never accumulate), so re-scraping is idempotent: histogram counts are
  whatever the worker last reported, not a running sum of scrapes.

* **Trace merge** (:func:`shift_trace_events`, :func:`merge_fleet_trace`):
  place N processes' Chrome trace events on ONE timeline.  Each worker's
  timestamps are microseconds since ITS OWN epoch (telemetry/spans.py
  ``_MONO_EPOCH``), so merging needs a per-process clock shift onto the
  router's trace clock.  Two estimates exist per worker (computed by the
  router's health poller, serving/router.py): the *epoch shift* — exact
  when both processes read the same underlying clock, which
  ``time.monotonic()`` (CLOCK_MONOTONIC) is across processes on Linux —
  and the *NTP-style handshake* estimate (worker reports its trace-clock
  "now" inside the health payload; the router brackets the call with its
  own stamps and maps the report to the bracket's midpoint, error
  bounded by rtt/2, min-rtt filtered across polls).  The merge prefers
  the epoch shift when the two agree within the handshake's error bound
  (shared clock confirmed) and falls back to the handshake estimate
  otherwise (distinct clocks — e.g. a future multi-host fleet).

Host-only module: no jax, no sockets — callers feed it text/dicts they
already fetched.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Labels the federation layer owns.  A worker series that already
# carries one of these (it should never) keeps its own value — injecting
# a duplicate label name would make the exposition invalid.
FEDERATION_LABELS = ("replica", "role", "generation")

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{.*\})?"
    r"(?P<rest>\s.+)$"
)

# Histogram/summary child-sample suffixes: `x_bucket`/`x_sum`/`x_count`
# belong to family `x` — grouping must keep them with their TYPE header.
_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


def _escape(v) -> str:
    s = str(v)
    return s.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def inject_labels(line: str, extra: Dict[str, object]) -> str:
    """One exposition sample line with ``extra`` labels appended.

    Comment/blank lines pass through untouched.  Existing labels win on
    a name collision (the injected pair is dropped, not duplicated)."""
    if not line or line.startswith("#"):
        return line
    m = _SAMPLE_RE.match(line)
    if m is None:
        return line
    existing = m.group("labels") or ""
    pairs = [
        f'{k}="{_escape(v)}"'
        for k, v in extra.items()
        if f'{k}="' not in existing
    ]
    if not pairs:
        return line
    if existing:
        inner = existing[1:-1]
        merged = "{" + (inner + "," if inner else "") + ",".join(pairs) + "}"
    else:
        merged = "{" + ",".join(pairs) + "}"
    return f"{m.group('name')}{merged}{m.group('rest')}"


def relabel_exposition(text: str, extra: Dict[str, object]) -> str:
    """A whole Prometheus text exposition with ``extra`` labels injected
    into every sample line (``# HELP``/``# TYPE`` untouched)."""
    return "\n".join(
        inject_labels(line, extra) for line in text.splitlines()
    ) + ("\n" if text.endswith("\n") else "")


def parse_exposition(text: str) -> List[dict]:
    """Exposition text as metric-family groups, document order:
    ``[{"name", "help", "type", "samples": [line, ...]}, ...]``.

    Grouping follows the comment headers: sample lines after a
    ``# TYPE x ...`` belong to family ``x`` until the next header; a
    bare sample with no header becomes its own untyped family (help and
    type ``None``), with histogram child suffixes folded into the base
    name so ``x_bucket``/``x_sum``/``x_count`` stay together."""
    families: List[dict] = []
    by_name: Dict[str, dict] = {}
    current: Optional[dict] = None

    def _family(name: str, help_text=None, kind=None) -> dict:
        fam = by_name.get(name)
        if fam is None:
            fam = {"name": name, "help": help_text, "type": kind,
                   "samples": []}
            by_name[name] = fam
            families.append(fam)
        else:
            if help_text is not None and fam["help"] is None:
                fam["help"] = help_text
            if kind is not None and fam["type"] is None:
                fam["type"] = kind
        return fam

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            current = _family(
                parts[0], help_text=parts[1] if len(parts) > 1 else ""
            )
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ", 1)
            current = _family(
                parts[0], kind=parts[1].strip() if len(parts) > 1 else None
            )
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        base = name
        for suf in _FAMILY_SUFFIXES:
            if name.endswith(suf):
                base = name[: -len(suf)]
                break
        if current is not None and (
            name == current["name"] or base == current["name"]
        ):
            current["samples"].append(line)
        else:
            _family(base if base in by_name else name)["samples"].append(
                line
            )
            current = by_name.get(base, by_name.get(name))
    return families


def federate_exposition(
    base_text: str,
    sections: Sequence[Tuple[str, Dict[str, object]]],
) -> str:
    """ONE valid exposition document from the router's own text plus N
    scraped worker snapshots.

    ``sections`` is ``[(worker_exposition_text, extra_labels), ...]`` —
    each worker's text is relabeled (:func:`relabel_exposition`) and
    merged family-by-family with ``base_text``: one ``# HELP``/``# TYPE``
    header per metric name (first writer wins), every family's samples
    grouped regardless of which process reported them.  Rendering always
    starts from the LATEST snapshots, so calling this twice with the
    same inputs returns the same bytes — the idempotent-re-scrape
    property the federation tests pin."""
    merged: List[dict] = []
    by_name: Dict[str, dict] = {}
    for text in [base_text] + [
        relabel_exposition(text, extra) for text, extra in sections
    ]:
        for fam in parse_exposition(text):
            have = by_name.get(fam["name"])
            if have is None:
                fam = dict(fam, samples=list(fam["samples"]))
                by_name[fam["name"]] = fam
                merged.append(fam)
            else:
                if have["help"] is None:
                    have["help"] = fam["help"]
                if have["type"] is None:
                    have["type"] = fam["type"]
                have["samples"].extend(fam["samples"])
    lines: List[str] = []
    for fam in merged:
        if fam["help"]:
            lines.append(f"# HELP {fam['name']} {fam['help']}")
        if fam["type"]:
            lines.append(f"# TYPE {fam['name']} {fam['type']}")
        lines.extend(fam["samples"])
    return "\n".join(lines) + "\n"


# -- cross-process trace merge -------------------------------------------


def resolve_clock_shift(
    epoch_shift_us: Optional[float],
    ntp_shift_us: Optional[float],
    rtt_us: Optional[float],
) -> Tuple[Optional[float], str]:
    """The per-process shift (µs to ADD to a worker event's ``ts`` to
    land it on the router's trace clock) and which estimate won.

    The epoch shift is exact when both processes share the underlying
    monotonic clock; the NTP handshake bounds its own error by rtt/2.
    So: when both exist and agree within the handshake's error bound
    (plus 1ms slack for scheduling between the stamps), the clocks are
    shared — use the exact epoch shift.  Disagreement means distinct
    clocks — trust the handshake.  Returns ``(None, "none")`` when no
    estimate exists (never health-polled)."""
    if epoch_shift_us is None and ntp_shift_us is None:
        return None, "none"
    if ntp_shift_us is None:
        return epoch_shift_us, "epoch"
    if epoch_shift_us is None:
        return ntp_shift_us, "ntp"
    bound = (rtt_us or 0.0) / 2.0 + 1_000.0
    if abs(epoch_shift_us - ntp_shift_us) <= bound:
        return epoch_shift_us, "epoch"
    return ntp_shift_us, "ntp"


def shift_trace_events(events: Iterable[dict],
                       shift_us: float) -> List[dict]:
    """Copies of ``events`` with ``ts`` shifted onto the merged clock
    (``dur`` and everything else untouched)."""
    out = []
    for ev in events:
        ev = dict(ev)
        if "ts" in ev:
            ev["ts"] = float(ev["ts"]) + shift_us
        out.append(ev)
    return out


def process_name_events(pid: int, name: str) -> List[dict]:
    """Perfetto metadata events labeling one process lane."""
    return [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": name},
    }]


def merge_fleet_trace(local_events: Sequence[dict],
                      local_name: str,
                      local_pid: int,
                      remotes: Sequence[dict]) -> dict:
    """One clock-aligned Perfetto timeline from the router's own span
    buffer plus N remote ``GET /trace`` payloads.

    Each remote entry: ``{"name", "payload", "epoch_shift_us",
    "ntp_shift_us", "rtt_us"}`` where ``payload`` is the worker's
    ``/trace`` reply (``{"pid", "events", ...}``).  Events keep their
    originating pid — Perfetto renders one lane per process — and every
    lane gets a ``process_name`` metadata row.  Returns
    ``{"traceEvents": [...], "displayTimeUnit": "ms", "fleetClock":
    {per-process shift/method/rtt}}``; a remote with no usable clock
    estimate contributes its lane UNSHIFTED and is flagged
    ``method="none"`` in ``fleetClock`` (visible, not silently
    dropped)."""
    events: List[dict] = list(local_events)
    events.extend(process_name_events(local_pid, local_name))
    clock: Dict[str, dict] = {
        local_name: {"shift_us": 0.0, "method": "local", "pid": local_pid},
    }
    for rem in remotes:
        payload = rem.get("payload") or {}
        pid = payload.get("pid")
        shift, method = resolve_clock_shift(
            rem.get("epoch_shift_us"), rem.get("ntp_shift_us"),
            rem.get("rtt_us"),
        )
        clock[rem["name"]] = {
            "shift_us": round(shift, 3) if shift is not None else None,
            "method": method,
            "rtt_us": rem.get("rtt_us"),
            "pid": pid,
        }
        evs = payload.get("events") or []
        events.extend(
            shift_trace_events(evs, shift) if shift is not None
            else [dict(e) for e in evs]
        )
        if pid is not None:
            events.extend(process_name_events(pid, rem["name"]))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "fleetClock": clock,
    }
