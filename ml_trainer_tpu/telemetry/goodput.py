"""Goodput accounting: where the wall-clock of a training run went.

MFU says how fast the chip runs while it runs; it says nothing about
the minutes the chip sat idle behind a cold input pipeline, a blocking
checkpoint enqueue, a compile storm, or a preemption gap.  Production
trainers (TorchTitan, arXiv 2410.06511) treat that decomposition as a
first-class metric: **goodput** = the fraction of wall-clock spent in
productive training compute.

This module is the process-wide ledger.  Layers account host seconds
into named buckets (cheap locked adds, same idiom as the data-loader
wait accounting in ``data/loader.py``):

===================  ====================================================
bucket               accounted by
===================  ====================================================
``data_wait``        host blocked assembling the next batch
                     (``loader_wait_snapshot`` — existing accounting)
``h2d``              host blocked placing batches on device
                     (``prefetch_to_device``)
``ckpt_stall``       host blocked in checkpoint enqueue / commit barriers
                     (trainer ``ckpt_write`` sites, ``wait_for_checkpoints``)
``compile``          XLA backend compiles (``compile_watch``)
``rollback``         rollback-to-last-good restores (NaN escape hatch)
``preempt_gap``      downtime between a preemption exit and the resume
                     that consumed its marker (``PREEMPTED.json`` age)
``reshape``          elastic mesh reshape around a lost host: drain +
                     emergency checkpoint + whole-tree re-placement +
                     loader/step rebuild (``resilience/elastic.py``) —
                     elastic downtime is attributed here, never folded
                     silently into compute
===================  ====================================================

Everything not in a bucket is **compute** — the remainder against the
run's wall-clock, so the buckets + compute sum to the wall-clock by
construction (the bucket-arithmetic test pins the tolerance).  A
:class:`GoodputMeter` anchors one run's window: the trainer starts it
at ``fit()`` entry, reports at every telemetry sync
(``train_goodput_fraction`` + ``train_goodput_seconds_total{bucket=}``
gauges, a ``goodput_fraction`` heartbeat field for the cluster view),
and distills the final decomposition into ``run_report.json``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

BUCKETS = (
    "data_wait", "h2d", "ckpt_stall", "compile", "rollback", "preempt_gap",
    "reshape",
)

_lock = threading.Lock()
_acc: Dict[str, float] = {b: 0.0 for b in BUCKETS}


def account(bucket: str, secs: float) -> None:
    """Add ``secs`` of non-compute wall-clock to ``bucket``."""
    if bucket not in _acc:
        raise ValueError(
            f"unknown goodput bucket {bucket!r}; expected one of {BUCKETS}"
        )
    if secs <= 0:
        return
    with _lock:
        _acc[bucket] += float(secs)


@contextlib.contextmanager
def timed(bucket: str):
    """Account a host region's duration into ``bucket``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        account(bucket, time.perf_counter() - t0)


def snapshot() -> Dict[str, float]:
    """Cumulative process-wide seconds per bucket."""
    with _lock:
        return dict(_acc)


def reset() -> None:
    """Zero the accumulators (tests only)."""
    with _lock:
        for b in BUCKETS:
            _acc[b] = 0.0


def decompose(wall_secs: float, base: Optional[Dict[str, float]] = None,
              now: Optional[Dict[str, float]] = None) -> dict:
    """Split ``wall_secs`` into buckets + the compute remainder.

    ``base``/``now`` are :func:`snapshot` dicts bounding the window
    (defaults: zero baseline / the current snapshot).  Bucket time can
    legitimately exceed the wall-clock only through accounting overlap
    (two buckets covering the same instant) — compute clamps at 0 and
    the report records the overshoot instead of hiding it."""
    now = now if now is not None else snapshot()
    base = base or {}
    wall = max(float(wall_secs), 0.0)
    buckets = {
        b: max(now.get(b, 0.0) - base.get(b, 0.0), 0.0) for b in BUCKETS
    }
    non_compute = sum(buckets.values())
    compute = max(wall - non_compute, 0.0)
    fraction = compute / wall if wall > 0 else 0.0
    return {
        "wall_secs": wall,
        "compute_secs": compute,
        "goodput_fraction": fraction,
        "buckets_secs": buckets,
        # > 0 only when bucket accounting overlapped the wall window
        # (e.g. a compile observed on another thread) — visible, not
        # silently clamped away.
        "overshoot_secs": max(non_compute - wall, 0.0),
    }


class GoodputMeter:
    """One run's goodput window over the process-wide ledger.

    ``start()`` anchors the wall-clock and baselines the buckets;
    ``report()`` publishes the cumulative decomposition since the anchor
    (gauges + returns the dict); ``finish()`` reports one last time and
    returns the final decomposition for the run report."""

    def __init__(self, registry=None):
        from ml_trainer_tpu.telemetry.registry import default_registry

        self.registry = registry if registry is not None else default_registry()
        r = self.registry
        self.g_fraction = r.gauge(
            "train_goodput_fraction",
            "fraction of wall-clock spent in productive train compute "
            "(1 - data_wait/h2d/ckpt_stall/compile/rollback/preempt_gap/"
            "reshape)",
        )
        self.g_bucket = r.gauge(
            "train_goodput_seconds_total",
            "wall-clock seconds attributed to each non-compute bucket "
            "since fit() start",
            ("bucket",),
        )
        self.g_compute = r.gauge(
            "train_goodput_compute_seconds_total",
            "wall-clock seconds of productive compute since fit() start",
        )
        self._t0: Optional[float] = None
        self._base: Dict[str, float] = {}
        self.last: Optional[dict] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()
        self._base = snapshot()
        self.last = None

    @property
    def started(self) -> bool:
        return self._t0 is not None

    def report(self) -> Optional[dict]:
        """Publish + return the decomposition since ``start()`` (None if
        never started)."""
        if self._t0 is None:
            return None
        d = decompose(time.perf_counter() - self._t0, base=self._base)
        self.g_fraction.set(d["goodput_fraction"])
        self.g_compute.set(d["compute_secs"])
        for b, v in d["buckets_secs"].items():
            self.g_bucket.labels(bucket=b).set(v)
        self.last = d
        return d

    def finish(self) -> Optional[dict]:
        return self.report()

    def fraction(self) -> float:
        """Current goodput fraction without publishing (heartbeats)."""
        if self._t0 is None:
            return 0.0
        d = decompose(time.perf_counter() - self._t0, base=self._base)
        return d["goodput_fraction"]
