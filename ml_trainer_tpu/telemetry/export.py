"""Registry exporters: Prometheus text exposition and a JSONL sink.

Prometheus exposition follows the text format version 0.0.4 — the shape
``promtool check metrics`` accepts: ``# HELP`` / ``# TYPE`` headers,
``name{label="value"} number`` samples, histograms as cumulative
``_bucket{le=...}`` series plus ``_sum`` / ``_count``.  The JSONL sink
is the zero-infra alternative: one flat JSON object per line, append-only,
durable across crashes (the line is flushed per write), so offline
tooling can ``jq`` a run without a metrics server.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Optional


def _fmt_value(v: float) -> str:
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _labels_text(labelnames, key, extra=()) -> str:
    pairs = [
        f'{ln}="{_escape_label(str(lv))}"'
        for ln, lv in zip(labelnames, key)
    ]
    pairs.extend(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def prometheus_text(registry) -> str:
    """The whole registry in Prometheus text exposition format 0.0.4."""
    lines = []
    for m in registry.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for key, _ in sorted(m.series().items()):
            if m.kind == "histogram":
                h = m._get(key)
                cum = 0
                for ub, c in zip(m.buckets, h["buckets"]):
                    cum += c
                    le = _labels_text(
                        m.labelnames, key, (f'le="{_fmt_value(ub)}"',)
                    )
                    lines.append(f"{m.name}_bucket{le} {cum}")
                le = _labels_text(m.labelnames, key, ('le="+Inf"',))
                lines.append(f"{m.name}_bucket{le} {h['count']}")
                lt = _labels_text(m.labelnames, key)
                lines.append(f"{m.name}_sum{lt} {_fmt_value(h['sum'])}")
                lines.append(f"{m.name}_count{lt} {h['count']}")
            else:
                lt = _labels_text(m.labelnames, key)
                lines.append(f"{m.name}{lt} {_fmt_value(m._get(key))}")
    return "\n".join(lines) + "\n"


class JsonlSink:
    """Append-only JSONL metrics/events sink (thread-safe).

    ``write(record)`` appends one timestamped JSON line;
    ``write_registry(registry)`` appends the registry's flat snapshot.
    The file handle is opened lazily and each line is flushed, so a
    crashed process keeps every record written before the crash.

    ``max_bytes`` (None = unbounded, the historical behavior) bounds
    the LIVE file: a write that would cross the bound first rotates the
    live file to ``<base>.<seq><ext>`` and records the segment in the
    sidecar index (``<path>.index.json``), so a long fleet run stops
    growing one unbounded file per worker and :func:`read_sink_records`
    can replay every segment in order."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = int(max_bytes) if max_bytes else None
        self._lock = threading.Lock()
        self._fp = None
        self._bytes = 0
        self._seq = 0

    @property
    def index_path(self) -> str:
        return self.path + ".index.json"

    def _handle(self):
        if self._fp is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._fp = open(self.path, "a", encoding="utf-8")
            self._bytes = self._fp.tell()
            # Resume the segment counter past any prior rotation (a
            # re-opened sink must not overwrite rotated segments).
            idx = self._read_index()
            self._seq = len(idx.get("rotated", []))
        return self._fp

    def _read_index(self) -> dict:
        try:
            with open(self.index_path, encoding="utf-8") as fp:
                return json.load(fp)
        except (OSError, json.JSONDecodeError):
            return {"version": 1, "live": self.path, "rotated": []}

    def _rotate_locked(self) -> None:
        self._fp.close()
        self._fp = None
        self._seq += 1
        base, ext = os.path.splitext(self.path)
        rotated = f"{base}.{self._seq:04d}{ext or '.jsonl'}"
        os.replace(self.path, rotated)
        idx = self._read_index()
        idx["live"] = self.path
        idx.setdefault("rotated", []).append({
            "path": rotated,
            "bytes": self._bytes,
            "rotated_at": round(time.time(), 6),
        })
        tmp = self.index_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fp:
            json.dump(idx, fp)
        os.replace(tmp, self.index_path)
        self._bytes = 0

    def write(self, record: dict, kind: str = "event") -> None:
        row = {"ts": round(time.time(), 6), "kind": kind}
        row.update(record)
        line = json.dumps(row, default=str)
        with self._lock:
            fp = self._handle()
            if (
                self.max_bytes is not None
                and self._bytes > 0
                and self._bytes + len(line) + 1 > self.max_bytes
            ):
                self._rotate_locked()
                fp = self._handle()
            fp.write(line + "\n")
            fp.flush()
            self._bytes += len(line) + 1

    def write_registry(self, registry) -> None:
        self.write(registry.snapshot(), kind="metrics")

    def close(self) -> None:
        with self._lock:
            if self._fp is not None:
                self._fp.close()
                self._fp = None


def read_sink_records(path: str) -> list:
    """Every record a (possibly rotated) sink wrote, oldest first: the
    index's rotated segments in rotation order, then the live file.
    Tolerates a missing index (unrotated sink) and a truncated final
    line (crash mid-write)."""
    paths = []
    try:
        with open(path + ".index.json", encoding="utf-8") as fp:
            idx = json.load(fp)
        paths.extend(seg["path"] for seg in idx.get("rotated", []))
    except (OSError, json.JSONDecodeError):
        pass
    paths.append(path)
    out = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as fp:
                for line in fp:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    return out


_default_sink: Optional[JsonlSink] = None
_sink_lock = threading.Lock()

SINK_ENV = "ML_TRAINER_TPU_METRICS_JSONL"
# Size bound (MB, float) for the live JSONL file; unset/0 = unbounded
# (the historical default).  Crossing the bound rotates the live file
# to `<base>.<seq><ext>` and records it in `<path>.index.json`.
SINK_MAX_MB_ENV = "ML_TRAINER_TPU_METRICS_JSONL_MAX_MB"
# Set by the fleet launcher (serving/fleet.py spawn): each worker
# process inherits the driver's SINK_ENV path, and N workers appending
# to ONE file interleave lines mid-record.  The worker id (or, for any
# other multi-process launcher, "pid") suffixes the sink path per
# process: `metrics.jsonl` -> `metrics.<worker>.jsonl` — one file per
# process, same directory, `jq`-able as a glob.
SINK_WORKER_ENV = "ML_TRAINER_TPU_METRICS_WORKER"


def sink_path_for_worker(path: str, worker: str) -> str:
    """``path`` with a per-worker suffix before the extension (or
    appended when there is none): the fleet sink layout."""
    base, ext = os.path.splitext(path)
    return f"{base}.{worker}{ext}" if ext else f"{path}.{worker}"


def default_sink() -> Optional[JsonlSink]:
    """Process-wide JSONL sink, enabled by pointing the env var
    ``ML_TRAINER_TPU_METRICS_JSONL`` at a file path; None when unset.
    When ``ML_TRAINER_TPU_METRICS_WORKER`` is also set (fleet worker
    processes), the path gains a per-worker suffix so concurrent
    workers never interleave writes into one file (``pid`` as the
    worker id gives the same isolation to ad-hoc launchers)."""
    global _default_sink
    path = os.environ.get(SINK_ENV, "")
    worker = os.environ.get(SINK_WORKER_ENV, "")
    try:
        max_mb = float(os.environ.get(SINK_MAX_MB_ENV, "") or 0.0)
    except ValueError:
        max_mb = 0.0
    max_bytes = int(max_mb * 1024 * 1024) if max_mb > 0 else None
    with _sink_lock:
        if not path:
            return None
        if worker:
            path = sink_path_for_worker(
                path, worker if worker != "pid" else str(os.getpid())
            )
        if _default_sink is None or _default_sink.path != path:
            _default_sink = JsonlSink(path, max_bytes=max_bytes)
        return _default_sink
