"""Unified telemetry spine — the one subsystem every layer reports into.

Four parts (docs/observability.md):

* **registry** — thread-safe counters / gauges / histograms with labels,
  a process-wide default registry, Prometheus text exposition and a
  JSONL sink (``registry.py`` / ``export.py``).  The trainer and the
  serving stack both publish here, so one scrape endpoint (or one JSONL
  tail) covers the whole process.
* **train step telemetry** — grad-norm / param-norm / update-ratio
  stats accumulated ON-DEVICE inside the compiled train step (same
  no-host-sync discipline as the all-finite guard; zero extra compiled
  programs), fetched at the trainer's existing ``log_every`` sync
  cadence and emitted as structured events + registry gauges alongside
  samples/s, tokens/s and an analytic MFU estimate
  (``train_metrics.py`` + ``flops.py``).
* **span tracing** — host-side spans emitting Chrome/Perfetto
  trace-event JSON, composable with ``utils.profiler.annotate`` so host
  spans and XLA device traces line up; plus on-demand ``jax.profiler``
  windows triggered by env/file flag or the serving admin endpoint
  (``spans.py``).
* **flight recorder** — a bounded ring of the last N step records and
  events, dumped to ``flight_<ts>.json`` on NaN-rollback, preemption,
  watchdog trip, or unhandled exception — the crash forensics a
  post-mortem needs when the logs are gone (``flight.py``).
* **cluster aggregation** — per-host heartbeats allgathered into
  ``cluster_*{host=...}`` gauges on every host, a straggler detector
  over the fenced step-time percentiles, desync forensics
  (``parallel/desync.py`` publishes fingerprints here), analytic
  collective-comms accounting (``parallel/comm_stats.py``), and the
  end-of-run ``run_report.json``/``.md`` distillation (``cluster.py``).
* **memory / goodput / recompile pillar** — the analytic per-device
  HBM ledger with live cross-check and fit-or-OOM planner
  (``memory.py``), the wall-clock-decomposition goodput ledger behind
  ``train_goodput_fraction`` (``goodput.py``), and compile forensics on
  JAX's own compilation path — ``compile_events_total{fn=}``, flight
  ``recompile`` events naming the offending shape
  (``compile_watch.py``).
* **watchtower** — the in-process time-series store: bounded per-series
  rings sampled from the registry at the existing publish cadences,
  windowed ``rate()`` / ``quantile_over_time()`` queries, declarative
  :class:`~.alerts.AlertRule` evaluation (threshold / rate-of-change /
  burn / absent-series; the autoscaler, deploy-canary and straggler
  watchers are rules on this engine), a stdlib-only live HTML dashboard
  (``GET /dash``), and snapshots into incident bundles and the run
  report (``watchtower.py`` / ``alerts.py``).
"""

from ml_trainer_tpu.telemetry.cluster import (
    HEARTBEAT_FIELDS,
    ClusterTelemetry,
    write_run_report,
)
from ml_trainer_tpu.telemetry.alerts import (
    AlertEngine,
    AlertRule,
    default_fleet_rules,
)
from ml_trainer_tpu.telemetry.export import (
    JsonlSink,
    prometheus_text,
    read_sink_records,
)
from ml_trainer_tpu.telemetry.flight import (
    FLIGHT_DIR_ENV,
    FlightRecorder,
    get_recorder,
)
from ml_trainer_tpu.telemetry import compile_watch, goodput, memory
from ml_trainer_tpu.telemetry.flops import (
    chip_hbm_capacity_bytes,
    chip_peak_flops,
    chip_peak_hbm_bytes,
    train_step_flops,
)
from ml_trainer_tpu.telemetry.goodput import GoodputMeter
from ml_trainer_tpu.telemetry.memory import (
    MemoryLedger,
    live_memory_snapshot,
    plan_train_memory,
    train_ledger,
)
from ml_trainer_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from ml_trainer_tpu.telemetry.spans import (
    StepProfiler,
    save_trace,
    span,
    trace_events,
)
from ml_trainer_tpu.telemetry.train_metrics import TrainTelemetry
from ml_trainer_tpu.telemetry.watchtower import (
    TimeSeriesStore,
    default_store,
    install_flight_context,
    render_dashboard,
    reset_default_store,
    save_dashboard,
    watch_context,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "prometheus_text",
    "JsonlSink",
    "span",
    "save_trace",
    "trace_events",
    "StepProfiler",
    "FlightRecorder",
    "get_recorder",
    "FLIGHT_DIR_ENV",
    "chip_peak_flops",
    "chip_peak_hbm_bytes",
    "chip_hbm_capacity_bytes",
    "train_step_flops",
    "compile_watch",
    "goodput",
    "memory",
    "GoodputMeter",
    "MemoryLedger",
    "live_memory_snapshot",
    "plan_train_memory",
    "train_ledger",
    "TrainTelemetry",
    "ClusterTelemetry",
    "HEARTBEAT_FIELDS",
    "write_run_report",
    "read_sink_records",
    "TimeSeriesStore",
    "default_store",
    "reset_default_store",
    "watch_context",
    "install_flight_context",
    "render_dashboard",
    "save_dashboard",
    "AlertRule",
    "AlertEngine",
    "default_fleet_rules",
]
