"""Analytic HBM ledger: will this config fit, and what is resident?

The parallelism menu (DP/TP/FSDP/ZeRO-1/SP/PP/EP, paged serving KV)
makes "does it fit in HBM" a function of half a dozen knobs — and the
pjit/TPUv4 scaling playbook (arXiv 2204.06514) is explicit that
per-config memory budgeting is what makes those knobs tractable rather
than trial-and-error.  This module owns that budget:

* **component walk** — :func:`train_ledger` walks a built Trainer's
  actual state (shape/dtype/sharding METADATA only — no device reads):
  params / optimizer moments / EMA / batch_stats per-device bytes with
  the dtype- and sharding-aware division the placement implies (ZeRO-1
  and ``dp_update='sharded'`` moments ÷N, TP/FSDP shard factors via
  each leaf's ``shard_shape``), plus the transients the steady numbers
  hide: fp32 gradients, the chunked-LM-head logits peak
  (``loss_chunk``), the pipeline activation stash sized from
  ``parallel/pipeline.py``'s own ``stash_slots`` accounting, and the
  input batch with its prefetch depth;
* **formula walk** — :func:`plan_train_memory` computes the same ledger
  from a config alone (``jax.eval_shape`` of model + optimizer init, no
  state built), so ``bench.py --memplan`` can predict peak HBM for a
  topology this host does not have, judged against the chip capacity
  table ``telemetry/flops.py`` owns;
* **live cross-check** — :func:`live_memory_snapshot` reads per-device
  ``memory_stats()`` on TPU and falls back to live-array nbytes
  accounting on CPU; :func:`measured_tree_bytes` measures what a state
  tree actually holds per device, and :func:`cross_check` pins the
  analytic walk against it (the smoke legs enforce 10% agreement);
* **exposition** — ``MemoryLedger.publish()`` emits
  ``mem_analytic_bytes{component=}`` gauges,
  :func:`publish_live_memory` emits ``mem_live_bytes{device=}`` /
  ``mem_live_peak_bytes{device=}``, and flight dumps attach
  :func:`memory_snapshot_payload` so OOM forensics name the resident
  components (``telemetry/flight.py`` context providers);
* **serving** — :func:`kv_pool_bytes` prices the paged KV pool
  (pages × H × page × D × dtype × layers × K/V) so the ledger covers
  the serving engine end to end (``serving_kv_pool_bytes{state=}``).

Everything here is host arithmetic over metadata: building a ledger
never allocates, syncs, or changes a compiled program.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.utils.logging import get_logger

logger = get_logger("ml_trainer_tpu.telemetry")

# Prefetch depth of the trainer's input pipeline (data/loader.py
# prefetch_to_device size=2) + the batch the step is consuming.
_BATCH_BUFFERS = 3


@dataclasses.dataclass
class Component:
    """One ledger line: per-device bytes of one memory consumer."""

    name: str
    bytes: float
    kind: str  # "resident" (steady-state) | "transient" (in-step peak)
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "bytes": int(self.bytes),
            "kind": self.kind,
            **({"detail": self.detail} if self.detail else {}),
        }


class MemoryLedger:
    """A per-device HBM budget: components + totals + exposition."""

    def __init__(self, components: Sequence[Component],
                 notes: Optional[List[str]] = None):
        self.components = list(components)
        self.notes = list(notes or [])

    def resident_bytes(self) -> float:
        return sum(c.bytes for c in self.components if c.kind == "resident")

    def transient_bytes(self) -> float:
        return sum(c.bytes for c in self.components if c.kind == "transient")

    def peak_bytes(self) -> float:
        """Predicted per-device peak: everything resident plus the
        in-step transients (they coexist at the backward's peak)."""
        return self.resident_bytes() + self.transient_bytes()

    def component(self, name: str) -> Optional[Component]:
        for c in self.components:
            if c.name == name:
                return c
        return None

    def as_dict(self) -> dict:
        return {
            "components": [c.as_dict() for c in self.components],
            "resident_bytes": int(self.resident_bytes()),
            "transient_bytes": int(self.transient_bytes()),
            "peak_bytes": int(self.peak_bytes()),
            "notes": self.notes,
        }

    def publish(self, registry=None) -> None:
        """Mirror the ledger into ``mem_analytic_bytes{component=}``
        gauges plus the resident/peak totals."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        g = r.gauge(
            "mem_analytic_bytes",
            "analytic per-device HBM bytes by component "
            "(telemetry/memory.py ledger)",
            ("component",),
        )
        for c in self.components:
            g.labels(component=c.name).set(float(c.bytes))
        r.gauge(
            "mem_analytic_resident_bytes",
            "analytic per-device steady-state resident HBM bytes",
        ).set(self.resident_bytes())
        r.gauge(
            "mem_analytic_peak_bytes",
            "analytic per-device peak HBM bytes (resident + transients)",
        ).set(self.peak_bytes())


# ------------------------------------------------------------ tree walks
def nbytes_of(shape, dtype) -> int:
    """Bytes of one (shape, dtype) pair — the ledger's unit price.  The
    graft-lint donation audit (analysis/jaxpr_checks.py) prices
    undonated-but-aliasable buffers through this, so lint findings and
    ledger components quote the same arithmetic."""
    if dtype is None:
        return 0
    return int(np.prod(shape, initial=1)) * jnp.dtype(dtype).itemsize


def _leaf_bytes(leaf) -> float:
    """Global bytes of one shape/dtype carrier (array or ShapeDtypeStruct)."""
    return float(
        nbytes_of(getattr(leaf, "shape", ()), getattr(leaf, "dtype", None))
    )


def _leaf_device_bytes(leaf, sharding=None) -> float:
    """Per-device bytes of a leaf under its sharding (metadata only).

    A NamedSharding's ``shard_shape`` is exactly the dtype- and
    sharding-aware division: replicated dims keep their extent, mesh-
    partitioned dims divide by the axis size — so TP/FSDP/ZeRO-1/stage
    placement all price correctly through one call."""
    sh = sharding if sharding is not None else getattr(leaf, "sharding", None)
    shape = tuple(getattr(leaf, "shape", ()))
    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        return 0.0
    itemsize = jnp.dtype(dtype).itemsize
    if sh is not None and hasattr(sh, "shard_shape") and shape:
        try:
            shape = tuple(sh.shard_shape(shape))
        except Exception:
            pass
    return float(np.prod(shape, initial=1)) * itemsize


def tree_device_bytes(tree, shardings=None) -> float:
    """Analytic per-device bytes of a pytree (sharding-aware).  With
    ``shardings`` (a matching tree) those override the leaves' own."""
    if shardings is None:
        return sum(_leaf_device_bytes(l) for l in jax.tree.leaves(tree))
    return sum(
        _leaf_device_bytes(l, s)
        for l, s in zip(jax.tree.leaves(tree), jax.tree.leaves(shardings))
    )


def measured_tree_bytes(tree) -> Tuple[float, Dict[str, float]]:
    """MEASURED per-device bytes of a tree of live jax.Arrays: real
    ``addressable_shards`` buffer sizes summed per device.  Returns
    ``(max_per_device, {device_id: bytes})`` — the cross-check's ground
    truth (host numpy leaves count as replicated-everywhere)."""
    per_dev: Dict[str, float] = {}
    n_dev = max(jax.local_device_count(), 1)
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                key = str(getattr(s.device, "id", s.device))
                data = s.data
                per_dev[key] = per_dev.get(key, 0.0) + float(
                    getattr(data, "nbytes", 0)
                )
        else:  # host value: charge every device (it will replicate)
            b = _leaf_bytes(leaf)
            for d in range(n_dev):
                per_dev[str(d)] = per_dev.get(str(d), 0.0) + b
    return (max(per_dev.values()) if per_dev else 0.0), per_dev


def cross_check(analytic_bytes: float, measured_bytes: float,
                tolerance: float = 0.10) -> dict:
    """Agreement verdict between the analytic walk and a measurement.
    ``ratio`` is analytic/measured; ``ok`` within ``tolerance``."""
    measured = float(measured_bytes)
    analytic = float(analytic_bytes)
    ratio = analytic / measured if measured > 0 else float("inf")
    return {
        "analytic_bytes": int(analytic),
        "measured_bytes": int(measured),
        "ratio": round(ratio, 4),
        "tolerance": tolerance,
        "ok": bool(measured > 0 and abs(ratio - 1.0) <= tolerance),
    }


# ------------------------------------------------------------- live side
def live_memory_snapshot() -> dict:
    """Per-device live memory: TPU ``memory_stats()`` (bytes_in_use +
    peak_bytes_in_use) or, where the backend has no allocator stats
    (CPU), the sum of live jax.Array buffer bytes per device — the
    graceful fallback that keeps the cross-check meaningful on the
    virtual-device test meshes."""
    devices = jax.local_devices()
    per_dev: Dict[str, dict] = {}
    source = "memory_stats"
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            per_dev[str(d.id)] = {
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0))
                ),
            }
        else:
            source = "live_arrays"
            per_dev = {}
            break
    if not per_dev:
        acc: Dict[str, float] = {str(d.id): 0.0 for d in devices}
        try:
            arrays = jax.live_arrays()
        except Exception:
            arrays = []
        for arr in arrays:
            for s in getattr(arr, "addressable_shards", []) or []:
                key = str(getattr(s.device, "id", s.device))
                if key in acc:
                    acc[key] += float(getattr(s.data, "nbytes", 0))
        per_dev = {
            k: {"bytes_in_use": int(v), "peak_bytes_in_use": int(v)}
            for k, v in acc.items()
        }
    return {
        "backend": jax.default_backend(),
        "source": source,
        "devices": per_dev,
        "max_bytes_in_use": max(
            (v["bytes_in_use"] for v in per_dev.values()), default=0
        ),
        "max_peak_bytes_in_use": max(
            (v["peak_bytes_in_use"] for v in per_dev.values()), default=0
        ),
    }


def publish_live_memory(snapshot: Optional[dict] = None,
                        registry=None) -> dict:
    """Emit the live snapshot as ``mem_live_bytes{device=}`` /
    ``mem_live_peak_bytes{device=}`` gauges; returns the snapshot."""
    from ml_trainer_tpu.telemetry.registry import default_registry

    snap = snapshot if snapshot is not None else live_memory_snapshot()
    r = registry if registry is not None else default_registry()
    g_now = r.gauge(
        "mem_live_bytes",
        f"live per-device bytes in use (source: {snap['source']})",
        ("device",),
    )
    g_peak = r.gauge(
        "mem_live_peak_bytes",
        "per-device peak bytes in use (TPU allocator; = live on the "
        "CPU live-array fallback)",
        ("device",),
    )
    for dev, v in snap["devices"].items():
        g_now.labels(device=dev).set(float(v["bytes_in_use"]))
        g_peak.labels(device=dev).set(float(v["peak_bytes_in_use"]))
    return snap


def memory_snapshot_payload() -> dict:
    """Small JSON-safe payload flight dumps attach: the live per-device
    view plus the last published analytic component split."""
    payload = {"live": live_memory_snapshot()}
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        snap = default_registry().snapshot()
        comp = {
            k[len("mem_analytic_bytes{component="):-1]: v
            for k, v in snap.items()
            if k.startswith("mem_analytic_bytes{component=")
        }
        if comp:
            payload["analytic_components"] = comp
        for k in ("mem_analytic_resident_bytes", "mem_analytic_peak_bytes"):
            if k in snap:
                payload[k] = snap[k]
    except Exception:
        pass
    return payload


# -------------------------------------------------------- trainer ledger
def _batch_component(batch_shape, dtype, data_parallel: int) -> Component:
    itemsize = jnp.dtype(dtype).itemsize
    per_dev = (
        float(np.prod(batch_shape, initial=1)) * itemsize
        / max(data_parallel, 1)
    )
    return Component(
        "batch", per_dev * _BATCH_BUFFERS, "resident",
        {"shape": list(batch_shape), "dtype": str(jnp.dtype(dtype)),
         "buffers": _BATCH_BUFFERS},
    )


def _loss_chunk_component(model, batch_shape,
                          data_parallel: int) -> Optional[Component]:
    """Chunked-LM-head peak: one fp32 logits chunk [b, chunk, V] lives
    during the forward and again (with its cotangent) in the backward."""
    chunk = int(getattr(model, "loss_chunk", 0) or 0)
    vocab = int(getattr(model, "vocab_size", 0) or 0)
    if not chunk or not vocab or len(batch_shape) < 2:
        return None
    b_local = max(int(batch_shape[0]) // max(data_parallel, 1), 1)
    chunk = min(chunk, int(batch_shape[1]))
    bytes_ = float(b_local) * chunk * vocab * 4 * 2  # chunk + cotangent
    return Component(
        "loss_chunk_peak", bytes_, "transient",
        {"chunk": chunk, "vocab": vocab, "local_batch": b_local},
    )


def _pipeline_stash_component(model, batch_shape,
                              info: Optional[dict] = None
                              ) -> Optional[Component]:
    """Activation stash of the pipeline engine, sized from the SAME
    numbers ``parallel/pipeline.py`` records at trace time
    (``stash_slots`` for the remat table, the [V, M] boundary stash for
    the value pass) — or from the formula when no trace has run yet."""
    n_stages = int(getattr(model, "n_stages", 0) or 0)
    if not n_stages:
        return None
    n_micro = int(getattr(model, "n_microbatches", 0) or 0) or n_stages
    n_virtual = int(getattr(model, "n_virtual", 1) or 1)
    remat = bool(getattr(model, "remat", True))
    embed = int(getattr(model, "embed_dim", 0) or 0)
    if len(batch_shape) < 2 or not embed:
        return None
    if info is None:
        from ml_trainer_tpu.parallel.pipeline import pipeline_schedule_info

        pinfo = pipeline_schedule_info()
        sched = str(getattr(model, "schedule", "gpipe"))
        info = pinfo.get(sched)
    # Microbatch boundary activation: [B/M, S, d] at the model dtype.
    dtype = getattr(model, "dtype", jnp.float32)
    itemsize = jnp.dtype(dtype).itemsize
    mb_rows = max(int(batch_shape[0]) // max(n_micro, 1), 1)
    mb_bytes = float(mb_rows) * int(batch_shape[1]) * embed * itemsize
    if info and info.get("stash_slots"):
        slots = int(info["stash_slots"])
        src = "traced"
    elif info and info.get("boundary_stash_microbatches"):
        slots = int(info["boundary_stash_microbatches"]) * n_virtual
        src = "traced"
    else:
        # The engine's documented bounds: remat keeps ~S*V microbatches
        # in flight; the no-remat value pass stashes every [V, M]
        # boundary activation.
        slots = n_stages * n_virtual if remat else n_virtual * n_micro
        src = "formula"
    return Component(
        "pipeline_stash", mb_bytes * slots, "transient",
        {"slots": slots, "microbatch_bytes": int(mb_bytes),
         "source": src, "remat": remat},
    )


def train_ledger(trainer, batch_shape: Optional[Sequence[int]] = None,
                 batch_dtype=None) -> MemoryLedger:
    """Analytic per-device ledger of a BUILT Trainer — a pure metadata
    walk of its state tree + sharding specs plus the step transients.
    ``batch_shape`` defaults to the trainer's global batch geometry."""
    state = trainer.state
    if state is None:
        raise ValueError("trainer has no state (datasets were not given)")
    comps: List[Component] = []
    notes: List[str] = []
    shardings = trainer._state_shardings

    def add(name, tree, sh_tree, kind="resident", detail=None):
        if tree is None:
            return
        b = tree_device_bytes(tree, sh_tree)
        if b > 0:
            comps.append(Component(name, b, kind, detail or {}))

    add("params", state.params, shardings.params)
    add("opt_state", state.opt_state, shardings.opt_state,
        detail={"zero1": bool(trainer._shard_opt_state)})
    if state.batch_stats:
        add("batch_stats", state.batch_stats, shardings.batch_stats)
    if state.ema_params is not None:
        add("ema_params", state.ema_params, shardings.ema_params)
    # Gradients: live at full LOCAL param size in fp32 during the
    # backward on every path (the sharded update reduce-scatters them
    # AFTER they materialize), so the peak charges the fp32 mirror.
    grad_bytes = sum(
        _leaf_device_bytes(l, s) / jnp.dtype(l.dtype).itemsize * 4
        for l, s in zip(
            jax.tree.leaves(state.params),
            jax.tree.leaves(shardings.params),
        )
    )
    comps.append(Component("grads", grad_bytes, "transient",
                           {"dtype": "float32"}))
    if trainer._compute_dtype is not None:
        # bf16 policy: the cast compute copy of the params coexists with
        # the fp32 masters through the step.
        comps.append(Component(
            "bf16_param_cast", grad_bytes / 2.0, "transient",
            {"dtype": str(jnp.dtype(trainer._compute_dtype))},
        ))
    shape = tuple(
        batch_shape
        if batch_shape is not None
        else getattr(trainer, "_batch_geometry", ()) or ()
    )
    if len(shape) > 1:
        comps.append(_batch_component(
            shape,
            batch_dtype or getattr(trainer, "_batch_dtype", None)
            or jnp.float32,
            trainer._data_parallel,
        ))
        lc = _loss_chunk_component(trainer.model, shape,
                                   trainer._data_parallel)
        if lc is not None:
            comps.append(lc)
        ps = _pipeline_stash_component(trainer.model, shape)
        if ps is not None:
            comps.append(ps)
    else:
        notes.append("batch geometry unknown: batch/transient rows omitted")
    return MemoryLedger(comps, notes)


# -------------------------------------------------------- formula ledger
def _spec_factor(shape, spec, axis_sizes: Dict[str, int]) -> float:
    """Division factor a PartitionSpec implies for ``shape`` (pure
    arithmetic — no Mesh object, so the planner can price topologies
    this host cannot build)."""
    factor = 1.0
    for dim, axes in zip(shape, tuple(spec) + (None,) * len(shape)):
        if axes is None:
            continue
        axes = axes if isinstance(axes, tuple) else (axes,)
        size = int(np.prod([axis_sizes.get(a, 1) for a in axes], initial=1))
        if size > 1 and dim % size == 0:
            factor *= size
    return factor


def _resolve_rule_spec(path_name: str, rules) -> Optional[tuple]:
    for pat, spec in (rules or []):
        if re.search(pat, path_name):
            return tuple(spec)
    return None


def plan_train_memory(
    model, batch_shape: Sequence[int], *,
    optimizer: str = "adamw",
    mesh_shape: Optional[Dict[str, int]] = None,
    sharding_rules=None,
    shard_opt_state: bool = False,
    dp_update: str = "fused",
    precision: Optional[str] = None,
    ema: bool = False,
    grad_accum_steps: int = 1,
    batch_dtype=None,
) -> MemoryLedger:
    """Formula-driven per-device ledger — no state built, no device
    memory touched (``jax.eval_shape`` only), so ``bench.py --memplan``
    can price a config BEFORE trying to allocate it.

    Division rules mirror the Trainer's placement exactly: params
    replicate over data axes and divide per ``sharding_rules`` on model
    axes; ZeRO-1 (``shard_opt_state`` / ``dp_update='sharded'``) moment
    leaves whose dim 0 divides the data degree go ÷N (the
    ``zero1_opt_shardings`` rule); the batch divides over data axes."""
    from ml_trainer_tpu.models.registry import get_model
    from ml_trainer_tpu.ops import get_optimizer
    from ml_trainer_tpu.parallel.sharding import path_str

    if isinstance(model, str):
        model = get_model(model)
    mesh_shape = dict(mesh_shape or {})
    axis_sizes = {a: int(n) for a, n in mesh_shape.items()}
    data_parallel = int(np.prod(
        [axis_sizes.get(a, 1) for a in ("data", "fsdp")], initial=1
    ))
    zero1 = bool(shard_opt_state) or dp_update == "sharded"
    notes: List[str] = []

    # Abstract init: parameter shapes without allocating anything.
    x_shape = jax.ShapeDtypeStruct(
        tuple(batch_shape),
        jnp.dtype(batch_dtype) if batch_dtype is not None else (
            jnp.int32 if len(batch_shape) == 2 else jnp.float32
        ),
    )
    import inspect

    init_kwargs = {}
    try:
        if "train" in inspect.signature(model.__call__).parameters:
            init_kwargs["train"] = False
    except (TypeError, ValueError):
        pass
    variables = jax.eval_shape(
        lambda r, x: model.init(r, x, **init_kwargs),
        jax.random.PRNGKey(0), x_shape,
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = get_optimizer(optimizer, 1e-3)
    opt_shapes = jax.eval_shape(tx.init, params)

    def param_leaf_bytes(path, leaf):
        spec = _resolve_rule_spec(path_str(path), sharding_rules)
        factor = (
            _spec_factor(leaf.shape, spec, axis_sizes) if spec else 1.0
        )
        return _leaf_bytes(leaf) / factor

    p_items = jax.tree_util.tree_flatten_with_path(params)[0]
    params_bytes = sum(param_leaf_bytes(p, l) for p, l in p_items)
    comps: List[Component] = [
        Component("params", params_bytes, "resident",
                  {"leaves": len(p_items)}),
    ]
    if batch_stats:
        comps.append(Component(
            "batch_stats",
            sum(_leaf_bytes(l) for l in jax.tree.leaves(batch_stats)),
            "resident",
        ))

    # Optimizer moments: a moment leaf inherits its param's rule-shard
    # factor (moments are born with the param's sharding); under ZeRO-1
    # a replicated leaf whose dim 0 divides N additionally goes ÷N.
    by_shape: Dict[tuple, float] = {}
    for p, l in p_items:
        spec = _resolve_rule_spec(path_str(p), sharding_rules)
        if spec:
            by_shape.setdefault(
                tuple(l.shape), _spec_factor(l.shape, spec, axis_sizes)
            )
    opt_bytes = 0.0
    for leaf in jax.tree.leaves(opt_shapes):
        b = _leaf_bytes(leaf)
        shape = tuple(getattr(leaf, "shape", ()))
        factor = by_shape.get(shape, 1.0)
        if (
            zero1 and factor == 1.0 and shape
            and data_parallel > 1 and shape[0] % data_parallel == 0
        ):
            factor = float(data_parallel)
        opt_bytes += b / factor
    comps.append(Component(
        "opt_state", opt_bytes, "resident",
        {"optimizer": optimizer, "zero1": zero1,
         "data_parallel": data_parallel},
    ))
    if ema:
        comps.append(Component("ema_params", params_bytes, "resident"))

    comps.append(Component("grads", params_bytes, "transient",
                           {"dtype": "float32"}))
    if precision not in (None, "fp32", "float32"):
        comps.append(Component(
            "bf16_param_cast", params_bytes / 2.0, "transient",
            {"dtype": str(precision)},
        ))
    comps.append(_batch_component(
        batch_shape, x_shape.dtype, data_parallel
    ))
    lc = _loss_chunk_component(model, batch_shape, data_parallel)
    if lc is not None:
        comps.append(lc)
    ps = _pipeline_stash_component(model, batch_shape, info={})
    if ps is not None:
        comps.append(ps)
    act = activation_bytes(model, batch_shape, data_parallel,
                           grad_accum_steps=grad_accum_steps)
    if act is not None:
        comps.append(Component(
            "activations_est", act, "transient",
            {"estimate": True, "grad_accum_steps": grad_accum_steps},
        ))
    else:
        notes.append(
            f"no activation model for {type(model).__name__}: peak "
            "underestimates the backward's stash"
        )
    return MemoryLedger(comps, notes)


def activation_bytes(model, batch_shape, data_parallel: int = 1,
                     grad_accum_steps: int = 1) -> Optional[float]:
    """Coarse transformer activation estimate for the planner: ~12
    boundary-sized tensors per block live for the backward (attention
    scores excluded — the flash path never materializes S×S).  Returns
    None for families without a rule (conv nets) — callers must treat
    that as "not modeled", never as zero."""
    name = type(model).__name__
    if name not in ("GPT2", "GPT2Pipelined", "BertEncoder", "LlamaLM",
                    "VisionTransformer"):
        return None
    d = int(getattr(model, "embed_dim", 0) or 0)
    depth = int(getattr(model, "depth", 0) or 0)
    if not depth:
        depth = int(getattr(model, "n_stages", 0) or 0) * int(
            getattr(model, "blocks_per_stage", 1) or 1
        )
    if not d or not depth or len(batch_shape) < 2:
        return None
    if name == "VisionTransformer":
        p = int(model.patch_size)
        seq = (int(batch_shape[1]) // p) * (int(batch_shape[2]) // p) + 1
    else:
        seq = int(batch_shape[1])
    b_local = max(
        int(batch_shape[0]) // max(data_parallel * grad_accum_steps, 1), 1
    )
    dtype = getattr(model, "dtype", jnp.float32)
    itemsize = jnp.dtype(dtype).itemsize
    return float(b_local) * seq * d * depth * 12 * itemsize


def bench_step_ledger(state, model, batch) -> MemoryLedger:
    """Ledger for a bare bench train step (bench.py model rows): the
    state tree as resident, fp32 grads + the chunked-LM-head peak as
    transients, plus the one on-device batch."""
    comps = [
        Component("state", tree_device_bytes(state), "resident"),
        Component(
            "grads",
            sum(
                _leaf_device_bytes(l) / jnp.dtype(l.dtype).itemsize * 4
                for l in jax.tree.leaves(state.params)
            ),
            "transient", {"dtype": "float32"},
        ),
    ]
    batch_bytes = sum(
        float(getattr(a, "nbytes", 0)) for a in jax.tree.leaves(batch)
    )
    if batch_bytes:
        comps.append(Component("batch", batch_bytes, "resident"))
        x = jax.tree.leaves(batch)[0]
        lc = _loss_chunk_component(model, getattr(x, "shape", ()), 1)
        if lc is not None:
            comps.append(lc)
    return MemoryLedger(comps)


# ------------------------------------------------------------ serving KV
def kv_pool_bytes(n_pages: int, page_size: int, num_heads: int,
                  head_dim: int, n_layers: int,
                  dtype=jnp.float32) -> float:
    """Total device bytes of a paged KV pool: pages × H × page × D ×
    dtype, × n_layers × 2 (K and V) — the ``serving_kv_pool_bytes``
    geometry (the trash page 0 is device memory too, so it counts)."""
    itemsize = jnp.dtype(dtype).itemsize
    return (
        float(n_pages) * num_heads * page_size * head_dim
        * itemsize * n_layers * 2
    )


def adapter_pool_bytes(slots: int, rank: int, target_dims,
                       dtype=jnp.float32) -> float:
    """Total device bytes of a batched-LoRA adapter pool
    (serving/adapter_pool.py): per targeted projection instance
    (one ``(in_dim, out_dim)`` entry in ``target_dims`` PER LAYER) the
    pool holds stacks ``A [slots, in, rank]`` + ``B [slots, rank, out]``
    — so ``slots × rank × Σ(in + out) × itemsize``.  The trash slot 0
    is device memory too, so it counts (the ``kv_pool_bytes`` rule).
    """
    itemsize = jnp.dtype(dtype).itemsize
    total_dims = sum(int(i) + int(o) for i, o in target_dims)
    return float(slots) * rank * total_dims * itemsize


def gpt2_lora_target_dims(model, targets) -> List[Tuple[int, int]]:
    """The ``(in, out)`` pairs :func:`adapter_pool_bytes` needs for a
    GPT-2-family config: per layer, qkv ``E -> 3E``, proj ``E -> E``,
    fc_in ``E -> 4E``, fc_out ``4E -> E``."""
    e = int(model.embed_dim)
    per_layer = {
        "qkv": (e, 3 * e),
        "proj": (e, e),
        "fc_in": (e, 4 * e),
        "fc_out": (4 * e, e),
    }
    depth = int(getattr(model, "depth", 0) or 0)
    return [per_layer[t] for _ in range(depth) for t in targets]


def serving_kv_ledger(engine) -> MemoryLedger:
    """Per-device ledger of a serving engine's KV memory (paged pool or
    contiguous slots) measured from its cache tree metadata — plus the
    LoRA adapter pool's stacks when the engine serves adapters."""
    comps: List[Component] = []
    if getattr(engine, "_lora_on", False):
        pool = engine.adapters
        stack_bytes = sum(
            _leaf_bytes(l) for l in jax.tree.leaves(engine._lora_stacks)
        )
        comps.append(Component(
            "adapter_pool", stack_bytes, "resident",
            {"slots": pool.slots, "rank": pool.rank,
             "targets": list(pool.targets),
             "bytes_per_slot": int(stack_bytes / max(pool.slots, 1))},
        ))
    cache_bytes = tree_device_bytes(engine.cache)
    if getattr(engine, "paged", False):
        pool_leaves = [
            l for l in jax.tree.leaves(engine.cache)
            if getattr(l, "ndim", 0) >= 1
            and l.shape[0] == engine.kv_pages
        ]
        pool_bytes = sum(_leaf_bytes(l) for l in pool_leaves)
        comps.append(Component(
            "kv_pool", pool_bytes, "resident",
            {"pages": engine.kv_pages, "page_size": engine.kv_page_size,
             "bytes_per_page": int(pool_bytes / max(engine.kv_pages, 1))},
        ))
        other = cache_bytes - pool_bytes
        if other > 0:
            comps.append(Component("kv_cache_other", other, "resident"))
    else:
        comps.append(Component(
            "kv_slots", cache_bytes, "resident",
            {"max_batch": engine.max_batch, "max_len": engine.max_len},
        ))
    return MemoryLedger(comps)


# ---------------------------------------------------------------- planner
def fit_verdict(peak_bytes: float, capacity_bytes: Optional[float] = None,
                margin: float = 0.9) -> dict:
    """fit-or-OOM verdict: predicted peak vs chip HBM capacity.  "fits"
    under ``margin`` × capacity, "tight" under capacity, else "oom"."""
    from ml_trainer_tpu.telemetry.flops import (
        chip_generation_label,
        chip_hbm_capacity_bytes,
    )

    cap = (
        float(capacity_bytes) if capacity_bytes is not None
        else chip_hbm_capacity_bytes()
    )
    frac = peak_bytes / cap if cap > 0 else float("inf")
    verdict = "fits" if frac <= margin else ("tight" if frac <= 1.0 else "oom")
    return {
        "peak_bytes": int(peak_bytes),
        "capacity_bytes": int(cap),
        "chip": chip_generation_label(),
        "utilization": round(frac, 4),
        "margin": margin,
        "verdict": verdict,
    }
