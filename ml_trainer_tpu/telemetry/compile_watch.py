"""Recompile forensics: every XLA compile, named, timed, and explained.

A TPU run that recompiles in steady state is a production incident — a
shape leaked into a traced argument, a Python float toggled weak_type,
a cache key drifted — and the symptom (a multi-second stall every N
steps) points nowhere near the cause.  The repo used to pin "no silent
recompiles" through ad-hoc ``jit._cache_size() == 1`` asserts scattered
across tests and smoke scripts; this module replaces those with one
real instrument on JAX's own compilation path:

* every backend compile is recorded as a :class:`CompileEvent` —
  function name, elapsed ms, timestamp — and counted in the registry as
  ``compile_events_total{fn=...}``;
* the tracing-cache-miss explanation JAX can produce
  (``jax_explain_cache_misses``) is captured and attached to the next
  compile event, so a post-warmup recompile names the offending
  argument and shape (``"at x, seen f32[4], but now given f32[8]"``);
* after :func:`mark_warm` (the Trainer calls it once its first epoch —
  train + eval — has compiled everything it legitimately needs), each
  further compile ALSO fires a flight-recorder ``recompile`` event and
  bumps ``compile_events_post_warmup_total``, so an OOM/wedge dump
  shows the compile storm right next to the steps it stalled;
* compile seconds feed the goodput ledger's ``compile`` bucket
  (``telemetry/goodput.py``) — wall-clock attribution, not just counts.

Mechanism: :func:`install` wraps ``jax._src.dispatch.log_elapsed_time``
(the one funnel both the pjit and pmap lowering paths time their
backend compiles through — looked up as a module attribute at call
time, so the wrap takes effect everywhere) and registers a capture
handler on the ``jax._src.pjit`` logger for the cache-miss
explanations.  If a future jax moves the funnel, ``install`` degrades
to the public ``jax.monitoring`` duration listener — counts and
elapsed survive, function names become ``"unknown"``.  The observed
programs are untouched: this is pure host-side bookkeeping, so the
compiled-step trajectory stays bit-identical with the watch installed
(test-pinned).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional

from ml_trainer_tpu.utils.logging import get_logger

logger = get_logger("ml_trainer_tpu.telemetry")

# The jax.monitoring key the backend-compile timer records under —
# public, stable across 0.4.x (jax._src.dispatch.BACKEND_COMPILE_EVENT).
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_MAX_EVENTS = 512  # bounded ring; a compile storm must not grow the host
_MAX_EXPLANATION = 2000  # chars kept of a cache-miss explanation


@dataclasses.dataclass
class CompileEvent:
    """One backend (XLA) compile."""

    seq: int
    fn: str
    elapsed_ms: float
    t: float  # time.time() at completion
    after_warmup: bool
    explanation: Optional[str] = None  # tracing-cache-miss forensics

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["elapsed_ms"] = round(d["elapsed_ms"], 3)
        return d


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.installed = False
        self.mode = "off"  # "patched" | "monitoring" | "off"
        self.events: List[CompileEvent] = []
        self.seq = 0
        self.total = 0
        self.post_warmup = 0
        self.warm = False
        self.by_fn: Dict[str, int] = {}
        self.pending_explanation: Optional[str] = None
        self.orig_log_elapsed = None
        self.explain_handler: Optional[logging.Handler] = None
        self.explain_prev_propagate: Optional[bool] = None
        self.explain_prev_config: Optional[bool] = None


_state = _State()


class _ExplainHandler(logging.Handler):
    """Captures ``TRACING CACHE MISS`` explanations (jax._src.pjit logs
    them at WARNING when ``jax_explain_cache_misses`` is on) so the next
    compile event can name the offending argument/shape."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        if "TRACING CACHE MISS" not in msg:
            return
        with _state.lock:
            _state.pending_explanation = msg[:_MAX_EXPLANATION]


def _on_compile(fn: str, elapsed_s: float) -> None:
    """One finished backend compile: ring + counters + (post-warmup)
    flight forensics + the goodput ledger's compile bucket."""
    now = time.time()
    with _state.lock:
        _state.seq += 1
        _state.total += 1
        _state.by_fn[fn] = _state.by_fn.get(fn, 0) + 1
        warm = _state.warm
        if warm:
            _state.post_warmup += 1
        explanation, _state.pending_explanation = (
            _state.pending_explanation, None
        )
        ev = CompileEvent(
            seq=_state.seq, fn=fn, elapsed_ms=elapsed_s * 1e3, t=now,
            after_warmup=warm, explanation=explanation,
        )
        _state.events.append(ev)
        del _state.events[:-_MAX_EVENTS]
    # Registry + goodput + flight OUTSIDE the lock (they take their own).
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = default_registry()
        r.counter(
            "compile_events_total",
            "XLA backend compiles observed this process",
            ("fn",),
        ).labels(fn=fn).inc()
        if warm:
            r.counter(
                "compile_events_post_warmup_total",
                "compiles AFTER the owning loop declared warmup done — "
                "each one is a steady-state recompile to investigate",
            ).inc()
    except Exception:  # the instrument must never break a compile
        pass
    try:
        from ml_trainer_tpu.telemetry import goodput

        goodput.account("compile", elapsed_s)
    except Exception:
        pass
    if warm:
        try:
            from ml_trainer_tpu.telemetry.flight import get_recorder

            get_recorder().record(
                "recompile", fn=fn, elapsed_ms=round(elapsed_s * 1e3, 3),
                explanation=explanation,
            )
        except Exception:
            pass
        logger.warning(
            f"post-warmup recompile: {fn} ({elapsed_s * 1e3:.1f}ms)"
            + (f"\n{explanation}" if explanation else "")
        )


def _patched_log_elapsed_time(orig):
    @contextlib.contextmanager
    def wrapped(fmt, fun_name, event=None):
        t0 = time.perf_counter()
        with orig(fmt, fun_name, event=event):
            yield
        if event == BACKEND_COMPILE_EVENT:
            _on_compile(str(fun_name), time.perf_counter() - t0)

    return wrapped


def install() -> str:
    """Install the compile watch (idempotent).  Returns the active mode:
    ``"patched"`` (full forensics) or ``"monitoring"`` (counts + elapsed
    only — the jax internals moved)."""
    with _state.lock:
        if _state.installed:
            return _state.mode
        _state.installed = True
    # Register the post-warmup counter eagerly (at 0): the fleet's
    # metrics federation (serving/router.py) pins every worker's
    # ``compile_events_post_warmup_total`` in the merged exposition —
    # absence must mean "watch not installed", never "no recompile yet".
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        default_registry().counter(
            "compile_events_post_warmup_total",
            "compiles AFTER the owning loop declared warmup done — "
            "each one is a steady-state recompile to investigate",
        )
    except Exception:
        pass
    import jax

    mode = "monitoring"
    try:
        from jax._src import dispatch as _dispatch

        orig = _dispatch.log_elapsed_time
        _dispatch.log_elapsed_time = _patched_log_elapsed_time(orig)
        _state.orig_log_elapsed = orig
        mode = "patched"
    except Exception as e:
        logger.warning(
            f"compile watch: jax internals moved ({e}); falling back to "
            "the monitoring listener (no function names)"
        )
        import jax.monitoring as _mon

        def _listener(key, dur, **kw):
            if key == BACKEND_COMPILE_EVENT:
                _on_compile("unknown", float(dur))

        _mon.register_event_duration_secs_listener(_listener)
    # Cache-miss explanations: jax logs them (WARNING, jax._src.pjit)
    # when the flag is on; our handler captures, propagation is silenced
    # while installed so every first-seen-function trace does not spam
    # the user's log (uninstall restores both).
    try:
        plog = logging.getLogger("jax._src.pjit")
        handler = _ExplainHandler()
        plog.addHandler(handler)
        _state.explain_handler = handler
        _state.explain_prev_propagate = plog.propagate
        plog.propagate = False
        _state.explain_prev_config = bool(
            jax.config.jax_explain_cache_misses
        )
        jax.config.update("jax_explain_cache_misses", True)
    except Exception:
        _state.explain_handler = None
    _state.mode = mode
    logger.info(f"compile watch installed (mode={mode})")
    return mode


def uninstall() -> None:
    """Remove the watch and restore jax's hooks (tests only)."""
    with _state.lock:
        if not _state.installed:
            return
        _state.installed = False
        _state.mode = "off"
    if _state.orig_log_elapsed is not None:
        try:
            from jax._src import dispatch as _dispatch

            _dispatch.log_elapsed_time = _state.orig_log_elapsed
        except Exception:
            pass
        _state.orig_log_elapsed = None
    if _state.explain_handler is not None:
        try:
            import jax

            plog = logging.getLogger("jax._src.pjit")
            plog.removeHandler(_state.explain_handler)
            if _state.explain_prev_propagate is not None:
                plog.propagate = _state.explain_prev_propagate
            if _state.explain_prev_config is not None:
                jax.config.update(
                    "jax_explain_cache_misses", _state.explain_prev_config
                )
        except Exception:
            pass
        _state.explain_handler = None


def installed() -> bool:
    with _state.lock:
        return _state.installed


def mark_warm() -> None:
    """Declare warmup over: every compile from here on is a steady-state
    recompile (flight ``recompile`` event + post-warmup counter)."""
    with _state.lock:
        _state.warm = True


def mark_cold() -> None:
    """Re-open warmup (a new model/config is about to compile on
    purpose — e.g. a second Trainer in the same process)."""
    with _state.lock:
        _state.warm = False


def is_warm() -> bool:
    with _state.lock:
        return _state.warm


def compile_count(fn: Optional[str] = None) -> int:
    """Total compiles observed (optionally for one function label)."""
    with _state.lock:
        if fn is None:
            return _state.total
        return _state.by_fn.get(fn, 0)


def post_warmup_count() -> int:
    with _state.lock:
        return _state.post_warmup


def counts_by_fn() -> Dict[str, int]:
    with _state.lock:
        return dict(_state.by_fn)


def events(last: Optional[int] = None) -> List[CompileEvent]:
    """The recorded compile events, oldest first (``last`` trims)."""
    with _state.lock:
        evs = list(_state.events)
    return evs[-last:] if last else evs


def recent_events_payload(last: int = 16) -> list:
    """JSON-safe tail of the compile ring — what a flight dump attaches
    so OOM/wedge forensics show the compile storm beside the steps."""
    return [e.as_dict() for e in events(last=last)]


def reset() -> None:
    """Clear counters/events (tests; the install state is untouched)."""
    with _state.lock:
        _state.events.clear()
        _state.seq = 0
        _state.total = 0
        _state.post_warmup = 0
        _state.warm = False
        _state.by_fn.clear()
        _state.pending_explanation = None


@contextlib.contextmanager
def expect_no_compiles(where: str = ""):
    """Assert a region compiles NOTHING — the steady-state invariant that
    replaces the old per-function ``_cache_size() == 1`` pins: stronger
    (process-wide, any function) and self-describing on failure."""
    if not installed():
        install()
    before = compile_count()
    yield
    after = compile_count()
    if after != before:
        fresh = events(last=after - before)
        detail = "; ".join(
            f"{e.fn} ({e.elapsed_ms:.1f}ms)"
            + (f" — {e.explanation.splitlines()[0]}" if e.explanation else "")
            for e in fresh
        )
        raise AssertionError(
            f"{after - before} unexpected compile(s)"
            + (f" in {where}" if where else "") + f": {detail}"
        )
