"""Host-side span tracing: Chrome/Perfetto trace events + profile windows.

``span("data_load")`` times a host region and records one Chrome
trace-event (``ph: "X"`` complete event) into a bounded process-wide
buffer; ``save_trace(path)`` writes the buffer as ``{"traceEvents":
[...]}`` JSON that chrome://tracing and ui.perfetto.dev load directly.
Events on the same thread nest by time containment, so a
``span("ckpt_write")`` inside a ``span("epoch")`` renders as a child.

Every span also enters ``utils.profiler.annotate`` (a
``jax.profiler.TraceAnnotation``), so when a ``jax.profiler`` device
trace is live the SAME names appear on the XLA timeline — host spans and
device traces line up by construction.

:class:`StepProfiler` is the on-demand ``jax.profiler`` window: a layer
calls ``on_step(step)`` once per step, and a window of K steps starts
when

* the env var ``ML_TRAINER_TPU_PROFILE`` is ``"<start>:<count>[:logdir]"``
  (armed at construction), or
* a trigger file named by ``ML_TRAINER_TPU_PROFILE_TRIGGER`` appears
  (its first line is ``<count>[:logdir]``; the file is consumed), or
* ``request(count, logdir)`` is called programmatically — the serving
  admin endpoint's path.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Optional

from ml_trainer_tpu.utils.logging import get_logger
from ml_trainer_tpu.utils.profiler import annotate

logger = get_logger("ml_trainer_tpu.telemetry")

# Trace clock: microseconds since process start (Chrome wants µs; a
# perf_counter epoch keeps values small and monotonic).  The monotonic
# epoch is captured in the same instant so timestamps recorded with
# ``time.monotonic()`` elsewhere (request lifecycle stamps — the
# deadline clock) can be converted onto the trace timeline.
_EPOCH = time.perf_counter()
_MONO_EPOCH = time.monotonic()

_MAX_EVENTS = 100_000
_events: collections.deque = collections.deque(maxlen=_MAX_EVENTS)
_events_lock = threading.Lock()


def _now_us() -> float:
    return (time.perf_counter() - _EPOCH) * 1e6


@contextlib.contextmanager
def span(name: str, category: str = "host", **args):
    """Time a host region: one Chrome complete event + an XLA trace
    annotation.  ``args`` (JSON-safe values) land in the event's
    ``args`` payload — visible in the Perfetto detail pane."""
    t0 = _now_us()
    with annotate(name):
        try:
            yield
        finally:
            t1 = _now_us()
            ev = {
                "name": name,
                "cat": category,
                "ph": "X",
                "ts": t0,
                "dur": t1 - t0,
                "pid": os.getpid(),
                "tid": threading.get_ident(),
            }
            if args:
                ev["args"] = args
            with _events_lock:
                _events.append(ev)


def complete_event(name: str, start_mono: float, end_mono: float,
                   category: str = "host", **args) -> None:
    """Record a RETROSPECTIVE complete event from ``time.monotonic()``
    stamps — how a request's lifecycle (submit → queue → prefill →
    decode → finish), known only once it ends, lands on the trace
    timeline as properly nested spans.  Events emitted from one thread
    with containing timestamps nest in Perfetto exactly like live
    ``span()`` regions."""
    t0 = (start_mono - _MONO_EPOCH) * 1e6
    ev = {
        "name": name,
        "cat": category,
        "ph": "X",
        "ts": t0,
        "dur": max((end_mono - start_mono) * 1e6, 0.0),
        "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if args:
        ev["args"] = args
    with _events_lock:
        _events.append(ev)


def instant(name: str, category: str = "event", **args) -> None:
    """A zero-duration marker on the trace timeline (``ph: "i"``)."""
    ev = {
        "name": name, "cat": category, "ph": "i", "s": "t",
        "ts": _now_us(), "pid": os.getpid(), "tid": threading.get_ident(),
    }
    if args:
        ev["args"] = args
    with _events_lock:
        _events.append(ev)


def trace_events() -> list:
    """Point-in-time copy of the buffered events (oldest first)."""
    with _events_lock:
        return list(_events)


def clock_payload() -> dict:
    """This process's trace-clock identity, JSON-safe — what the fleet
    clock handshake exchanges (telemetry/federation.py): the trace
    clock's "now" (the NTP-style sample a caller brackets with its own
    stamps) and the raw monotonic epoch (exact cross-process alignment
    when CLOCK_MONOTONIC is machine-shared, which Linux guarantees)."""
    return {
        "pid": os.getpid(),
        "trace_now_us": _now_us(),
        "mono_epoch": _MONO_EPOCH,
    }


def trace_payload(name: str = "") -> dict:
    """The span buffer plus clock identity — one process's reply to the
    fleet plane's ``GET /trace`` (serving/api.py): everything
    ``Router.save_fleet_trace()`` needs to place this process's lane on
    the merged timeline."""
    payload = clock_payload()
    payload["name"] = name
    payload["events"] = trace_events()
    return payload


def clear_trace() -> None:
    with _events_lock:
        _events.clear()


def save_trace(path: str) -> str:
    """Write the span buffer as Chrome/Perfetto trace-event JSON."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    payload = {
        "traceEvents": trace_events(),
        "displayTimeUnit": "ms",
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(payload, fp)
    os.replace(tmp, path)
    return path


# -- on-demand jax.profiler windows -------------------------------------

PROFILE_ENV = "ML_TRAINER_TPU_PROFILE"
PROFILE_TRIGGER_ENV = "ML_TRAINER_TPU_PROFILE_TRIGGER"
_DEFAULT_LOGDIR = "/tmp/ml_trainer_tpu_profile"


class StepProfiler:
    """Profile steps N..N+K on demand, without restarting the job.

    Thread-safe: ``request()`` may come from any thread (the serving
    admin endpoint), ``on_step()`` from the step-driving thread.  Only
    one window runs at a time; overlapping requests are ignored with a
    log line (``jax.profiler`` cannot nest traces)."""

    def __init__(self, name: str = "train"):
        self.name = name
        self._lock = threading.Lock()
        self._pending: Optional[tuple] = None  # (count, logdir)
        self._active_left = 0
        self._active_logdir: Optional[str] = None
        env = os.environ.get(PROFILE_ENV, "")
        if env:
            try:
                parts = env.split(":", 2)
                start, count = int(parts[0]), int(parts[1])
                logdir = parts[2] if len(parts) > 2 else _DEFAULT_LOGDIR
                self._env_window = (start, count, logdir)
            except (ValueError, IndexError):
                logger.warning(
                    f"ignoring malformed {PROFILE_ENV}={env!r} "
                    "(expected start:count[:logdir])"
                )
                self._env_window = None
        else:
            self._env_window = None

    def request(self, count: int, logdir: Optional[str] = None) -> bool:
        """Arm a window: the next ``count`` steps are traced.  Returns
        False (and changes nothing) when a window is already pending or
        running."""
        if count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        with self._lock:
            if self._pending is not None or self._active_left > 0:
                return False
            self._pending = (int(count), logdir or _DEFAULT_LOGDIR)
            return True

    def _check_trigger_file(self) -> None:
        path = os.environ.get(PROFILE_TRIGGER_ENV, "")
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as fp:
                first = (fp.readline() or "").strip()
            os.remove(path)  # consumed — one window per touch
        except OSError:
            return
        count, _, logdir = first.partition(":")
        try:
            self.request(int(count or 1), logdir or None)
        except ValueError:
            logger.warning(
                f"ignoring malformed profile trigger {first!r} "
                "(expected count[:logdir])"
            )

    def on_step(self, step: int) -> None:
        """Called once per step by the owning loop.  Starts/stops the
        ``jax.profiler`` trace at window boundaries; free when idle."""
        if self._env_window is not None and step == self._env_window[0]:
            self.request(self._env_window[1], self._env_window[2])
        if os.environ.get(PROFILE_TRIGGER_ENV):
            self._check_trigger_file()
        with self._lock:
            start, stop = False, False
            if self._active_left > 0:
                self._active_left -= 1
                if self._active_left == 0:
                    stop = True
            elif self._pending is not None:
                count, logdir = self._pending
                self._pending = None
                self._active_left = count
                self._active_logdir = logdir
                start = True
        # The profiler calls run outside the lock: start_trace can block.
        if start:
            import jax

            logdir = os.path.join(
                self._active_logdir, f"{self.name}_step{step}"
            )
            try:
                jax.profiler.start_trace(logdir)
                instant("profile_window_start", step=step, logdir=logdir)
                logger.info(
                    "profile_window_start", step=step, logdir=logdir
                )
            except Exception as e:  # a live trace elsewhere: skip, don't die
                logger.warning(f"profile window failed to start: {e}")
                with self._lock:
                    self._active_left = 0
        if stop:
            import jax

            try:
                jax.profiler.stop_trace()
                instant("profile_window_stop", step=step)
                logger.info("profile_window_stop", step=step)
            except Exception as e:
                logger.warning(f"profile window failed to stop: {e}")
