"""Attention ops: fused XLA path + Pallas TPU flash-attention kernel.

The reference has no attention code at all (SURVEY.md §5 long-context:
"entirely absent") — this module exists for the north-star model families
(BERT/ViT/GPT-2, BASELINE.json configs[2..4]) and is designed TPU-first:

* ``dot_product_attention`` — the XLA path.  Plain einsum + softmax; XLA
  fuses the mask/scale/softmax chain and tiles the two matmuls onto the MXU.
  Works on any backend (CPU tests run this).
* ``flash_attention`` — a Pallas kernel computing attention with the online
  softmax recurrence, never materializing the [S, S] score matrix in HBM:
  the query block stays in VMEM while KV blocks stream through, carrying
  running (max, sum, output) accumulators.  Backward currently recomputes
  through the XLA path (a true flash backward kernel is a planned
  refinement).
* ``attention`` — dispatcher: 'auto' picks flash on TPU for tile-aligned
  shapes, XLA otherwise.

Shapes follow the TPU-native convention [batch, heads, seq, head_dim].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _mask_bias(mask, dtype):
    return jnp.where(mask, 0.0, jnp.finfo(dtype).min).astype(dtype)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Reference XLA attention.  q,k,v: [B, H, S, D] (k/v may have S_kv)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    s_q, s_k = scores.shape[-2], scores.shape[-1]
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        causal_mask = row + (s_k - s_q) >= col
        scores = scores + _mask_bias(causal_mask, scores.dtype)
    if mask is not None:
        scores = scores + _mask_bias(mask, scores.dtype)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


# --------------------------------------------------------------------- flash
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, o_scr, m_scr, l_scr, *,
                  block_k: int, causal: bool, scale: float):
    """One (batch·head, q-block, kv-block) grid step of the online-softmax
    recurrence.  KV streams through VMEM one [block_k, D] tile at a time
    (the kv grid axis iterates fastest), with running (o, m, l) accumulators
    in VMEM scratch that persist across kv steps; the final kv step
    normalizes and writes the output block."""
    from jax.experimental import pallas as pl

    _, block_q, d = q_ref.shape
    kv_idx = pl.program_id(2)
    num_kv = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q
    kv_start = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        o_scr[:] = jnp.zeros((block_q, d), jnp.float32)
        m_scr[:] = jnp.full((block_q, 1), jnp.finfo(jnp.float32).min,
                            jnp.float32)
        l_scr[:] = jnp.zeros((block_q, 1), jnp.float32)

    # Under causal masking, blocks fully above the diagonal contribute
    # nothing — skip their matmuls entirely.
    live = (q_start + block_q > kv_start) if causal else True

    @pl.when(live)
    def _attend():
        q = q_ref[0].astype(jnp.float32) * scale
        kk = k_ref[0].astype(jnp.float32)
        vv = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            keep = (q_start + row) >= (kv_start + col)
            scores = jnp.where(keep, scores, jnp.finfo(jnp.float32).min)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_scr[:] = o_scr[:] * alpha + jax.lax.dot_general(
            p, vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        o_ref[0] = (o_scr[:] / l_scr[:]).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal, scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    qr = q.reshape(b * h, s_q, d)
    kr = k.reshape(b * h, s_k, d)
    vr = v.reshape(b * h, s_k, d)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale
    )
    grid = (b * h, pl.cdiv(s_q, block_q), pl.cdiv(s_k, block_k))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kv: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, j, kv: (i, kv, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, j, kv: (i, kv, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kv: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, s_q, d)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(
    q, k, v,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Pallas flash attention, [B, H, S, D] -> [B, H, S, D].

    Forward runs the tiled online-softmax kernel; the VJP recomputes through
    ``dot_product_attention`` (O(S²) memory in backward — acceptable at the
    current north-star sequence lengths; a flash backward kernel is the
    planned upgrade).  ``interpret=True`` runs the kernel in interpreter
    mode for CPU tests.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: dot_product_attention(
            q_, k_, v_, causal=causal, scale=scale
        ),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _flash_supported(q, k, block_q, block_k) -> bool:
    s_q, d = q.shape[-2], q.shape[-1]
    s_k = k.shape[-2]
    return (
        jax.default_backend() == "tpu"
        and s_q == s_k  # kernel's causal mask is diagonal-aligned (see below)
        and s_q % block_q == 0
        and s_k % block_k == 0
        and d % 64 == 0  # sublane-friendly head dim (Mosaic pads 64 -> 128)
    )


def attention(
    q, k, v,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    implementation: str = "auto",
    block_q: int = 128,
    block_k: int = 128,
):
    """Dispatch between the Pallas flash kernel and the XLA path.

    ``implementation``: 'auto' | 'xla' | 'flash'.  Arbitrary masks always
    take the XLA path (the flash kernel handles the causal mask only);
    requesting 'flash' with a mask is an error rather than a silent drop.
    The flash kernel also requires s_q == s_k — its causal mask is aligned
    to the main diagonal, whereas the XLA path uses bottom-right alignment
    for cross-length decode shapes.
    """
    if implementation == "flash":
        if mask is not None:
            raise ValueError(
                "flash attention supports the causal mask only; pass "
                "implementation='xla' (or 'auto') for arbitrary masks"
            )
        if q.shape[-2] != k.shape[-2]:
            raise ValueError(
                "flash attention requires equal query/key lengths "
                f"(got {q.shape[-2]} vs {k.shape[-2]}); use the XLA path"
            )
        if q.shape[-2] % block_q or k.shape[-2] % block_k:
            raise ValueError(
                f"flash attention requires sequence lengths divisible by the "
                f"block sizes (S={q.shape[-2]}, block_q={block_q}, "
                f"block_k={block_k}); pad the sequence or use the XLA path"
            )
        return flash_attention(q, k, v, causal, scale, block_q, block_k, False)
    if (
        implementation == "auto"
        and mask is None
        and _flash_supported(q, k, block_q, block_k)
    ):
        return flash_attention(q, k, v, causal, scale, block_q, block_k, False)
    return dot_product_attention(q, k, v, causal=causal, mask=mask, scale=scale)
