"""Attention ops: fused XLA path + Pallas TPU flash-attention kernel.

The reference has no attention code at all (SURVEY.md §5 long-context:
"entirely absent") — this module exists for the north-star model families
(BERT/ViT/GPT-2, BASELINE.json configs[2..4]) and is designed TPU-first:

* ``dot_product_attention`` — the XLA path.  Plain einsum + softmax; XLA
  fuses the mask/scale/softmax chain and tiles the two matmuls onto the MXU.
  Works on any backend (CPU tests run this).
* ``flash_attention`` — a Pallas kernel computing attention with the online
  softmax recurrence, never materializing the [S, S] score matrix in HBM:
  the query block stays in VMEM while KV blocks stream through, carrying
  running (max, sum, output) accumulators.  Backward is the matching
  FlashAttention-2-style block-recompute kernel pair (dQ / dK+dV) driven by
  the saved per-row logsumexp, so memory is O(S) in both directions.
* ``attention`` — dispatcher: 'auto' picks flash on TPU for tile-aligned
  shapes, XLA otherwise.

Shapes follow the TPU-native convention [batch, heads, seq, head_dim].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _mask_bias(mask, dtype):
    return jnp.where(mask, 0.0, jnp.finfo(dtype).min).astype(dtype)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Reference XLA attention.  q,k,v: [B, H, S, D] (k/v may have S_kv)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    s_q, s_k = scores.shape[-2], scores.shape[-1]
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        causal_mask = row + (s_k - s_q) >= col
        scores = scores + _mask_bias(causal_mask, scores.dtype)
    if mask is not None:
        scores = scores + _mask_bias(mask, scores.dtype)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


# --------------------------------------------------------------------- flash
def _flash_kernel(*refs, block_k: int, causal: bool, scale: float,
                  masked: bool):
    """One (batch·head, q-block, kv-block) grid step of the online-softmax
    recurrence.  KV streams through VMEM one [block_k, D] tile at a time
    (the kv grid axis iterates fastest), with running (o, m, l) accumulators
    in VMEM scratch that persist across kv steps; the final kv step
    normalizes and writes the output block.  With ``masked`` a per-sequence
    valid-key count streams in via SMEM and columns past it are dropped —
    the right-padded (BERT) mask family, fused into the kernel instead of
    falling back to the XLA path."""
    from jax.experimental import pallas as pl

    if masked:
        q_ref, k_ref, v_ref, lens_ref, o_ref, lse_ref, o_scr, m_scr, l_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, o_scr, m_scr, l_scr = refs
        lens_ref = None

    _, block_q, d = q_ref.shape
    kv_idx = pl.program_id(2)
    num_kv = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q
    kv_start = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        o_scr[:] = jnp.zeros((block_q, d), jnp.float32)
        m_scr[:] = jnp.full((block_q, 1), jnp.finfo(jnp.float32).min,
                            jnp.float32)
        l_scr[:] = jnp.zeros((block_q, 1), jnp.float32)

    # Under causal masking, blocks fully above the diagonal contribute
    # nothing — skip their matmuls entirely; likewise blocks entirely in
    # the padded key tail.
    kv_len = lens_ref[pl.program_id(0)] if masked else None
    live = (q_start + block_q > kv_start) if causal else True
    if masked:
        live = jnp.logical_and(live, kv_start < kv_len)

    @pl.when(live)
    def _attend():
        q = q_ref[0].astype(jnp.float32) * scale
        kk = k_ref[0].astype(jnp.float32)
        vv = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        keep = _keep_mask(
            (block_q, block_k), q_start, kv_start, kv_len, causal, masked,
        )
        if keep is not None:
            scores = jnp.where(keep, scores, jnp.finfo(jnp.float32).min)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_scr[:] = o_scr[:] * alpha + jax.lax.dot_general(
            p, vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        o_ref[0] = (o_scr[:] / l_scr[:]).astype(o_ref.dtype)
        # Per-row logsumexp of the scaled scores — the only softmax
        # statistic the flash backward needs (FlashAttention-2 style).
        # Written as a [block_q, 1] column: a trailing singleton dim is
        # exempt from Mosaic's (8, 128) block-tiling rule, whereas a
        # [1, block_q] row block is rejected by the compiled lowering
        # (interpret mode never checks this).
        lse_ref[0] = m_scr[:] + jnp.log(l_scr[:])


def _lens_per_bh(kv_lens, b, h):
    """[B] valid-key counts -> [B*H] int32 (one per grid row)."""
    return jnp.repeat(kv_lens.astype(jnp.int32), h)


def _flash_forward(q, k, v, kv_lens, *, causal, scale, block_q, block_k,
                   interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    qr = q.reshape(b * h, s_q, d)
    kr = k.reshape(b * h, s_k, d)
    vr = v.reshape(b * h, s_k, d)
    masked = kv_lens is not None
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale,
        masked=masked,
    )
    grid = (b * h, pl.cdiv(s_q, block_q), pl.cdiv(s_k, block_k))
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j, kv: (i, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, j, kv: (i, kv, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, j, kv: (i, kv, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [qr, kr, vr]
    if masked:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(_lens_per_bh(kv_lens, b, h))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kv: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kv: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return out.reshape(b, h, s_q, d), lse.reshape(b, h, s_q)


# Per-row statistics (lse, delta) travel through the backward kernels as
# [B*H, S, 1] columns with (1, block, 1) blocks for the same Mosaic
# block-tiling reason documented in _flash_kernel's finalize.


def _keep_mask(p_shape, q_start, kv_start, kv_len, causal, masked):
    """The score-keep mask shared by all three kernels (forward and the
    two backward passes): causal diagonal and/or the padded-key tail —
    one definition so value and gradient masking cannot diverge."""
    row = jax.lax.broadcasted_iota(jnp.int32, p_shape, 0)
    col = jax.lax.broadcasted_iota(jnp.int32, p_shape, 1)
    keep = None
    if causal:
        keep = (q_start + row) >= (kv_start + col)
    if masked:
        keep_pad = (kv_start + col) < kv_len
        keep = keep_pad if keep is None else jnp.logical_and(keep, keep_pad)
    return keep


def _flash_bwd_dq_kernel(*refs, block_k: int, causal: bool, scale: float,
                         masked: bool):
    """dQ pass: one q-block stays resident while KV blocks stream through
    (kv is the fastest grid axis); dQ accumulates in VMEM scratch and is
    written once on the last kv step.  Recomputes P from (q, k, lse) — the
    block-recompute that keeps backward memory O(S)."""
    from jax.experimental import pallas as pl

    if masked:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, lens_ref,
         dq_ref, dq_scr) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr = refs
        lens_ref = None

    _, block_q, d = q_ref.shape
    kv_idx = pl.program_id(2)
    num_kv = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q
    kv_start = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        dq_scr[:] = jnp.zeros((block_q, d), jnp.float32)

    kv_len = lens_ref[pl.program_id(0)] if masked else None
    live = (q_start + block_q > kv_start) if causal else True
    if masked:
        live = jnp.logical_and(live, kv_start < kv_len)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        kk = k_ref[0].astype(jnp.float32)
        vv = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                   # [block_q, 1]
        delta = delta_ref[0]               # [block_q, 1]
        scores = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(scores - lse)          # [block_q, block_k]
        keep = _keep_mask(
            p.shape, q_start, kv_start, kv_len, causal, masked,
        )
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dp = jax.lax.dot_general(
            do, vv, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, block_q: int, causal: bool, scale: float,
                          masked: bool):
    """dK/dV pass: one kv-block stays resident while Q blocks stream through
    (q is the fastest grid axis); dK and dV accumulate in VMEM scratch."""
    from jax.experimental import pallas as pl

    if masked:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, lens_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        lens_ref = None

    _, block_k, d = k_ref.shape
    q_idx = pl.program_id(2)
    num_q = pl.num_programs(2)
    kv_start = pl.program_id(1) * block_k
    q_start = q_idx * block_q

    @pl.when(q_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_scr[:] = jnp.zeros((block_k, d), jnp.float32)

    kv_len = lens_ref[pl.program_id(0)] if masked else None
    live = (q_start + block_q > kv_start) if causal else True
    if masked:
        # A kv block entirely in the padded tail gets zero gradient.
        live = jnp.logical_and(live, kv_start < kv_len)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        kk = k_ref[0].astype(jnp.float32)
        vv = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                   # [block_q, 1]
        delta = delta_ref[0]               # [block_q, 1]
        scores = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(scores - lse)          # [block_q, block_k]
        keep = _keep_mask(
            p.shape, q_start, kv_start, kv_len, causal, masked,
        )
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, vv, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(q_idx == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, kv_lens, out, lse, g, *, causal, scale, block_q,
                    block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    qr = q.reshape(b * h, s_q, d)
    kr = k.reshape(b * h, s_k, d)
    vr = v.reshape(b * h, s_k, d)
    dor = g.reshape(b * h, s_q, d)
    lser = lse.reshape(b * h, s_q, 1)
    # delta_i = rowsum(dO_i * O_i) — a cheap elementwise reduce; let XLA
    # fuse it rather than adding a third kernel pass.
    delta = jnp.sum(
        dor.astype(jnp.float32) * out.reshape(b * h, s_q, d).astype(jnp.float32),
        axis=-1, keepdims=True,
    )
    nq, nkv = pl.cdiv(s_q, block_q), pl.cdiv(s_k, block_k)
    masked = kv_lens is not None
    operands = [qr, kr, vr, dor, lser, delta]
    lens_spec = []
    if masked:
        operands.append(_lens_per_bh(kv_lens, b, h))
        lens_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]

    qspec = pl.BlockSpec((1, block_q, d), lambda i, j, x: (i, j, 0),
                         memory_space=pltpu.VMEM)
    kvspec_stream = pl.BlockSpec((1, block_k, d), lambda i, j, x: (i, x, 0),
                                 memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, block_q, 1), lambda i, j, x: (i, j, 0),
                           memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale, masked=masked),
        grid=(b * h, nq, nkv),
        in_specs=[qspec, kvspec_stream, kvspec_stream, qspec, rowspec,
                  rowspec] + lens_spec,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*operands)

    kvspec = pl.BlockSpec((1, block_k, d), lambda i, j, x: (i, j, 0),
                          memory_space=pltpu.VMEM)
    qspec_stream = pl.BlockSpec((1, block_q, d), lambda i, j, x: (i, x, 0),
                                memory_space=pltpu.VMEM)
    rowspec_stream = pl.BlockSpec((1, block_q, 1), lambda i, j, x: (i, x, 0),
                                  memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale, masked=masked),
        grid=(b * h, nkv, nq),
        in_specs=[qspec_stream, kvspec, kvspec, qspec_stream, rowspec_stream,
                  rowspec_stream] + lens_spec,
        out_specs=[kvspec, kvspec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return (
        dq.reshape(b, h, s_q, d),
        dk.reshape(b, h, s_k, d),
        dv.reshape(b, h, s_k, d),
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8)
)
def flash_attention(
    q, k, v,
    kv_lens=None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Pallas flash attention, [B, H, S, D] -> [B, H, S, D].

    Forward runs the tiled online-softmax kernel and saves only the per-row
    logsumexp; the VJP is the FlashAttention-2-style block-recompute pair of
    Pallas kernels (dQ streaming KV, dK/dV streaming Q), so training memory
    stays O(S) — the [S, S] score matrix is never materialized in either
    direction.  ``interpret=True`` runs the kernels in interpreter mode for
    CPU tests.

    ``kv_lens`` ([B] int, or None) masks the padded key tail per sequence —
    key/value positions >= kv_lens[b] are dropped from the softmax (the
    right-padded BERT mask family, fused into the kernel).  Every length
    must be >= 1.  custom_vjp functions take positional arguments only.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, _ = _flash_forward(
        q, k, v, kv_lens, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out


def _flash_fwd(q, k, v, kv_lens, causal, scale, block_q, block_k, interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, lse = _flash_forward(
        q, k, v, kv_lens, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out, (q, k, v, kv_lens, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, kv_lens, out, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    dq, dk, dv = _flash_backward(
        q, k, v, kv_lens, out, lse, g, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    dlens = (
        None if kv_lens is None
        else np.zeros(kv_lens.shape, jax.dtypes.float0)
    )
    return dq, dk, dv, dlens


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _flash_supported(q, k, block_q, block_k) -> bool:
    s_q, d = q.shape[-2], q.shape[-1]
    s_k = k.shape[-2]
    return (
        jax.default_backend() == "tpu"
        and s_q == s_k  # kernel's causal mask is diagonal-aligned (see below)
        and s_q % block_q == 0
        and s_k % block_k == 0
        and d % 64 == 0  # sublane-friendly head dim (Mosaic pads 64 -> 128)
    )


# In 'auto' mode the padded-flash path only engages from this sequence
# length up: padding to the next block multiple costs up to
# (ceil(S/128)*128 / S)^2 extra score FLOPs, which at short S can hand
# back more than flash saves, while the XLA path's materialized [S, S]
# scores are still cheap there.  From ~1K tokens the O(S) memory and
# fused-softmax wins dominate.  Explicit implementation='flash' pads at
# any length.
_AUTO_PAD_MIN_SEQ = 1024


def _flash_padded(q, k, v, kv_lens, causal, scale, block_q, block_k,
                  interpret=False):
    """Run the flash kernel on shapes it cannot take directly, by padding.

    * head_dim -> next multiple of 64: zero-padding q and k adds zero
      terms to every score (q·k over the padded lanes), and zero-padding
      v makes the extra output lanes exact zeros — both sliced off, so
      the result is bit-equivalent math, not an approximation.
    * seq -> next multiple of lcm(block_q, block_k): padded KEYS are
      masked via the kernel's fused ``kv_lens`` right-padding (so they
      contribute nothing forward and get zero dK/dV); padded QUERY rows
      compute values that are sliced off, and their output cotangent is
      zero under the slice's VJP, so ds for those rows vanishes and they
      contribute nothing to dQ/dK/dV either.

    Requires s_q == s_k (the kernel's causal mask is diagonal-aligned);
    ``scale`` is resolved against the ORIGINAL head_dim before padding.
    """
    import math

    b, h, s, d = q.shape
    if scale is None:
        scale = d ** -0.5
    block = math.lcm(block_q, block_k)
    s_pad = -(-s // block) * block
    d_pad = -(-d // 64) * 64
    pad = ((0, 0), (0, 0), (0, s_pad - s), (0, d_pad - d))
    qp, kp, vp = (jnp.pad(t, pad) for t in (q, k, v))
    if kv_lens is None and s_pad == s:
        # Head-dim-only padding adds no masked keys — keep the unmasked
        # kernel variant (no SMEM lens operand, no per-block keep mask).
        lens = None
    elif kv_lens is None:
        lens = jnp.full((b,), s, jnp.int32)
    else:
        lens = jnp.minimum(kv_lens.astype(jnp.int32), s)
    out = flash_attention(
        qp, kp, vp, lens, causal, scale, block_q, block_k, interpret
    )
    return out[..., :s, :d]


def attention(
    q, k, v,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    kv_lens: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    implementation: str = "auto",
    block_q: int = 128,
    block_k: int = 128,
    mesh=None,
    ring_axis: str = "sequence",
):
    """Dispatch between the Pallas flash kernel, ring sequence parallelism
    and the XLA path.

    ``implementation``: 'auto' | 'xla' | 'flash' | 'ring' | 'ulysses'.
    ARBITRARY masks always take the XLA path (requesting 'flash' with one
    is an error rather than a silent drop), but the right-padded mask
    family — ``kv_lens`` [B] valid-key counts, the BERT padding case — is
    fused into the flash kernel, so padded batches keep the O(S) kernel
    instead of falling back.  When both ``mask`` and ``kv_lens`` are given
    they must describe the same thing (callers pass the boolean mask for
    the XLA fallback and the lengths for the kernel); the flash path uses
    only ``kv_lens``.  Lengths are clamped to >= 1 on BOTH paths (a
    zero-length row would divide by an empty softmax in the kernel and
    produce uniform garbage in the fallback — the clamp makes the two
    backends agree on attending key 0).  The flash kernel also requires
    s_q == s_k — its
    causal mask is aligned to the main diagonal, whereas the XLA path uses
    bottom-right alignment for cross-length decode shapes.

    Off-tile shapes (sequence not divisible by the block sizes, head_dim
    not a multiple of 64) run the kernel through ``_flash_padded`` —
    exact math via zero-padding plus the fused kv_lens mask, at the cost
    of the padded block's extra FLOPs.  'flash' pads whenever needed;
    'auto' pads only from ``_AUTO_PAD_MIN_SEQ`` tokens up, where the
    O(S) memory win dominates, and otherwise falls back to XLA.

    'ring' runs sequence-parallel ring attention (parallel.ring) over
    ``mesh[ring_axis]`` — K/V shards rotate around the ICI ring while each
    device attends its local query shard; requires ``mesh``.  'ulysses'
    is the all-to-all variant (parallel.ulysses): one a2a scatters heads /
    gathers sequence, attention runs dense locally, a second a2a restores
    the layout; requires ``mesh`` and heads divisible by the axis size.
    """
    if implementation in ("ring", "ulysses"):
        # Shared preconditions for the sequence-parallel strategies.
        if mask is not None or kv_lens is not None:
            raise ValueError(
                f"{implementation} attention supports the causal mask only; "
                "pass implementation='xla' for arbitrary masks"
            )
        if mesh is None or ring_axis not in mesh.axis_names:
            raise ValueError(
                f"implementation='{implementation}' needs a mesh with a "
                f"live '{ring_axis}' axis (got mesh={mesh})"
            )
        if implementation == "ring":
            from ml_trainer_tpu.parallel.ring import ring_attention as sp_fn
        else:
            from ml_trainer_tpu.parallel.ulysses import (
                ulysses_attention as sp_fn,
            )
        return sp_fn(
            q, k, v, mesh, axis_name=ring_axis, causal=causal, scale=scale
        )
    if kv_lens is not None:
        # Contract: every length >= 1 (see docstring); clamp on both
        # backends so they agree instead of NaN-vs-garbage divergence.
        kv_lens = jnp.maximum(kv_lens, 1)
    if implementation == "flash":
        if mask is not None and kv_lens is None:
            raise ValueError(
                "flash attention supports the causal mask and kv_lens "
                "right-padding only; pass implementation='xla' (or 'auto') "
                "for arbitrary masks"
            )
        if q.shape[-2] != k.shape[-2]:
            raise ValueError(
                "flash attention requires equal query/key lengths "
                f"(got {q.shape[-2]} vs {k.shape[-2]}); use the XLA path"
            )
        if (
            q.shape[-2] % block_q
            or k.shape[-2] % block_k
            or q.shape[-1] % 64
        ):
            # Off-tile shapes run through the padding wrapper — exact
            # math (see _flash_padded), slightly more FLOPs.
            return _flash_padded(
                q, k, v, kv_lens, causal, scale, block_q, block_k
            )
        return flash_attention(
            q, k, v, kv_lens, causal, scale, block_q, block_k, False
        )
    if implementation == "auto" and (mask is None or kv_lens is not None):
        if _flash_supported(q, k, block_q, block_k):
            return flash_attention(
                q, k, v, kv_lens, causal, scale, block_q, block_k, False
            )
        if (
            jax.default_backend() == "tpu"
            and q.shape[-2] == k.shape[-2]
            and q.shape[-2] >= _AUTO_PAD_MIN_SEQ
        ):
            # Long off-tile sequences: the O(S) memory win beats the
            # padding overhead (see _AUTO_PAD_MIN_SEQ rationale).
            return _flash_padded(
                q, k, v, kv_lens, causal, scale, block_q, block_k
            )
    if mask is None and kv_lens is not None:
        # XLA fallback must honor the padding the kernel would have fused.
        mask = (
            jnp.arange(k.shape[-2])[None, None, None, :]
            < kv_lens[:, None, None, None]
        )
    return dot_product_attention(q, k, v, causal=causal, mask=mask, scale=scale)
