"""Compute-path building blocks: optimizers, schedules, losses, metrics,
prediction functions and attention ops.

These are the TPU-native equivalents of the reference's factory methods on
the Trainer (ref: src/trainer.py:115-172) — split into a proper ops layer so
they are pure, jit-able functions instead of device-bound torch modules.
"""

from ml_trainer_tpu.ops.optimizers import (
    decay_mask_matrices_only,
    get_optimizer,
    OPTIMIZERS,
)
from ml_trainer_tpu.ops.schedules import make_lr_schedule, PlateauController, SCHEDULERS
from ml_trainer_tpu.ops.losses import get_criterion, CRITERIA
from ml_trainer_tpu.ops.metrics import get_metric, METRICS
from ml_trainer_tpu.ops.predictions import get_prediction_function, get_predictions

__all__ = [
    "decay_mask_matrices_only",
    "get_optimizer",
    "OPTIMIZERS",
    "make_lr_schedule",
    "PlateauController",
    "SCHEDULERS",
    "get_criterion",
    "CRITERIA",
    "get_metric",
    "METRICS",
    "get_prediction_function",
    "get_predictions",
]
