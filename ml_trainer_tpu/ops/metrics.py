"""Metric engine: 'accuracy' and 'mcrmse', computed on-device.

Reference semantics (ref: src/trainer.py:160-166):

* ``accuracy`` — argmax of (pred-fn-transformed) outputs vs integer targets.
  The reference round-trips through sklearn on the CPU per batch — a device
  sync we replace with a fused jnp mean-of-equality so metrics ride inside
  the compiled step and are fetched once per epoch.
* ``mcrmse`` — mean column-wise RMSE, identical math
  (ref: src/trainer.py:161-163).

Each metric is (outputs, targets) -> scalar; the prediction function is
bound at registry time so the trainer treats all metrics uniformly.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ml_trainer_tpu.ops.predictions import get_predictions


def accuracy(outputs, targets, pred_function: Optional[Callable] = None):
    predictions = get_predictions(outputs, pred_function)
    return jnp.mean((predictions == targets).astype(jnp.float32))


def mcrmse(outputs, targets, pred_function: Optional[Callable] = None):
    colwise_mse = jnp.mean(jnp.square(targets - outputs), axis=0)
    return jnp.mean(jnp.sqrt(colwise_mse), axis=0)


METRICS = {
    "accuracy": accuracy,
    "mcrmse": mcrmse,
}


def get_metric(
    name: Optional[str], pred_function: Optional[Callable] = None
) -> Optional[Callable]:
    """Bind a metric by name; ``None`` disables metrics (ref: main.py:70-71)."""
    if name is None:
        return None
    try:
        fn = METRICS[name]
    except KeyError:
        raise ValueError(
            f"Unknown metric {name!r}; expected one of {sorted(METRICS)}"
        ) from None
    return lambda outputs, targets: fn(outputs, targets, pred_function)
