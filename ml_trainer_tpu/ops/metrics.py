"""Metric engine, computed on-device: the reference pair
('accuracy'/'mcrmse') plus 'top5_accuracy', 'f1' and 'perplexity' for
the north-star model families.

Reference semantics (ref: src/trainer.py:160-166):

* ``accuracy`` — argmax of (pred-fn-transformed) outputs vs integer targets.
  The reference round-trips through sklearn on the CPU per batch — a device
  sync we replace with a fused jnp mean-of-equality so metrics ride inside
  the compiled step and are fetched once per epoch.
* ``mcrmse`` — mean column-wise RMSE, identical math
  (ref: src/trainer.py:161-163).

Each metric is (outputs, targets) -> scalar; the prediction function is
bound at registry time so the trainer treats all metrics uniformly.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ml_trainer_tpu.ops.predictions import get_predictions


def accuracy(outputs, targets, pred_function: Optional[Callable] = None):
    predictions = get_predictions(outputs, pred_function)
    return jnp.mean((predictions == targets).astype(jnp.float32))


def mcrmse(outputs, targets, pred_function: Optional[Callable] = None):
    colwise_mse = jnp.mean(jnp.square(targets - outputs), axis=0)
    return jnp.mean(jnp.sqrt(colwise_mse), axis=0)


def top5_accuracy(outputs, targets, pred_function: Optional[Callable] = None):
    """Target appears in the 5 highest-scoring classes — the ImageNet
    companion metric to top-1 (north-star configs[1..3]).  Monotone
    pred-fns (softmax/logsoftmax) do not change the ranking, so raw
    outputs are ranked directly (lax.top_k: partial selection, not a
    full 1000-class sort per row).  Fewer than 5 classes degenerates to
    plain membership of the full set (k clamps) rather than a trace-time
    crash deep inside the compiled step."""
    _, top5 = jax.lax.top_k(outputs, min(5, outputs.shape[-1]))
    return jnp.mean(
        jnp.any(top5 == targets[..., None], axis=-1).astype(jnp.float32)
    )


def f1(outputs, targets, pred_function: Optional[Callable] = None):
    """PER-BATCH binary F1 on class-1 (the SST-2 convention), from
    on-device TP/FP/FN counts; 0 when the batch has no positives (the
    empty-harmonic-mean convention sklearn uses).

    The engine reports the mean of this over batches — which equals
    sklearn's DATASET F1 only within a batch, not across batches (F1 is
    not linear in its counts: a batch-less corpus F1 needs the summed
    counts).  The trainer's scalar accumulator keeps the reference's
    running-average semantics (ref: src/trainer.py:193-194, 200-203), so
    this metric is a training-progress signal; for an exact corpus F1,
    run ``Trainer.predict()`` and score the collected predictions."""
    predictions = get_predictions(outputs, pred_function)
    pred_pos = (predictions == 1).astype(jnp.float32)
    true_pos = (targets == 1).astype(jnp.float32)
    tp = jnp.sum(pred_pos * true_pos)
    denom = jnp.sum(pred_pos) + jnp.sum(true_pos)  # 2TP + FP + FN
    return jnp.where(denom > 0, 2.0 * tp / denom, 0.0)


def _mean_token_nll(outputs, targets, pred_function: Optional[Callable] = None):
    """Per-batch mean token negative log-likelihood ([B, S, V] logits vs
    [B, S] next-token ids) — perplexity's accumulator."""
    logprobs = jax.nn.log_softmax(outputs.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)
    return jnp.mean(nll)


# A metric may carry a ``finalize`` attribute: the engine accumulates
# the fn's scalar across batches, averages, then applies the finalizer
# to the EPOCH value — which is what makes nonlinear report metrics
# honest: 'perplexity' accumulates mean NLL (linear, so the epoch mean
# is the corpus mean over equal-size batches) and exponentiates once at
# the end, exp(mean nll) — NOT the Jensen-inflated mean of per-batch
# exp(nll) a naive per-batch metric would produce.  Attribute (not a
# tuple in the table) so METRICS values stay plain callables for any
# direct-dispatch consumer.
_mean_token_nll.finalize = jnp.exp

METRICS = {
    "accuracy": accuracy,
    "mcrmse": mcrmse,
    "top5_accuracy": top5_accuracy,
    "f1": f1,
    "perplexity": _mean_token_nll,
}


def get_metric(
    name: Optional[str], pred_function: Optional[Callable] = None
) -> Optional[Callable]:
    """Bind a metric by name; ``None`` disables metrics (ref: main.py:70-71).

    When the underlying metric is nonlinear it carries a ``finalize``
    attribute, propagated onto the returned callable, that the engine
    applies to the averaged epoch value; linear metrics carry NO such
    attribute — consumers probe with ``getattr(fn, "finalize", None)``
    (as the trainer does).  See the METRICS table."""
    if name is None:
        return None
    try:
        fn = METRICS[name]
    except KeyError:
        raise ValueError(
            f"Unknown metric {name!r}; expected one of {sorted(METRICS)}"
        ) from None

    def bound(outputs, targets):
        return fn(outputs, targets, pred_function)

    finalize = getattr(fn, "finalize", None)
    if finalize is not None:
        bound.finalize = finalize
    return bound
