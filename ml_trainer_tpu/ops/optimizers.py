"""Optimizer factory: string name -> optax gradient transformation.

TPU-native re-design of the reference's ``Trainer._get_optimizer``
(ref: src/trainer.py:123-138).  The reference instantiates torch optimizers
bound to module parameters; here each optimizer is a pure optax
``GradientTransformation`` applied inside the compiled train step, so the
update math runs fused on-device and the same transformation works under any
mesh sharding.

Semantics match torch's optimizers for the reference's five names:

* ``sgd``     — momentum + *coupled* weight decay (torch adds ``wd * p`` to
                the gradient before the momentum buffer).
* ``adam`` / ``adagrad`` / ``adamax`` — coupled L2 weight decay, as torch.
* ``adamw``   — decoupled weight decay (optax.adamw == torch.AdamW).

``learning_rate`` may be a float or an optax schedule (step -> lr); the
schedule path is how the per-batch cosine restarts of the reference
(ref: src/trainer.py:189-190) are expressed without host-side stepping.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import optax

ScalarOrSchedule = Union[float, Callable]


def _with_coupled_decay(tx: optax.GradientTransformation, weight_decay: float,
                        mask=None):
    """Torch-style coupled L2: grad += wd * param, applied before the inner tx."""
    if weight_decay:
        return optax.chain(
            optax.add_decayed_weights(weight_decay, mask=mask), tx
        )
    return tx


def _sgd(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
         mask=None):
    return _with_coupled_decay(
        optax.sgd(lr, momentum=momentum if momentum else None),
        weight_decay, mask,
    )


def _adam(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
          mask=None):
    return _with_coupled_decay(optax.adam(lr), weight_decay, mask)


def _adagrad(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
             mask=None):
    return _with_coupled_decay(optax.adagrad(lr), weight_decay, mask)


def _adamax(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
            mask=None):
    return _with_coupled_decay(optax.adamax(lr), weight_decay, mask)


def _adamw(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
           mask=None):
    return optax.adamw(lr, weight_decay=weight_decay, mask=mask)


def _lamb(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
          mask=None):
    # LAMB (layerwise-adaptive Adam): the large-batch TPU recipe used for
    # BERT pretraining — decoupled decay like adamw, per-layer trust ratio.
    return optax.lamb(lr, weight_decay=weight_decay, mask=mask)


def _lion(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
          mask=None):
    # Lion: sign-momentum optimizer; one moment buffer instead of Adam's
    # two — 2x less optimizer HBM for the big-model configs.
    return optax.lion(lr, weight_decay=weight_decay, mask=mask)


def _adafactor(lr: ScalarOrSchedule, momentum: float, weight_decay: float,
               mask=None):
    # Adafactor: the memory-frugal LM-pretraining standard — second
    # moments stored FACTORED (row + column vectors instead of a full
    # matrix), so optimizer HBM for a [m, n] kernel drops from O(m*n) to
    # O(m + n).  Momentum off (the memory-saving configuration) and
    # update clipping per the paper; coupled decay keeps the factory's
    # torch-style convention for the non-decoupled names.
    return _with_coupled_decay(
        optax.adafactor(lr, multiply_by_parameter_scale=False,
                        clipping_threshold=1.0),
        weight_decay, mask,
    )


# The first five names are the reference set (ref: src/trainer.py:123-138);
# lamb/lion/adafactor extend it for the north-star large-batch/large-model
# configs.
OPTIMIZERS = {
    "sgd": _sgd,
    "adam": _adam,
    "adagrad": _adagrad,
    "adamax": _adamax,
    "adamw": _adamw,
    "lamb": _lamb,
    "lion": _lion,
    "adafactor": _adafactor,
}


def decay_mask_matrices_only(params):
    """The standard transformer decay mask: weight decay applies to
    matrices (ndim >= 2 — the matmul kernels and embeddings) and skips
    biases / LayerNorm scales (1-D), whose decay is known to hurt.  Pass
    as ``decay_mask`` to ``get_optimizer`` (Trainer:
    ``decay_exclude_bias_norm=True``)."""
    return jax.tree.map(lambda p: getattr(p, "ndim", 0) >= 2, params)


def _decay_all(params):
    """The default mask (torch semantics: decay everything).  A mask is
    ALWAYS passed so the optax ``masked`` wrapper — and therefore the
    opt_state pytree structure and checkpoints — is identical whichever
    mask is in force; toggling ``decay_exclude_bias_norm`` across a
    resume must not change the state tree (same invariant the trainer
    keeps for grad clipping)."""
    return jax.tree.map(lambda _: True, params)


def get_optimizer(
    name: str,
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    decay_mask=None,
) -> optax.GradientTransformation:
    """Map an optimizer name to an optax transformation.

    The reference's five names (ref: src/trainer.py:123-138) plus
    ``lamb``/``lion`` for the north-star configs.  Unknown names raise
    ``ValueError`` (the reference silently returns ``None`` — a latent bug we
    do not replicate).  ``decay_mask``: optional params -> bool-pytree
    callable restricting which leaves weight decay touches (torch
    semantics — decay everything — is the default, matching the
    reference; see ``decay_mask_matrices_only``).
    """
    try:
        factory = OPTIMIZERS[name]
    except KeyError:
        raise ValueError(
            f"Unknown optimizer {name!r}; expected one of {sorted(OPTIMIZERS)}"
        ) from None
    return factory(
        learning_rate, momentum, weight_decay, decay_mask or _decay_all
    )
