"""Criterion factory: string name -> pure loss function (outputs, targets) -> scalar.

Same name set as the reference's ``Trainer._get_criterion``
(ref: src/trainer.py:140-150): ``cross_entropy``, ``neg-loss``, ``l1``,
``l2``, ``custom``.  Each is a pure jnp function, fused by XLA into the
train step (the reference's losses are torch modules moved to the device,
ref: src/trainer.py:102-103).

Deliberate fixes over the reference (documented divergences):

* ``neg-loss`` and ``l2`` return *callable losses*; the reference returns
  the classes ``torch.nn.NLLLoss`` / ``torch.nn.MSELoss`` uninstantiated
  (ref: src/trainer.py:144, 148) which crashes when called with two tensors.
* unknown names raise ``ValueError`` instead of silently returning ``None``.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import optax

from ml_trainer_tpu.utils.functions import custom_loss_function


def cross_entropy(outputs, targets):
    """Softmax cross entropy with integer labels, mean over batch — the
    semantics of ``torch.nn.CrossEntropyLoss()`` (ref: src/trainer.py:142)."""
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(outputs, targets)
    )


def nll_loss(outputs, targets):
    """Negative log-likelihood over log-probability inputs
    (``torch.nn.NLLLoss`` semantics; ref: src/trainer.py:143-144, fixed to be
    an instance).  Pairs with the ``logsoftmax`` prediction function."""
    picked = jnp.take_along_axis(outputs, targets[..., None], axis=-1)
    return -jnp.mean(picked)


def l1_loss(outputs, targets):
    """Mean absolute error (``torch.nn.L1Loss``, ref: src/trainer.py:145-146)."""
    return jnp.mean(jnp.abs(outputs - targets))


def l2_loss(outputs, targets):
    """Mean squared error (``torch.nn.MSELoss``, ref: src/trainer.py:147-148,
    fixed to be an instance)."""
    return jnp.mean(jnp.square(outputs - targets))


CRITERIA = {
    "cross_entropy": cross_entropy,
    "neg-loss": nll_loss,
    "l1": l1_loss,
    "l2": l2_loss,
    "custom": custom_loss_function,
}


def get_criterion(name: str) -> Callable:
    try:
        return CRITERIA[name]
    except KeyError:
        raise ValueError(
            f"Unknown criterion {name!r}; expected one of {sorted(CRITERIA)}"
        ) from None
