"""Criterion factory: string name -> pure loss function (outputs, targets) -> scalar.

Same name set as the reference's ``Trainer._get_criterion``
(ref: src/trainer.py:140-150): ``cross_entropy``, ``neg-loss``, ``l1``,
``l2``, ``custom``.  Each is a pure jnp function, fused by XLA into the
train step (the reference's losses are torch modules moved to the device,
ref: src/trainer.py:102-103).

Deliberate fixes over the reference (documented divergences):

* ``neg-loss`` and ``l2`` return *callable losses*; the reference returns
  the classes ``torch.nn.NLLLoss`` / ``torch.nn.MSELoss`` uninstantiated
  (ref: src/trainer.py:144, 148) which crashes when called with two tensors.
* unknown names raise ``ValueError`` instead of silently returning ``None``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ml_trainer_tpu.utils.functions import custom_loss_function


def cross_entropy(outputs, targets):
    """Softmax cross entropy with integer labels, mean over batch — the
    semantics of ``torch.nn.CrossEntropyLoss()`` (ref: src/trainer.py:142)."""
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(outputs, targets)
    )


def cross_entropy_smoothed(label_smoothing: float) -> Callable:
    """Cross entropy with smoothed targets — the ViT/ResNet recipe
    ingredient (``torch.nn.CrossEntropyLoss(label_smoothing=...)``
    semantics, including the degenerate-but-legal 1.0 = pure uniform
    targets): each one-hot target mixes with the uniform distribution
    at weight ``label_smoothing``."""
    if not 0.0 <= label_smoothing <= 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1], got {label_smoothing}"
        )
    if label_smoothing == 0.0:
        return cross_entropy

    def smoothed(outputs, targets):
        n = outputs.shape[-1]
        onehot = jax.nn.one_hot(targets, n, dtype=outputs.dtype)
        soft = optax.smooth_labels(onehot, label_smoothing)
        return jnp.mean(optax.softmax_cross_entropy(outputs, soft))

    return smoothed


def nll_loss(outputs, targets):
    """Negative log-likelihood over log-probability inputs
    (``torch.nn.NLLLoss`` semantics; ref: src/trainer.py:143-144, fixed to be
    an instance).  Pairs with the ``logsoftmax`` prediction function."""
    picked = jnp.take_along_axis(outputs, targets[..., None], axis=-1)
    return -jnp.mean(picked)


def l1_loss(outputs, targets):
    """Mean absolute error (``torch.nn.L1Loss``, ref: src/trainer.py:145-146)."""
    return jnp.mean(jnp.abs(outputs - targets))


def l2_loss(outputs, targets):
    """Mean squared error (``torch.nn.MSELoss``, ref: src/trainer.py:147-148,
    fixed to be an instance)."""
    return jnp.mean(jnp.square(outputs - targets))


def chunked_lm_cross_entropy(hidden, embedding, targets, chunk_size=128):
    """LM cross entropy WITHOUT materializing the [B, S, V] logits tensor.

    The logits of a tied-head language model are the memory hot spot of
    training: GPT-2 124M at [8, 1024, 50257] is ~0.8 GB of bf16 logits
    (plus the f32 softmax intermediates the backward keeps).  This
    computes ``mean(xent(h @ E^T, targets))`` by a ``lax.scan`` over
    sequence chunks with ``jax.checkpoint`` around the body, so both
    forward and backward only ever hold one [B, chunk, V] logits block —
    peak memory drops by S/chunk at the cost of recomputing each block's
    matmul once in the backward (the flash-attention trade applied to
    the LM head).

    hidden: [B, S, D] (any float dtype; logits accumulate in f32),
    embedding: [V, D] (the tied token-embedding matrix), targets: [B, S]
    int labels.  S must divide by ``chunk_size`` (pick a divisor — the
    caller knows its sequence length statically).
    """
    b, s, d = hidden.shape
    if s % chunk_size:
        raise ValueError(
            f"sequence length {s} not divisible by chunk_size {chunk_size}"
        )
    n = s // chunk_size
    h_chunks = hidden.reshape(b, n, chunk_size, d).swapaxes(0, 1)
    t_chunks = targets.reshape(b, n, chunk_size).swapaxes(0, 1)

    @jax.checkpoint
    def body(total, chunk):
        h_c, t_c = chunk
        logits = jnp.einsum(
            "bcd,vd->bcv", h_c.astype(jnp.float32),
            embedding.astype(jnp.float32),
        )
        return (
            total
            + optax.softmax_cross_entropy_with_integer_labels(
                logits, t_c
            ).sum(),
            None,
        )

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (h_chunks, t_chunks))
    return total / (b * s)


CRITERIA = {
    "cross_entropy": cross_entropy,
    "neg-loss": nll_loss,
    "l1": l1_loss,
    "l2": l2_loss,
    "custom": custom_loss_function,
}


def get_criterion(name: str, label_smoothing: float = 0.0) -> Callable:
    """Map a criterion name to its loss fn; ``label_smoothing`` (the
    ViT/ResNet recipe) composes only with ``cross_entropy`` — criterion
    construction and its validation live HERE, not in the trainer."""
    try:
        criterion = CRITERIA[name]
    except KeyError:
        raise ValueError(
            f"Unknown criterion {name!r}; expected one of {sorted(CRITERIA)}"
        ) from None
    if label_smoothing:
        if name != "cross_entropy":
            raise ValueError(
                "label_smoothing only applies to criterion='cross_entropy', "
                f"got {name!r}"
            )
        return cross_entropy_smoothed(label_smoothing)
    return criterion
