"""Prediction-function factory.

TPU-native form of ``Trainer._get_prediction_function`` /
``Trainer._get_predictions`` (ref: src/trainer.py:115-121, 168-172):
``softmax`` / ``logsoftmax`` / None applied before an argmax over the last
axis.  Pure jnp functions so the whole predict path stays on-device — the
reference's argmax feeds a sklearn metric on host (ref: src/trainer.py:166),
a per-batch device sync we deliberately avoid.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.nn
import jax.numpy as jnp


def get_prediction_function(name: Optional[str]) -> Optional[Callable]:
    """'softmax' | 'logsoftmax' | None (ref: src/trainer.py:115-121)."""
    if name == "logsoftmax":
        return jax.nn.log_softmax
    if name == "softmax":
        return jax.nn.softmax
    return None


def get_predictions(outputs, pred_function: Optional[Callable]):
    """Argmax of (optionally transformed) outputs (ref: src/trainer.py:168-172)."""
    if pred_function is not None:
        return jnp.argmax(pred_function(outputs), axis=-1)
    return jnp.argmax(outputs, axis=-1)
