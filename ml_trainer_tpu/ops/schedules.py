"""LR schedules: string name -> step-indexed schedule function.

The reference eagerly constructs three torch schedulers and steps them from
the host loop (ref: src/trainer.py:105-112, 189-190, 198-199).  On TPU the
schedule must live *inside* the compiled step — host-side ``.step()`` calls
would force a sync per batch — so each schedule here is a pure function of
the global step count, traced once by XLA.

Name set and hyperparameters match the reference registry
(ref: src/trainer.py:105-112):

* ``CosineAnnealingWarmRestarts`` — T_0 = 5 epochs, eta_min = 1e-7, stepped
  per-batch with fractional epoch ``epoch - 1 + i/len(loader)``
  (ref: src/trainer.py:189-190).  Expressed as lr(step) with
  ``epoch_frac = step / steps_per_epoch``.
* ``StepLR`` — step_size = 2 epochs, gamma = 0.1 (torch default), stepped at
  the end of each training epoch (ref: src/trainer.py:198-199), i.e. during
  1-indexed epoch e the factor is ``gamma ** ((e - 1) // 2)``.
* ``ReduceLROnPlateau`` — 'min' mode, min_lr = 1e-7
  (ref: src/trainer.py:108).  The reference constructs it but **never steps
  it** (dead code); we fix that deliberately: the base schedule is constant
  and ``PlateauController`` runs on the host at epoch boundaries (the only
  place a metric-conditional LR is known), feeding a scalar ``lr_scale``
  into the compiled step.  Documented divergence.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax.numpy as jnp

SCHEDULERS = (
    "CosineAnnealingWarmRestarts", "ReduceLROnPlateau", "StepLR",
    "WarmupCosine", "WarmupLinear",
)


def make_lr_schedule(
    scheduler_type: Optional[str],
    base_lr: float,
    steps_per_epoch: int,
    total_steps: Optional[int] = None,
) -> Callable:
    """Build lr(step).  ``scheduler_type=None`` -> constant (ref default).

    The first three names mirror the reference registry; ``WarmupCosine``
    (linear warmup -> cosine decay to ~0, the ViT/GPT pretraining
    standard) and ``WarmupLinear`` (linear warmup -> linear decay, the
    BERT fine-tuning standard) extend it for the north-star recipes.
    Both warm up over 5% of ``total_steps`` (min 1 step) and decay over
    the remainder; without ``total_steps`` they assume a 100-epoch
    horizon with a 1-epoch warmup.
    """
    steps_per_epoch = max(int(steps_per_epoch), 1)

    if scheduler_type is None:
        return lambda step: jnp.asarray(base_lr, dtype=jnp.float32)

    if scheduler_type in ("WarmupCosine", "WarmupLinear"):
        import optax

        if total_steps is None:
            warmup = steps_per_epoch
            horizon = 100 * steps_per_epoch
        else:
            horizon = max(int(total_steps), 2)
            warmup = max(horizon // 20, 1)
        if scheduler_type == "WarmupCosine":
            return optax.warmup_cosine_decay_schedule(
                0.0, base_lr, warmup, horizon, end_value=0.0
            )
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, base_lr, warmup),
                optax.linear_schedule(
                    base_lr, 0.0, max(horizon - warmup, 1)
                ),
            ],
            boundaries=[warmup],
        )

    if scheduler_type == "CosineAnnealingWarmRestarts":
        t0_epochs = 5.0
        eta_min = 1e-7

        def cosine_restarts(step):
            epoch_frac = step / steps_per_epoch
            t_cur = jnp.mod(epoch_frac, t0_epochs) / t0_epochs
            return eta_min + (base_lr - eta_min) * 0.5 * (
                1.0 + jnp.cos(jnp.pi * t_cur)
            )

        return cosine_restarts

    if scheduler_type == "StepLR":
        step_size_epochs = 2
        gamma = 0.1

        def step_lr(step):
            epoch = step // steps_per_epoch  # 0-indexed epoch in progress
            return base_lr * gamma ** (epoch // step_size_epochs)

        return step_lr

    if scheduler_type == "ReduceLROnPlateau":
        # Constant base; runtime reduction comes from PlateauController via
        # the lr_scale argument of the train step.
        return lambda step: jnp.asarray(base_lr, dtype=jnp.float32)

    raise ValueError(
        f"Unknown scheduler {scheduler_type!r}; expected one of {SCHEDULERS}"
    )


class PlateauController:
    """Host-side ReduceLROnPlateau (torch defaults: factor 0.1, patience 10,
    rel threshold 1e-4, 'min' mode, min_lr 1e-7 per ref: src/trainer.py:108).

    ``update(value)`` is called once per epoch with the validation loss and
    returns the multiplicative ``lr_scale`` to feed into the compiled step —
    the epoch boundary is the only host-sync point, so this costs nothing.
    """

    def __init__(
        self,
        base_lr: float,
        factor: float = 0.1,
        patience: int = 10,
        threshold: float = 1e-4,
        min_lr: float = 1e-7,
    ):
        self.base_lr = base_lr
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.min_lr = min_lr
        self.best = math.inf
        self.num_bad_epochs = 0
        self.scale = 1.0

    def update(self, value: float) -> float:
        if value < self.best * (1.0 - self.threshold):
            self.best = value
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            new_lr = max(self.base_lr * self.scale * self.factor, self.min_lr)
            self.scale = new_lr / self.base_lr
            self.num_bad_epochs = 0
        return self.scale
