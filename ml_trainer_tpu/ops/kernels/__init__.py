"""Pallas TPU kernels for the serving + training hot paths.

Every kernel in this package ships as a PAIR under one dispatcher:

* a **lax reference** — ordinary jnp/lax ops, bitwise-identical to the
  pre-kernel XLA path it replaces (that identity is pinned by
  ``tests/test_kernels.py``), shipped as the CPU/GPU runtime path;
* a **Pallas TPU kernel** — the fused program that removes the HBM
  round-trips the XLA path pays, pinned bit-for-bit against the lax
  reference in interpret mode on CPU (the repo's kernel discipline,
  same as ``ops/attention.py``'s flash kernel).

``implementation='auto'`` resolves to the Pallas kernel on TPU and the
lax reference everywhere else, so enabling a kernel knob never changes
bytes on a non-TPU backend — byte-identity gates stay exact while the
TPU path earns the fusion win.

Catalog (see docs/kernels.md for block layouts and measured numbers):

* ``paged_attention`` — paged-attention decode: fuses the per-step
  page-table gather (``pool[table]`` materializing [B, H, L, D] twice)
  into the attention kernel; pages stream HBM->VMEM via a
  scalar-prefetched table index_map.
* ``unscale_sqsum`` / ``fused_adam_update`` — the ``dp_update='sharded'``
  optimizer tail: one pass over the 1/N dim-0 shard for unscale +
  global-norm contribution, and one for clip + Adam moments + schedule
  step + param write (optax opt_state structure preserved bit-for-bit).
* ``int8_matmul`` / ``quantize_per_channel`` — int8 weight-quantized
  matmul with per-output-channel scales, backing the opt-in quantized
  decode path (``Server(quant_int8=True)``).
"""

from ml_trainer_tpu.ops.kernels.paged_attention import (  # noqa: F401
    paged_attention,
    paged_attention_reference,
)
from ml_trainer_tpu.ops.kernels.fused_adam import (  # noqa: F401
    adam_scalars,
    fused_adam_update,
    unscale_sqsum,
)
from ml_trainer_tpu.ops.kernels.int8_matmul import (  # noqa: F401
    int8_matmul,
    quantize_per_channel,
    quantize_tree,
)

__all__ = [
    "paged_attention",
    "paged_attention_reference",
    "adam_scalars",
    "fused_adam_update",
    "unscale_sqsum",
    "int8_matmul",
    "quantize_per_channel",
    "quantize_tree",
]
