"""Fused unscale + clip + Adam update for the ``dp_update='sharded'``
path.

The sharded-dp optimizer tail (``trainer.py::_make_sharded_train_step``)
runs as optax's many small ops over each 1/N dim-0 shard: unscale,
per-leaf squared-norm for the global clip, the clip multiply, two
moment updates, bias corrections, the schedule step, and the param
write — each a separate HBM round-trip over the same bytes.  This
module fuses them into two passes (the global-norm psum between them is
an unavoidable barrier):

* ``unscale_sqsum`` — ``g / denom`` and the f32 sum-of-squares of the
  result in one read of ``g``;
* ``fused_adam_update`` — clip multiply + Adam moment/bias-correction/
  step + schedule scale + ``lr_scale`` + param write in one read of
  (g, p, mu, nu) and one write of (p', mu', nu', u).

Bit-identity contract (pinned by tests/test_kernels.py): the lax
references replicate optax 0.2.3's exact op chain —
``scale_by_adam`` (``mu' = (1-b1)·g + b1·mu``, ``nu' = (1-b2)·g² +
b2·nu``, ``safe_int32_increment`` counts, ``m / (1 - b**count)`` bias
corrections cast to the moment dtype), ``scale_by_schedule``
(``jnp.array(-lr(count), u.dtype) * u``), the trainer's ``u * lr_scale``
and ``optax.apply_updates`` — so the fused path's fp32 trajectory is
bitwise the optax path's, and the rebuilt ``opt_state``
(``EmptyState``, (``ScaleByAdamState``, ``ScaleByScheduleState``))
keeps checkpoints and the NaN-guard's where-select structure unchanged.

The Pallas kernels are elementwise over lane-padded 2-D views (no
cross-element reductions except ``unscale_sqsum``'s whole-leaf sum,
which runs single-block to preserve the reference reduction order —
leaves past the VMEM budget fall back to the reference).  Output
shapes/dtypes come from ``jax.eval_shape`` of the reference, so the
kernels inherit its promotion semantics exactly.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

# optax.adam defaults — the only config the fused path accepts (the
# trainer gates on optimizer='adam' with weight_decay=0).
B1, B2, EPS, EPS_ROOT = 0.9, 0.999, 1e-8, 0.0

# unscale_sqsum runs the whole leaf as one Pallas block (reduction-order
# preservation); leaves above this many elements use the reference.
_SQSUM_VMEM_ELEMS = 2 * 1024 * 1024

_LANES = 128


def adam_scalars(count, sched_count, lr_schedule):
    """The per-step scalars every leaf shares: incremented counts, the
    two bias corrections, and the schedule step size — each the exact
    optax expression (``safe_int32_increment``, ``1 - b**count_inc``,
    ``-lr(count)`` evaluated at the PRE-increment schedule count)."""
    count_inc = optax.safe_int32_increment(count)
    bc1 = 1 - B1 ** count_inc
    bc2 = 1 - B2 ** count_inc
    if callable(lr_schedule):
        step_size = -1 * lr_schedule(sched_count)
    else:
        step_size = jnp.asarray(-1.0 * lr_schedule, jnp.float32)
    sched_inc = optax.safe_int32_increment(sched_count)
    return count_inc, bc1, bc2, step_size, sched_inc


def _flat2(t):
    """Lane-padded 2-D view for the elementwise kernels (bit-safe: no
    cross-element arithmetic touches the padding)."""
    f = t.reshape(-1)
    pad = (-f.shape[0]) % _LANES
    if pad:
        f = jnp.pad(f, (0, pad))
    return f.reshape(-1, _LANES)


def _unflat(f, shape):
    n = 1
    for s in shape:
        n *= int(s)
    return f.reshape(-1)[:n].reshape(shape)


# --------------------------------------------------------- unscale+sqsum
def _unscale_reference(g, denom, compute_sq):
    g_u = g / denom
    if not compute_sq:
        return g_u, None
    return g_u, jnp.sum(jnp.square(g_u.astype(jnp.float32)))


def _unscale_pallas(g, denom, compute_sq, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    static_denom = isinstance(denom, (int, float))
    ref_out = jax.eval_shape(
        lambda gg, dd: _unscale_reference(gg, dd, True), g,
        denom if static_denom else jnp.asarray(denom),
    )
    out_dtype = ref_out[0].dtype

    def kernel(*refs):
        if static_denom:
            g_ref, o_ref, sq_ref = refs
            g_u = g_ref[...] / denom
        else:
            d_ref, g_ref, o_ref, sq_ref = refs
            g_u = g_ref[...] / d_ref[0, 0]
        o_ref[...] = g_u.astype(o_ref.dtype)
        if compute_sq:
            sq_ref[0, 0] = jnp.sum(jnp.square(g_u.astype(jnp.float32)))
        else:
            sq_ref[0, 0] = 0.0

    # NO lane padding or reshape here: a multi-axis full reduce
    # associates per-axis, so the sqsum only matches the reference if
    # the kernel sees g's original shape (1-d leaves ride as (1, N),
    # which reduces in the same order).
    flat = g if g.ndim >= 2 else g.reshape(1, -1)
    in_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)]
    args = [flat]
    if not static_denom:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(0, jnp.asarray(denom, jnp.float32).reshape(1, 1))
    out, sq = pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(flat.shape, out_dtype),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    out = out.reshape(g.shape)
    return (out, sq[0, 0]) if compute_sq else (out, None)


def unscale_sqsum(
    g: jax.Array,
    denom,
    *,
    compute_sq: bool = True,
    implementation: str = "auto",
    interpret: bool = False,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """``(g / denom, sum(square(f32(g / denom))))`` in one pass.

    ``denom`` is a python float (no loss scaling) or a traced f32 scalar
    (``denom * scale``); the division matches the unfused path bit-for-
    bit either way.  ``compute_sq=False`` skips the norm contribution
    (no clip, no telemetry).

    Caveat (documented VMEM bound): the Pallas path keeps the whole leaf
    in one block so the sum reduction runs in the reference's order;
    ``implementation='auto'`` falls back to the reference for leaves
    past the budget."""
    if implementation == "auto":
        implementation = (
            "pallas"
            if jax.default_backend() == "tpu"
            and g.size <= _SQSUM_VMEM_ELEMS
            else "reference"
        )
    if implementation == "reference":
        return _unscale_reference(g, denom, compute_sq)
    if implementation != "pallas":
        raise ValueError(
            f"Unknown unscale_sqsum implementation {implementation!r}"
        )
    return _unscale_pallas(g, denom, compute_sq, interpret)


# ------------------------------------------------- clip + Adam + write
def _adam_reference(g, p, mu, nu, bc1, bc2, step_size, lr_scale, factor):
    if factor is not None:
        g = g * factor
    mu_n = (1 - B1) * g + B1 * mu
    nu_n = (1 - B2) * (g ** 2) + B2 * nu
    mu_hat = mu_n / bc1.astype(mu_n.dtype)
    nu_hat = nu_n / bc2.astype(nu_n.dtype)
    u = mu_hat / (jnp.sqrt(nu_hat + EPS_ROOT) + EPS)
    u = jnp.array(step_size, u.dtype) * u
    u = u * lr_scale
    p_n = jnp.asarray(p + u).astype(jnp.asarray(p).dtype)
    return p_n, mu_n, nu_n, u


def _adam_kernel(s_ref, g_ref, p_ref, mu_ref, nu_ref,
                 p_out, mu_out, nu_out, u_out, *, has_factor):
    # Scalars arrive as strong-f32 SMEM reads, matching the traced
    # scalars of the unfused path (promotion semantics identical).
    g = g_ref[...]
    if has_factor:
        g = g * s_ref[0, 4]
    mu_n = (1 - B1) * g + B1 * mu_ref[...]
    nu_n = (1 - B2) * (g ** 2) + B2 * nu_ref[...]
    mu_hat = mu_n / s_ref[0, 0].astype(mu_n.dtype)
    nu_hat = nu_n / s_ref[0, 1].astype(nu_n.dtype)
    u = mu_hat / (jnp.sqrt(nu_hat + EPS_ROOT) + EPS)
    u = s_ref[0, 2].astype(u.dtype) * u
    u = u * s_ref[0, 3]
    p_out[...] = (p_ref[...] + u).astype(p_out.dtype)
    mu_out[...] = mu_n.astype(mu_out.dtype)
    nu_out[...] = nu_n.astype(nu_out.dtype)
    u_out[...] = u.astype(u_out.dtype)


def _adam_pallas(g, p, mu, nu, bc1, bc2, step_size, lr_scale, factor,
                 interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    has_factor = factor is not None
    ref_out = jax.eval_shape(
        lambda *a: _adam_reference(*a),
        g, p, mu, nu, jnp.asarray(bc1, jnp.float32),
        jnp.asarray(bc2, jnp.float32),
        jnp.asarray(step_size, jnp.float32),
        jnp.asarray(lr_scale, jnp.float32),
        jnp.asarray(factor, jnp.float32) if has_factor else None,
    )
    scalars = jnp.stack([
        jnp.asarray(bc1, jnp.float32),
        jnp.asarray(bc2, jnp.float32),
        jnp.asarray(step_size, jnp.float32),
        jnp.asarray(lr_scale, jnp.float32),
        jnp.asarray(factor if has_factor else 1.0, jnp.float32),
    ]).reshape(1, 5)
    flats = [_flat2(t) for t in (g, p, mu, nu)]
    outs = pl.pallas_call(
        functools.partial(_adam_kernel, has_factor=has_factor),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_shape=[
            jax.ShapeDtypeStruct(flats[1].shape, ref_out[0].dtype),
            jax.ShapeDtypeStruct(flats[2].shape, ref_out[1].dtype),
            jax.ShapeDtypeStruct(flats[3].shape, ref_out[2].dtype),
            jax.ShapeDtypeStruct(flats[1].shape, ref_out[3].dtype),
        ],
        interpret=interpret,
    )(scalars, *flats)
    return tuple(
        _unflat(o, r.shape) for o, r in zip(outs, ref_out)
    )


def fused_adam_update(
    g: jax.Array,
    p: jax.Array,
    mu: jax.Array,
    nu: jax.Array,
    *,
    bc1,
    bc2,
    step_size,
    lr_scale,
    factor=None,
    implementation: str = "auto",
    interpret: bool = False,
):
    """One fused pass of the post-psum optimizer tail for one leaf
    shard: returns ``(p', mu', nu', u)`` where ``u`` is the applied
    update (the telemetry update-norm input).  ``factor=None`` means no
    clip was configured — the multiply is omitted entirely, matching the
    unfused path's conditional."""
    if implementation == "auto":
        implementation = (
            "pallas" if jax.default_backend() == "tpu" else "reference"
        )
    if implementation == "reference":
        return _adam_reference(
            g, p, mu, nu, bc1, bc2, step_size, lr_scale, factor
        )
    if implementation != "pallas":
        raise ValueError(
            f"Unknown fused_adam_update implementation {implementation!r}"
        )
    return _adam_pallas(
        g, p, mu, nu, bc1, bc2, step_size, lr_scale, factor, interpret
    )
