"""Int8 weight-quantized matmul with per-output-channel scales.

Backs the opt-in quantized decode path (``Server(quant_int8=True)``):
the four LoRA-target Dense projections (qkv / proj / fc_in / fc_out)
store int8 weights + f32 per-column scales in a ``"quant"`` variable
collection built host-side by :func:`quantize_tree` — param paths and
checkpoints are untouched, and prefill stays fp32 (only the decode
model clone flips the knob).  Embeddings and the tied LM head stay
fp32 by design: they dominate the quality budget and are one matmul
each per step.

This is NOT a bit-parity path against fp32 — quantization changes the
math by construction.  The discipline here is:

* the lax reference and the Pallas kernel ARE pinned bit-for-bit
  against each other in interpret mode (tests/test_kernels.py): both
  upcast x and the int8 weights to f32, run the full-K dot, and apply
  the column scales to the f32 product;
* fp32 quality is gated end-to-end instead (argmax agreement >= 99.5%
  and bounded logit error on the bench leg / smoke).

Symmetric per-output-channel quantization: ``scale[n] =
max(|w[:, n]|) / 127`` (all-zero columns get scale 1 so dequant is
exact), ``w_q = clip(round(w / scale), -127, 127)``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# Dense targets the quantized decode path covers — the same four the
# LoRA adapters attach to (models/layers.py::LORA_TARGETS; kept literal
# here to avoid an ops -> models import cycle).
QUANT_TARGETS = ("qkv", "proj", "fc_in", "fc_out")


def quantize_per_channel(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[K, N] float weights -> (int8 [K, N], f32 scales [N])."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=0)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    w_q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return w_q, scale


def _int8_reference(x, w_q, scale):
    x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    y = jax.lax.dot_general(
        x2, w_q.astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    return y.reshape(*x.shape[:-1], w_q.shape[-1]).astype(x.dtype)


def _int8_kernel(x_ref, w_ref, s_ref, o_ref):
    y = jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * s_ref[0]
    o_ref[...] = y.astype(o_ref.dtype)


def _int8_pallas(x, w_q, scale, block_n, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x2 = x.reshape(-1, x.shape[-1])
    m, k = x2.shape
    n = w_q.shape[-1]
    bn = min(block_n, n)
    if n % bn:
        bn = n  # ragged N: one block (decode N is 128-aligned in practice)
    y = pl.pallas_call(
        _int8_kernel,
        grid=(n // bn,),
        in_specs=[
            # Full-K blocks: the contraction is never split, so each
            # output element reduces in the reference's order.
            pl.BlockSpec((m, k), lambda j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, bn), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda j: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(x2, w_q, scale.reshape(1, -1))
    return y.reshape(*x.shape[:-1], n)


def int8_matmul(
    x: jax.Array,
    w_q: jax.Array,
    scale: jax.Array,
    *,
    implementation: str = "auto",
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``x @ dequant(w_q, scale)`` computed as f32-dot(x, int8->f32 w)
    scaled per output column; returns x.dtype.  x: [..., K],
    w_q: [K, N] int8, scale: [N] f32."""
    if w_q.dtype != jnp.int8:
        raise ValueError(f"w_q must be int8, got {w_q.dtype}")
    if implementation == "auto":
        implementation = (
            "pallas" if jax.default_backend() == "tpu" else "reference"
        )
    if implementation == "reference":
        return _int8_reference(x, w_q, scale)
    if implementation != "pallas":
        raise ValueError(
            f"Unknown int8_matmul implementation {implementation!r}"
        )
    return _int8_pallas(x, w_q, scale, block_n, interpret)


def quantize_tree(params, targets=QUANT_TARGETS):
    """Build the ``"quant"`` collection from a params tree.

    Walks the (nested-dict) params pytree; every sub-dict named in
    ``targets`` that carries a Dense ``kernel`` contributes
    ``<name>_w`` / ``<name>_scale`` / ``<name>_b`` entries at its
    PARENT's scope — exactly where the owning module's
    ``self.variable("quant", ...)`` reads them — so the builder needs no
    knowledge of block naming.  Returns ``{}`` when nothing matched (the
    caller should refuse rather than serve un-quantized silently)."""
    if not isinstance(params, dict):
        raise TypeError(
            f"quantize_tree expects a nested-dict params tree, got "
            f"{type(params).__name__}"
        )

    def walk(d):
        out = {}
        for name, sub in d.items():
            if not isinstance(sub, dict):
                continue
            if name in targets and "kernel" in sub:
                w_q, scale = quantize_per_channel(sub["kernel"])
                out[f"{name}_w"] = w_q
                out[f"{name}_scale"] = scale
                out[f"{name}_b"] = jnp.asarray(
                    sub.get("bias", jnp.zeros((w_q.shape[-1],))),
                    jnp.float32,
                )
            else:
                inner = walk(sub)
                if inner:
                    out[name] = inner
        return out

    return walk(params)
