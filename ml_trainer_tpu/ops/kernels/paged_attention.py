"""Paged-attention decode kernel: the page-table gather fused into
attention.

The serving engine's paged decode step
(``models/layers.py::_paged_decode_step``) attends each row's single
query against K/V scattered across a shared page pool.  The XLA path
must first materialize the gather — ``pool[table]`` then a transpose
back to logical order — which copies the FULL [B, H, L, D] cached K and
V through HBM every decode step; at decode batch sizes that copy is the
dominant byte-mover (the attention matmuls then read the same bytes
again).  This kernel removes it: the per-row page list rides in as a
scalar-prefetch operand and the grid's page axis pulls each page
HBM->VMEM directly via its BlockSpec ``index_map`` — the gather IS the
pipeline's fetch, never a separate HBM-resident array.

Parity discipline (pinned by tests/test_kernels.py):

* ``paged_attention_reference`` is bitwise-identical to the pre-kernel
  engine path (gather + ``dot_product_attention`` under the validity
  mask) — it IS that path, minus the engine's mask plumbing.
* the Pallas kernel in ``interpret=True`` mode is bitwise-identical to
  the reference: scores/softmax/output are computed once per (b, h) on
  the full [1, L] row with the exact op chain of
  ``dot_product_attention`` (f32 dots, mask bias ADDED, same softmax),
  and the scratch holds the very pages the reference gathers — trash
  and partially-filled pages included — so masked positions see the
  same bytes on both sides.

Layout contract (owned by serving/kv_pool.py + models/layers.py):
``k_pool``/``v_pool`` are [N, H, page, D] with page 0 the trash page;
``table`` is [B, P] int32; ``lengths`` is [B] int32 with
``lengths[b] >= 1`` (position 0 is always valid — the engine passes
``cache_index + 1``).  Rows past ``lengths`` are masked, so trash-page
rows (all-zero tables) and partial last pages cost nothing but the
masked lanes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ml_trainer_tpu.ops.attention import _mask_bias, dot_product_attention


def paged_attention_reference(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    table: jax.Array,
    lengths: jax.Array,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """The gather + masked dot-product-attention path, verbatim.

    q: [B, H, D] (one query token per row); pools: [N, H, page, D];
    table: [B, P]; lengths: [B].  Returns [B, H, D] in q.dtype.
    """
    b, h, d = q.shape
    _, _, ps, _ = k_pool.shape
    P = table.shape[-1]
    L = P * ps

    def gather(pool):  # [B, P, H, page, D] -> [B, H, L, D]
        return pool[table].transpose(0, 2, 1, 3, 4).reshape(b, h, L, d)

    valid = (jnp.arange(L)[None, :] < lengths[:, None])[:, None, None, :]
    out = dot_product_attention(
        q[:, :, None, :], gather(k_pool), gather(v_pool),
        mask=valid, scale=scale,
    )
    return out[:, :, 0, :]


def _paged_kernel(table_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  k_scr, v_scr, *, pages, page_size, scale):
    """Grid (B, H, P); page p of row b's table lands in k_ref/v_ref (the
    BlockSpec index_map did the gather).  Pages accumulate into VMEM
    scratch; the last page triggers the one [1, L] attention row."""
    from jax.experimental import pallas as pl

    b_i = pl.program_id(0)
    p_i = pl.program_id(2)
    L = pages * page_size
    k_scr[pl.ds(p_i * page_size, page_size), :] = k_ref[0, 0]
    v_scr[pl.ds(p_i * page_size, page_size), :] = v_ref[0, 0]

    @pl.when(p_i == pages - 1)
    def _finish():
        # The exact dot_product_attention op chain on the [1, L] row:
        # f32 score dot, python-float scale, ADDED mask bias, softmax,
        # weights cast to v.dtype then f32 for the output dot.
        qv = q_ref[0].astype(jnp.float32)                      # [1, D]
        scores = jax.lax.dot_general(
            qv, k_scr[...].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                              # [1, L]
        pos = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)
        scores = scores + _mask_bias(pos < lens_ref[b_i], scores.dtype)
        weights = jax.nn.softmax(scores, axis=-1)
        weights = weights.astype(v_scr.dtype).astype(jnp.float32)
        out = jax.lax.dot_general(
            weights, v_scr[...].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                      # [1, D]
        o_ref[0] = out.astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pool, v_pool, table, lengths, scale,
                            interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    _, _, ps, _ = k_pool.shape
    P = table.shape[-1]
    L = P * ps

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, P),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda bi, hi, pi, tbl, lens: (bi, hi, 0)),
            # The fused gather: page p of row b streams in from whatever
            # pool page the prefetched table names for it.
            pl.BlockSpec(
                (1, 1, ps, d),
                lambda bi, hi, pi, tbl, lens: (tbl[bi, pi], hi, 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, ps, d),
                lambda bi, hi, pi, tbl, lens: (tbl[bi, pi], hi, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, d), lambda bi, hi, pi, tbl, lens: (bi, hi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((L, d), k_pool.dtype),
            pltpu.VMEM((L, d), v_pool.dtype),
        ],
    )
    kernel = functools.partial(
        _paged_kernel, pages=P, page_size=ps, scale=scale
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(table, lengths, q, k_pool, v_pool)


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    table: jax.Array,
    lengths: jax.Array,
    *,
    scale: Optional[float] = None,
    implementation: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """Fused paged-attention decode step.  See module docstring.

    implementation: 'auto' (pallas on TPU, reference elsewhere),
    'pallas', or 'reference'.  ``interpret=True`` runs the Pallas kernel
    in interpret mode (the CPU parity harness).
    """
    if q.ndim != 3:
        raise ValueError(f"q must be [B, H, D], got {q.shape}")
    if k_pool.shape != v_pool.shape:
        raise ValueError(
            f"k_pool/v_pool shapes differ: {k_pool.shape} vs {v_pool.shape}"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if implementation == "auto":
        implementation = (
            "pallas" if jax.default_backend() == "tpu" else "reference"
        )
    if implementation in ("reference", "xla"):
        return paged_attention_reference(
            q, k_pool, v_pool, table, lengths, scale=scale
        )
    if implementation != "pallas":
        raise ValueError(
            f"Unknown paged_attention implementation {implementation!r}; "
            "expected 'auto', 'pallas', or 'reference'"
        )
    return _paged_attention_pallas(
        q, k_pool, v_pool, table, jnp.asarray(lengths, jnp.int32),
        scale, interpret,
    )
