"""Speculative decoding: draft K tokens cheaply, verify in ONE forward.

The vanilla decode loop (generate.py, serving/engine.py) is latency-bound
by one full-model forward per token regardless of batch occupancy.
Speculative decoding breaks that bound: a cheap *drafter* proposes K
tokens, and the target model scores all K (+1 bonus position) in a
single forward over a length-``K+1`` token window — the windowed
cache-append in ``models/layers.py`` writes the window's K/V at each
row's own dynamic offset, so shapes stay static at fixed K and nothing
recompiles as requests come and go.

Two draft sources:

* :class:`NgramDrafter` — model-free prompt/history lookup ("prompt
  lookup decoding"): match the last n-gram of the generated-so-far
  sequence against everything before it and propose the continuation of
  the most recent match.  Free to compute, and devastatingly effective
  on repetitive text (code, cycles, extraction) where greedy decoding
  revisits its own n-grams.
* :class:`DraftModelDrafter` — any registry causal LM with the SAME
  vocabulary (e.g. a tiny GPT-2 config) decoding greedily with its own
  KV cache; K sequential small-model steps buy one large-model forward.

Acceptance:

* **greedy** (``temperature == 0``): longest-accepted-prefix — draft
  token ``d_j`` is accepted iff it equals the target's argmax after
  ``d_1..d_{j-1}``; the first mismatch position takes the target's
  argmax instead.  The committed stream is therefore *provably
  byte-identical* to vanilla greedy ``generate()`` for ANY drafts (the
  drafts only decide how many tokens commit per step, never which).
* **sampled** (``temperature > 0``): standard speculative rejection
  sampling against the drafter's point distribution: accept ``d_j``
  with probability ``p(d_j)`` (target softmax at temperature), else
  resample from the renormalized residual ``p`` with ``d_j`` masked.
  The output DISTRIBUTION matches vanilla sampling; the realized draw
  stream differs from ``generate()``'s per-token ``fold_in`` sequence.

Cache discipline: every compiled program here *sets* the per-row
``cache_index``/``pos_index`` leaves from an explicit host-owned
``pos`` vector on entry, so "rolling back" rejected draft positions is
free — stale K/V beyond ``pos`` is simply never attended (the per-row
mask is ``arange(L) <= pos + j``) and the next window overwrites it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.generate import _COMPILED, _cache_shapes, _empty_cache


def _set_index(cache, pos):
    """Broadcast the host-owned ``pos`` [B] vector into every per-row
    index leaf (``cache_index``/``pos_index``, the only 1-D leaves).
    K/V leaves pass through untouched — contiguous ``[B, H, L, D]``
    blocks and PAGED pool/page-table leaves alike (4-D pools and the 2-D
    ``page_table``, serving/kv_pool.py), which is what lets one verify
    program serve both cache layouts: in paged mode the verify window's
    reads and writes resolve through the page table at the same ``pos``
    offsets."""
    return jax.tree.map(
        lambda l: pos.astype(l.dtype) if l.ndim == 1 else l, cache
    )


def _widen_cache(cache, b):
    """Scalar index leaves -> per-row [B] vectors (the slot-indexed
    layout of models/layers.py; content irrelevant — ``_set_index``
    overwrites it on every program entry)."""
    return jax.tree.map(
        lambda l: jnp.zeros((b,), l.dtype) if l.ndim == 0 else l, cache
    )


class NgramDrafter:
    """Model-free prompt/history n-gram lookup drafter.

    ``draft_one(history)`` matches the last ``n``-gram (falling back to
    shorter grams down to ``min_n``) of ``history`` against every
    earlier position; the continuation after the MOST RECENT match is
    proposed.  No match -> repeat the last token (the best guess for
    period-1 cycles, and free to be wrong: a rejected draft costs
    nothing but its slot in the verify window)."""

    def __init__(self, k: int = 4, n: int = 3, min_n: int = 1):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if not 1 <= min_n <= n:
            raise ValueError(f"need 1 <= min_n <= n, got n={n} min_n={min_n}")
        self.k = k
        self.n = n
        self.min_n = min_n

    def draft_one(self, history: np.ndarray) -> np.ndarray:
        hist = np.asarray(history).reshape(-1)
        m = hist.shape[0]
        for n in range(min(self.n, m - 1), self.min_n - 1, -1):
            pat = hist[m - n:]
            # Windows over hist[:-1]: every start has a continuation.
            wins = np.lib.stride_tricks.sliding_window_view(hist[:-1], n)
            hits = np.flatnonzero((wins == pat).all(axis=1))
            if hits.size:
                i = int(hits[-1])  # most recent match
                cont = hist[i + n: i + n + self.k]
                if cont.size < self.k:
                    cont = np.concatenate([
                        cont,
                        np.full(self.k - cont.size, cont[-1], hist.dtype),
                    ])
                return cont.astype(np.int32)
        return np.full(self.k, hist[-1], np.int32)

    def draft(self, histories) -> np.ndarray:
        """[B, k] drafts for a batch of 1-D histories."""
        return np.stack([self.draft_one(h) for h in histories])


class DraftModelDrafter:
    """A small registry causal LM as the draft source.

    The draft model must share the target's vocabulary (checked against
    the target at use time) and expose the same ``decode``/``max_len``
    contract.  It decodes greedily with its own KV cache through one
    compiled K+1-step scan — the extra (K+1)-th step consumes the last
    draft so the draft cache stays position-aligned with the target's
    commit state for EVERY acceptance count 0..K."""

    def __init__(self, model, variables: dict):
        self.model = model
        self.params = (
            variables["params"] if "params" in variables else variables
        )

    def check_compatible(self, target_model) -> None:
        if self.model.vocab_size != target_model.vocab_size:
            raise ValueError(
                "draft model vocab_size "
                f"({self.model.vocab_size}) must equal the target's "
                f"({target_model.vocab_size}) — speculative acceptance "
                "compares token ids across the two models"
            )


# ------------------------------------------------------- compiled programs


def build_spec_prefill(model, b: int, greedy: bool):
    """Batch prefill for the speculative loop: one causal forward over
    the whole [B, P] prompt, cache widened to per-row index leaves, and
    the first new token sampled exactly as ``generate()`` samples its
    t=0 token (argmax when greedy, ``categorical(fold_in(rng, 0))``
    otherwise)."""
    dm = model.clone(decode=True)
    cache_shapes = _cache_shapes(dm, b, jnp.int32)

    @jax.jit
    def run(params, prompt_ids, temperature, rng):
        cache = _empty_cache(cache_shapes)
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, prompt_ids,
            train=False, mutable=["cache"],
        )
        cache = _widen_cache(mut["cache"], b)
        last = logits[:, -1]
        if greedy:
            tok = jnp.argmax(last, axis=-1)
        else:
            tok = jax.random.categorical(
                jax.random.fold_in(rng, 0), last / temperature, axis=-1
            )
        return cache, tok[:, None].astype(jnp.int32)

    return run


def build_verify(model, b: int, s: int):
    """The compiled verify step at window length ``s`` (= K+1).

    One program serves greedy AND sampled rows (per-row ``temps``
    select), so the serving engine's ragged traffic shares a single
    executable at fixed K.  Inputs: the [B, s] window (last committed
    token + K drafts), the host-owned consumed-token count ``pos`` [B],
    per-row write caps, temps/rngs/steps for sampling.  Returns the
    updated cache, per-row accepted-draft counts, the next committed
    token, and the new ``pos`` (``min(pos + accepted + 1, caps)`` —
    the caller mirrors the same formula on host)."""
    dm = model.clone(decode=True)

    # The cache is rebound on every call — donate it so the verify step
    # updates K/V in place instead of allocating a second copy.
    @functools.partial(jax.jit, donate_argnums=(1,))
    def verify(params, cache, window, pos, caps, temps, rngs, steps):
        cache = _set_index(cache, pos)
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, window,
            train=False, mutable=["cache"],
        )
        logits = logits.astype(jnp.float32)              # [B, s, V]
        greedy_next = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        match_g = greedy_next[:, :-1] == window[:, 1:]   # [B, K]
        # Rejection sampling vs the drafter's point distribution:
        # accept d_j with prob p_{j-1}(d_j).
        safe_t = jnp.where(temps > 0, temps, 1.0)[:, None, None]
        probs = jax.nn.softmax(logits / safe_t, axis=-1)
        p_draft = jnp.take_along_axis(
            probs[:, :-1, :], window[:, 1:, None].astype(jnp.int32), axis=-1
        )[..., 0]                                        # [B, K]
        keys = jax.vmap(jax.random.fold_in)(rngs, steps)
        u = jax.vmap(
            lambda k_: jax.random.uniform(jax.random.fold_in(k_, 1), (s - 1,))
        )(keys)
        match = jnp.where((temps > 0)[:, None], u < p_draft, match_g)
        # Longest accepted prefix: #leading True.
        accepted = jnp.sum(
            jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
        )                                                # [B] in [0, K]
        # Next token at position `accepted`: greedy rows take argmax;
        # sampled rows draw from the residual (rejected draft masked,
        # renormalized) — or the untouched bonus distribution when all
        # K drafts were accepted.
        p_next = jnp.take_along_axis(
            probs, accepted[:, None, None], axis=1
        )[:, 0, :]                                       # [B, V]
        rejected = jnp.take_along_axis(
            window, jnp.minimum(accepted + 1, s - 1)[:, None], axis=1
        )[:, 0]
        mask_rej = accepted < (s - 1)
        p_resid = jnp.where(
            jax.nn.one_hot(rejected, probs.shape[-1], dtype=bool)
            & mask_rej[:, None],
            0.0, p_next,
        )
        p_resid = p_resid / jnp.maximum(
            p_resid.sum(axis=-1, keepdims=True), 1e-20
        )
        samp_next = jax.vmap(
            lambda k_, pr: jax.random.categorical(
                jax.random.fold_in(k_, 2), jnp.log(jnp.maximum(pr, 1e-20))
            )
        )(keys, p_resid)
        greedy_pick = jnp.take_along_axis(
            greedy_next, accepted[:, None], axis=1
        )[:, 0]
        nxt = jnp.where(temps > 0, samp_next, greedy_pick).astype(jnp.int32)
        new_pos = jnp.minimum(pos + accepted + 1, caps)
        return _set_index(mut["cache"], new_pos), accepted, nxt[:, None], new_pos

    return verify


def build_draft_scan(draft_model, b: int, k: int):
    """K+1 greedy single-token draft-model steps as one compiled scan.

    Step j consumes the previous token and emits draft ``d_j``; the
    final (K+1)-th step consumes ``d_K`` purely to land its K/V in the
    draft cache, so the draft cache covers the full verify window and
    the host's single ``pos`` vector stays valid for both models at any
    acceptance count."""
    dm = draft_model.clone(decode=True)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(params, cache, tok, pos):
        cache = _set_index(cache, pos)

        def step(carry, _):
            cache, tok = carry
            logits, mut = dm.apply(
                {"params": params, "cache": cache}, tok,
                train=False, mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (mut["cache"], nxt[:, None]), nxt

        (cache, _), drafts = jax.lax.scan(
            step, (cache, tok), None, length=k + 1
        )
        return cache, jnp.moveaxis(drafts[:k], 0, 1)     # [B, k]

    return run


def _program(key, build):
    run = _COMPILED.get(key)
    if run is None:
        run = build()
        _COMPILED[key] = run
    return run


# ------------------------------------------------------------- batch API


def speculative_generate(
    model,
    variables: dict,
    prompt_ids,
    max_new_tokens: int,
    draft_k: int = 4,
    drafter="ngram",
    draft_variables: Optional[dict] = None,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    ngram: int = 3,
    return_stats: bool = False,
):
    """``generate()`` with speculative decoding — same output contract.

    ``drafter`` is ``"ngram"`` (prompt/history lookup), an
    :class:`NgramDrafter`, a :class:`DraftModelDrafter`, or a registry
    model instance (then ``draft_variables`` supplies its params).
    Greedy output (``temperature == 0``) is byte-identical to
    ``generate()``; sampled output follows the same distribution via
    rejection sampling but draws a different stream.  ``top_k``/
    ``top_p`` are not supported here — use vanilla ``generate()``.

    Returns [B, P + max_new_tokens] ids, plus a stats dict
    (``accept_hist``, ``acceptance_rate``, ``verify_steps``) when
    ``return_stats``.
    """
    params = variables["params"] if "params" in variables else variables
    prompt_ids = jnp.asarray(prompt_ids)
    b, p = prompt_ids.shape
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if draft_k < 1:
        raise ValueError(f"draft_k must be >= 1, got {draft_k}")
    if p + max_new_tokens + draft_k > model.max_len:
        raise ValueError(
            f"prompt ({p}) + new tokens ({max_new_tokens}) + draft_k "
            f"({draft_k}) exceeds max_len ({model.max_len}); the verify "
            "window needs draft_k tokens of cache slack — reduce draft_k "
            "or max_new_tokens"
        )
    if eos_token_id is not None and not 0 <= eos_token_id < model.vocab_size:
        raise ValueError(
            f"eos_token_id must be in [0, vocab_size={model.vocab_size}), "
            f"got {eos_token_id}"
        )
    if max_new_tokens == 0:
        return (prompt_ids, _empty_stats(draft_k)) if return_stats \
            else prompt_ids
    greedy = temperature == 0.0
    if rng is None:
        rng = jax.random.PRNGKey(0)

    # -- drafter normalization ------------------------------------------
    draft_model = None
    if drafter == "ngram":
        drafter = NgramDrafter(k=draft_k, n=ngram)
    elif isinstance(drafter, NgramDrafter):
        if drafter.k != draft_k:
            raise ValueError(
                f"drafter.k ({drafter.k}) != draft_k ({draft_k})"
            )
    elif isinstance(drafter, DraftModelDrafter):
        draft_model = drafter
    elif hasattr(drafter, "max_len"):  # a registry model instance
        if draft_variables is None:
            raise ValueError(
                "a draft model needs draft_variables (its params)"
            )
        draft_model = DraftModelDrafter(drafter, draft_variables)
    else:
        raise ValueError(
            f"drafter must be 'ngram', an NgramDrafter, a "
            f"DraftModelDrafter or a registry model, got {drafter!r}"
        )
    if draft_model is not None:
        draft_model.check_compatible(model)
        if p + max_new_tokens + draft_k > draft_model.model.max_len:
            raise ValueError(
                "the draft model's max_len "
                f"({draft_model.model.max_len}) is too short for this "
                f"request (needs {p + max_new_tokens + draft_k})"
            )

    s = draft_k + 1
    prefill = _program(
        ("spec_prefill", model, b, p, greedy),
        lambda: build_spec_prefill(model, b, greedy),
    )
    verify = _program(
        ("spec_verify", model, b, s), lambda: build_verify(model, b, s)
    )
    temp = jnp.asarray(temperature, jnp.float32)
    cache, tok = prefill(params, prompt_ids, temp, rng)

    if draft_model is not None:
        d_prefill = _program(
            ("spec_prefill", draft_model.model, b, p, True),
            lambda: build_spec_prefill(draft_model.model, b, True),
        )
        d_scan = _program(
            ("spec_draft", draft_model.model, b, draft_k),
            lambda: build_draft_scan(draft_model.model, b, draft_k),
        )
        d_cache, _ = d_prefill(draft_model.params, prompt_ids, temp, rng)

    # -- host state ------------------------------------------------------
    out = np.zeros((b, max_new_tokens), np.int32)
    counts = np.zeros(b, np.int64)          # committed tokens per row
    done = np.zeros(b, bool)                # rows that emitted EOS
    pos = np.full(b, p, np.int32)           # consumed tokens per row
    caps = np.full(b, p + max_new_tokens - 1, np.int32)
    temps = np.full(b, temperature, np.float32)
    # Per-row keys (fold the row index): rows must draw INDEPENDENT
    # accept/resample noise — a shared key would correlate acceptance
    # across the batch.
    rngs = np.stack([
        np.asarray(jax.random.fold_in(rng, i), np.uint32).reshape(-1)[:2]
        for i in range(b)
    ])
    steps = np.zeros(b, np.int32)
    prompt_np = np.asarray(prompt_ids)
    hist = np.zeros((b, p + max_new_tokens), np.int32)
    hist[:, :p] = prompt_np
    accept_hist = np.zeros(s, np.int64)
    verify_steps = 0

    tok_h = np.asarray(tok)[:, 0]
    _commit_token(tok_h, out, counts, done, hist, p,
                  eos_token_id, pad_token_id, max_new_tokens)

    while counts.min() < max_new_tokens:
        if draft_model is not None:
            d_cache, drafts_dev = d_scan(
                draft_model.params, d_cache, tok, jnp.asarray(pos)
            )
            drafts = np.asarray(drafts_dev)
        else:
            drafts = drafter.draft(
                [hist[i, : p + int(counts[i])] for i in range(b)]
            )
        window = jnp.concatenate(
            [tok, jnp.asarray(drafts, jnp.int32)], axis=1
        )
        cache, accepted, tok, _ = verify(
            params, cache, window, jnp.asarray(pos), jnp.asarray(caps),
            jnp.asarray(temps), jnp.asarray(rngs), jnp.asarray(steps),
        )
        acc = np.asarray(accepted)
        tok_h = np.asarray(tok)[:, 0]
        verify_steps += 1
        live = counts < max_new_tokens
        np.add.at(accept_hist, acc[live], 1)
        for j in range(draft_k + 1):
            # Commit accepted drafts then the verify token, row-wise.
            sel = acc >= j + 1
            row_tok = np.where(sel, drafts[:, j] if j < draft_k else 0,
                               tok_h)
            mask = (acc >= j) & live
            _commit_token(row_tok, out, counts, done, hist, p,
                          eos_token_id, pad_token_id, max_new_tokens,
                          rows=mask)
        pos = np.minimum(pos + acc + 1, caps).astype(np.int32)
        steps = steps + acc.astype(np.int32) + 1

    full = np.concatenate([prompt_np, out], axis=1)
    result = jnp.asarray(full, prompt_ids.dtype)
    if return_stats:
        # One histogram entry per (step, live row): drafted counts K per
        # entry, not K per step — the batch dimension drafts too.
        drafted = int(accept_hist.sum()) * draft_k
        accepted_total = int(
            (accept_hist * np.arange(s)).sum()
        )
        return result, {
            "draft_k": draft_k,
            "verify_steps": verify_steps,
            "accept_hist": accept_hist.tolist(),
            "drafted_tokens": drafted,
            "accepted_tokens": accepted_total,
            "acceptance_rate": (
                accepted_total / drafted if drafted else 0.0
            ),
            "tokens_per_step": (
                float((accept_hist * (np.arange(s) + 1)).sum()
                      / accept_hist.sum())
                if accept_hist.sum() else 0.0
            ),
        }
    return result


def _empty_stats(draft_k: int) -> dict:
    return {
        "draft_k": draft_k, "verify_steps": 0,
        "accept_hist": [0] * (draft_k + 1), "drafted_tokens": 0,
        "accepted_tokens": 0, "acceptance_rate": 0.0,
        "tokens_per_step": 0.0,
    }


def _commit_token(row_tok, out, counts, done, hist, p,
                  eos_token_id, pad_token_id, max_new_tokens, rows=None):
    """Append one token per selected row to the output/history buffers,
    honoring EOS -> pad tails (generate()'s masking semantics) and the
    per-row budget."""
    b = out.shape[0]
    for i in range(b):
        if rows is not None and not rows[i]:
            continue
        c = int(counts[i])
        if c >= max_new_tokens:
            continue
        t = int(row_tok[i])
        if done[i] and eos_token_id is not None:
            t = pad_token_id
        out[i, c] = t
        hist[i, p + c] = t
        counts[i] = c + 1
        if eos_token_id is not None and not done[i] and t == eos_token_id:
            done[i] = True
