"""Sharding rules: PartitionSpecs for batches and parameter trees.

Replaces the reference's DDP placement logic (replicate everything, shard
only the batch via DistributedSampler; ref: src/trainer.py:60-64, 97-101)
with explicit ``NamedSharding`` annotations:

* ``batch_sharding`` — split the leading (batch) dim over the data-like
  mesh axes; this single annotation is what turns the compiled step into a
  data-parallel program (XLA inserts the gradient psum automatically).
* ``shard_params`` — apply regex-keyed PartitionSpec rules to a parameter
  pytree; this is how tensor/fsdp sharding is declared for the model zoo
  (no analog in the reference, which has no model parallelism — SURVEY.md
  §2C).
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def _data_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def batch_sharding(mesh: Mesh, shard_sequence: Optional[bool] = None) -> NamedSharding:
    """Shard the leading dim over data(+fsdp) axes.

    When the mesh has a live ``sequence`` axis (sequence/context
    parallelism), dim 1 — the token dim of [B, S] batches — shards over it
    by default; consumers that place lower-rank arrays (labels, scalars)
    truncate the spec to the array rank (see data.loader.prefetch_to_device).
    """
    axes = _data_axes(mesh)
    if shard_sequence is None:
        shard_sequence = "sequence" in mesh.axis_names
    if shard_sequence and "sequence" in mesh.axis_names:
        return NamedSharding(mesh, P(axes if axes else None, "sequence"))
    return NamedSharding(mesh, P(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fit_sharding_to_rank(sharding: NamedSharding, ndim: int) -> NamedSharding:
    """Truncate a batch sharding's spec to an array's rank — a [B, S]-shaped
    sequence-parallel spec applies to token batches while the 1-D labels in
    the same batch tuple keep only the batch-dim entry."""
    if len(sharding.spec) > ndim:
        return NamedSharding(sharding.mesh, P(*sharding.spec[:ndim]))
    return sharding


def path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def logical_to_shardings(
    tree,
    mesh: Mesh,
    rules: Optional[Rules] = None,
):
    """Pytree of NamedShardings: first regex rule matching each param path
    wins; unmatched params are replicated (the DDP default)."""
    compiled: List[Tuple[re.Pattern, P]] = [
        (re.compile(pat), spec) for pat, spec in (rules or [])
    ]

    def resolve(path, leaf):
        name = path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                # Drop axes absent from this mesh so one rule set serves
                # dp-only and dp×tp meshes alike.
                cleaned = P(
                    *(
                        a
                        if (
                            a is None
                            or (isinstance(a, str) and a in mesh.axis_names)
                            or (
                                isinstance(a, tuple)
                                and all(x in mesh.axis_names for x in a)
                            )
                        )
                        else None
                        for a in spec
                    )
                )
                return NamedSharding(mesh, cleaned)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(resolve, tree)


def place_tree(tree, shardings):
    """Place a pytree onto per-leaf shardings, multi-host-safely.

    Single-process this is plain per-leaf ``device_put``.  Multi-process,
    ``device_put`` of a host/uncommitted value onto a NON-addressable
    sharding makes jax verify the value is identical on every process —
    one ``broadcast_one_to_all`` collective PER LEAF.  Besides being
    O(leaves) DCN round-trips at construction time, the resulting storm
    of back-to-back differently-sized collectives aborts the gloo CPU
    backend of the 2-process test cluster (ops race on the TCP pairs:
    ``op.preamble.length <= op.nbytes`` in gloo's pair.cc — each check
    only syncs device 0's buffer, leaving the other local devices'
    collectives in flight when the next one is issued).  A single jitted
    identity with ``out_shardings`` places the WHOLE tree in one SPMD
    program with zero cross-host traffic — each process contributes its
    local values, the normal SPMD contract (the cross-host equality
    guarantee comes from seeded determinism, audited by
    ``parallel/desync.py``, not from per-leaf broadcasts)."""
    if jax.process_count() == 1:
        return jax.tree.map(jax.device_put, tree, shardings)
    return jax.jit(lambda t: t, out_shardings=shardings)(tree)


def shard_params(params, mesh: Mesh, rules: Optional[Rules] = None):
    """Materialize a parameter tree onto the mesh under the given rules."""
    shardings = logical_to_shardings(params, mesh, rules)
    return place_tree(params, shardings)


def shard_opt_state(opt_state, mesh: Mesh, axis: str = "data"):
    """ZeRO-1-style optimizer-state sharding as a pure placement decision.

    Re-places every optimizer-state leaf that is currently fully replicated
    and whose leading dim divides the ``axis`` size so dim 0 is partitioned
    over that mesh axis; XLA's SPMD partitioner then turns the weight
    update into compute on 1/N of the moments per device with the
    collectives it implies (the technique of "Automatic Cross-Replica
    Sharding of Weight Update in Data-Parallel Training" — here it is just
    a sharding annotation, not a rewrite).  Values are bit-identical to the
    replicated layout; only memory/placement changes.  Leaves already
    sharded by TP/FSDP rules (momenta inherit their param's sharding) are
    left alone.
    """
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return opt_state
    n = mesh.shape[axis]

    def place(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return leaf
        current = getattr(leaf, "sharding", None)
        if isinstance(current, NamedSharding) and any(
            s is not None for s in current.spec
        ):
            return leaf  # already model-sharded; don't fight the rules
        if leaf.shape[0] % n:
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, P(axis)))

    return jax.tree.map(place, opt_state)


def zero1_opt_shardings(opt_shapes, mesh: Mesh, axis: str = "data"):
    """Target shardings for a pure-DP ZeRO-1 optimizer state, decided from
    ``jax.eval_shape(tx.init, params)`` so init can be jitted with
    ``out_shardings`` and the moments are born partitioned (never
    materialized replicated).  Shape-based rule: leading dim divisible by
    the axis size → P(axis); everything else replicated.  Only valid when
    params are replicated (no TP/FSDP rules) — rule-sharded params need the
    materialized-placement path instead."""
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), opt_shapes)
    n = mesh.shape[axis]

    def target(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) and shape[0] % n == 0 and shape[0] > 0:
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    return jax.tree.map(target, opt_shapes)
