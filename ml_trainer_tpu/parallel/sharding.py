"""Sharding rules: PartitionSpecs for batches and parameter trees.

Replaces the reference's DDP placement logic (replicate everything, shard
only the batch via DistributedSampler; ref: src/trainer.py:60-64, 97-101)
with explicit ``NamedSharding`` annotations:

* ``batch_sharding`` — split the leading (batch) dim over the data-like
  mesh axes; this single annotation is what turns the compiled step into a
  data-parallel program (XLA inserts the gradient psum automatically).
* ``shard_params`` — apply regex-keyed PartitionSpec rules to a parameter
  pytree; this is how tensor/fsdp sharding is declared for the model zoo
  (no analog in the reference, which has no model parallelism — SURVEY.md
  §2C).
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def _data_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def batch_sharding(mesh: Mesh, shard_sequence: Optional[bool] = None) -> NamedSharding:
    """Shard the leading dim over data(+fsdp) axes.

    When the mesh has a live ``sequence`` axis (sequence/context
    parallelism), dim 1 — the token dim of [B, S] batches — shards over it
    by default; consumers that place lower-rank arrays (labels, scalars)
    truncate the spec to the array rank (see data.loader.prefetch_to_device).
    """
    axes = _data_axes(mesh)
    if shard_sequence is None:
        shard_sequence = "sequence" in mesh.axis_names
    if shard_sequence and "sequence" in mesh.axis_names:
        return NamedSharding(mesh, P(axes if axes else None, "sequence"))
    return NamedSharding(mesh, P(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fit_sharding_to_rank(sharding: NamedSharding, ndim: int) -> NamedSharding:
    """Truncate a batch sharding's spec to an array's rank — a [B, S]-shaped
    sequence-parallel spec applies to token batches while the 1-D labels in
    the same batch tuple keep only the batch-dim entry."""
    if len(sharding.spec) > ndim:
        return NamedSharding(sharding.mesh, P(*sharding.spec[:ndim]))
    return sharding


def path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def logical_to_shardings(
    tree,
    mesh: Mesh,
    rules: Optional[Rules] = None,
):
    """Pytree of NamedShardings: first regex rule matching each param path
    wins; unmatched params are replicated (the DDP default)."""
    compiled: List[Tuple[re.Pattern, P]] = [
        (re.compile(pat), spec) for pat, spec in (rules or [])
    ]

    def resolve(path, leaf):
        name = path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                # Drop axes absent from this mesh so one rule set serves
                # dp-only and dp×tp meshes alike.
                cleaned = P(
                    *(
                        a
                        if (
                            a is None
                            or (isinstance(a, str) and a in mesh.axis_names)
                            or (
                                isinstance(a, tuple)
                                and all(x in mesh.axis_names for x in a)
                            )
                        )
                        else None
                        for a in spec
                    )
                )
                return NamedSharding(mesh, cleaned)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(resolve, tree)


def shard_params(params, mesh: Mesh, rules: Optional[Rules] = None):
    """Materialize a parameter tree onto the mesh under the given rules."""
    shardings = logical_to_shardings(params, mesh, rules)
    return jax.tree.map(jax.device_put, params, shardings)
