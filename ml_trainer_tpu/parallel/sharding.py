"""Sharding rules: PartitionSpecs for batches and parameter trees.

Replaces the reference's DDP placement logic (replicate everything, shard
only the batch via DistributedSampler; ref: src/trainer.py:60-64, 97-101)
with explicit ``NamedSharding`` annotations:

* ``batch_sharding`` — split the leading (batch) dim over the data-like
  mesh axes; this single annotation is what turns the compiled step into a
  data-parallel program (XLA inserts the gradient psum automatically).
* ``shard_params`` — apply regex-keyed PartitionSpec rules to a parameter
  pytree; this is how tensor/fsdp sharding is declared for the model zoo
  (no analog in the reference, which has no model parallelism — SURVEY.md
  §2C).
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def _data_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def batch_sharding(mesh: Mesh, ndim: int = None) -> NamedSharding:
    """Shard the leading dim over data(+fsdp) axes; replicate the rest."""
    axes = _data_axes(mesh)
    spec = P(axes if axes else None)
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def logical_to_shardings(
    tree,
    mesh: Mesh,
    rules: Optional[Rules] = None,
):
    """Pytree of NamedShardings: first regex rule matching each param path
    wins; unmatched params are replicated (the DDP default)."""
    compiled: List[Tuple[re.Pattern, P]] = [
        (re.compile(pat), spec) for pat, spec in (rules or [])
    ]

    def resolve(path, leaf):
        name = path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                # Drop axes absent from this mesh so one rule set serves
                # dp-only and dp×tp meshes alike.
                cleaned = P(
                    *(
                        a
                        if (
                            a is None
                            or (isinstance(a, str) and a in mesh.axis_names)
                            or (
                                isinstance(a, tuple)
                                and all(x in mesh.axis_names for x in a)
                            )
                        )
                        else None
                        for a in spec
                    )
                )
                return NamedSharding(mesh, cleaned)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(resolve, tree)


def shard_params(params, mesh: Mesh, rules: Optional[Rules] = None):
    """Materialize a parameter tree onto the mesh under the given rules."""
    shardings = logical_to_shardings(params, mesh, rules)
    return jax.tree.map(jax.device_put, params, shardings)
