"""Sharding rules: PartitionSpecs for batches and parameter trees.

Replaces the reference's DDP placement logic (replicate everything, shard
only the batch via DistributedSampler; ref: src/trainer.py:60-64, 97-101)
with explicit ``NamedSharding`` annotations:

* ``batch_sharding`` — split the leading (batch) dim over the data-like
  mesh axes; this single annotation is what turns the compiled step into a
  data-parallel program (XLA inserts the gradient psum automatically).
* ``shard_params`` — apply regex-keyed PartitionSpec rules to a parameter
  pytree; this is how tensor/fsdp sharding is declared for the model zoo
  (no analog in the reference, which has no model parallelism — SURVEY.md
  §2C).
"""

from __future__ import annotations

import re
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def _data_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def batch_sharding(mesh: Mesh, shard_sequence: Optional[bool] = None) -> NamedSharding:
    """Shard the leading dim over data(+fsdp) axes.

    When the mesh has a live ``sequence`` axis (sequence/context
    parallelism), dim 1 — the token dim of [B, S] batches — shards over it
    by default; consumers that place lower-rank arrays (labels, scalars)
    truncate the spec to the array rank (see data.loader.prefetch_to_device).
    """
    axes = _data_axes(mesh)
    if shard_sequence is None:
        shard_sequence = "sequence" in mesh.axis_names
    if shard_sequence and "sequence" in mesh.axis_names:
        return NamedSharding(mesh, P(axes if axes else None, "sequence"))
    return NamedSharding(mesh, P(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fit_sharding_to_rank(sharding: NamedSharding, ndim: int) -> NamedSharding:
    """Truncate a batch sharding's spec to an array's rank — a [B, S]-shaped
    sequence-parallel spec applies to token batches while the 1-D labels in
    the same batch tuple keep only the batch-dim entry."""
    if len(sharding.spec) > ndim:
        return NamedSharding(sharding.mesh, P(*sharding.spec[:ndim]))
    return sharding


def path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def logical_to_shardings(
    tree,
    mesh: Mesh,
    rules: Optional[Rules] = None,
):
    """Pytree of NamedShardings: first regex rule matching each param path
    wins; unmatched params are replicated (the DDP default)."""
    compiled: List[Tuple[re.Pattern, P]] = [
        (re.compile(pat), spec) for pat, spec in (rules or [])
    ]

    def resolve(path, leaf):
        name = path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                # Drop axes absent from this mesh so one rule set serves
                # dp-only and dp×tp meshes alike.
                cleaned = P(
                    *(
                        a
                        if (
                            a is None
                            or (isinstance(a, str) and a in mesh.axis_names)
                            or (
                                isinstance(a, tuple)
                                and all(x in mesh.axis_names for x in a)
                            )
                        )
                        else None
                        for a in spec
                    )
                )
                return NamedSharding(mesh, cleaned)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(resolve, tree)


def respec_sharding(sharding: NamedSharding, new_mesh: Mesh) -> NamedSharding:
    """Carry one leaf's PartitionSpec onto a different mesh, dropping
    axes the new mesh no longer has (an axis shrunk to 1 disappears
    from the mesh — its entries replicate, the same rule
    :func:`logical_to_shardings` applies).  The elastic-reshape path
    (resilience/elastic.py) uses this to re-bind a whole state tree's
    placement after the mesh loses a host."""
    cleaned = P(
        *(
            a
            if (
                a is None
                or (isinstance(a, str) and a in new_mesh.axis_names)
                or (
                    isinstance(a, tuple)
                    and all(x in new_mesh.axis_names for x in a)
                )
            )
            else None
            for a in sharding.spec
        )
    )
    return NamedSharding(new_mesh, cleaned)


def place_tree(tree, shardings):
    """Place a pytree onto per-leaf shardings, multi-host-safely.

    Single-process this is plain per-leaf ``device_put``.  Multi-process,
    ``device_put`` of a host/uncommitted value onto a NON-addressable
    sharding makes jax verify the value is identical on every process —
    one ``broadcast_one_to_all`` collective PER LEAF.  Besides being
    O(leaves) DCN round-trips at construction time, the resulting storm
    of back-to-back differently-sized collectives aborts the gloo CPU
    backend of the 2-process test cluster (ops race on the TCP pairs:
    ``op.preamble.length <= op.nbytes`` in gloo's pair.cc — each check
    only syncs device 0's buffer, leaving the other local devices'
    collectives in flight when the next one is issued).  A single jitted
    identity with ``out_shardings`` places the WHOLE tree in one SPMD
    program with zero cross-host traffic — each process contributes its
    local values, the normal SPMD contract (the cross-host equality
    guarantee comes from seeded determinism, audited by
    ``parallel/desync.py``, not from per-leaf broadcasts)."""
    if jax.process_count() == 1:
        return jax.tree.map(jax.device_put, tree, shardings)
    return jax.jit(lambda t: t, out_shardings=shardings)(tree)


def shard_params(params, mesh: Mesh, rules: Optional[Rules] = None):
    """Materialize a parameter tree onto the mesh under the given rules."""
    shardings = logical_to_shardings(params, mesh, rules)
    return place_tree(params, shardings)


def shard_opt_state(opt_state, mesh: Mesh, axis: str = "data"):
    """ZeRO-1-style optimizer-state sharding as a pure placement decision.

    Re-places every optimizer-state leaf that is currently fully replicated
    and whose leading dim divides the ``axis`` size so dim 0 is partitioned
    over that mesh axis; XLA's SPMD partitioner then turns the weight
    update into compute on 1/N of the moments per device with the
    collectives it implies (the technique of "Automatic Cross-Replica
    Sharding of Weight Update in Data-Parallel Training" — here it is just
    a sharding annotation, not a rewrite).  Values are bit-identical to the
    replicated layout; only memory/placement changes.  Leaves already
    sharded by TP/FSDP rules (momenta inherit their param's sharding) are
    left alone.
    """
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return opt_state
    n = mesh.shape[axis]

    def place(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return leaf
        current = getattr(leaf, "sharding", None)
        if isinstance(current, NamedSharding) and any(
            s is not None for s in current.spec
        ):
            return leaf  # already model-sharded; don't fight the rules
        if leaf.shape[0] % n:
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, P(axis)))

    return jax.tree.map(place, opt_state)


class GradBucketPlan(NamedTuple):
    """Static plan for the bucketed reduce-scatter backward + sharded
    weight update (the cross-replica weight-update sharding of arXiv
    2004.13336, bucketed the way DDP's reducer buckets its all-reduces so
    communication can hide under remaining backward compute).

    ``sharded[i]`` says whether param leaf ``i`` (tree-flatten order)
    takes the reduce-scatter/sharded-update path (dim 0 divides the axis)
    or stays on the replicated psum path.  ``buckets`` lists leaf indices
    grouped into size-bounded buckets in REVERSE flatten order — the
    backward produces last-layer gradients first, so reverse forward
    order approximates production order and each bucket's collective has
    its inputs ready while earlier layers' gradients are still being
    computed (the XLA latency-hiding scheduler can then overlap them; a
    single tail psum has nothing to overlap with).
    """

    n: int
    sharded: Tuple[bool, ...]
    buckets: Tuple[Tuple[int, ...], ...]
    bucket_bytes: Tuple[int, ...]
    overlap_fraction: float


def plan_grad_buckets(tree, n: int,
                      bucket_bytes: int = 4 * 2 ** 20) -> GradBucketPlan:
    """Partition ``tree``'s leaves (shape/dtype carriers — ``eval_shape``
    output works) into reduce-scatter buckets of at most ``bucket_bytes``
    each.  The shard rule matches :func:`zero1_opt_shardings` exactly, so
    gradient shards, parameter shards and ZeRO-1 moment shards line up
    leaf-for-leaf.  ``overlap_fraction`` is the analytic share of
    reduce-scatter bytes whose collectives can hide under remaining
    backward compute — everything but the final bucket, whose inputs
    (the earliest layers' grads) are only ready when the backward ends."""
    if n < 1:
        raise ValueError(f"axis size must be >= 1, got {n}")
    leaves = jax.tree.leaves(tree)
    sharded = tuple(
        len(getattr(leaf, "shape", ())) > 0
        and leaf.shape[0] > 0
        and leaf.shape[0] % n == 0
        for leaf in leaves
    )
    nbytes = [
        int(np.prod(leaf.shape, initial=1, dtype=np.int64))
        * np.dtype(leaf.dtype).itemsize
        for leaf in leaves
    ]
    buckets: List[Tuple[int, ...]] = []
    sizes: List[int] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaves))):
        if not sharded[i]:
            continue
        if cur and cur_bytes + nbytes[i] > bucket_bytes:
            buckets.append(tuple(cur))
            sizes.append(cur_bytes)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes[i]
    if cur:
        buckets.append(tuple(cur))
        sizes.append(cur_bytes)
    total = float(sum(sizes))
    overlap = 1.0 - sizes[-1] / total if len(sizes) > 1 and total else 0.0
    return GradBucketPlan(
        n=int(n),
        sharded=sharded,
        buckets=tuple(buckets),
        bucket_bytes=tuple(sizes),
        overlap_fraction=overlap,
    )


def bucketed_reduce_scatter(leaves, plan: GradBucketPlan,
                            axis: str = "data"):
    """Inside a ``shard_map`` body: reduce-scatter each bucket of local
    (per-replica, unreduced) gradient leaves in ONE collective per bucket
    via the instrumented wrapper, returning the list with every sharded
    leaf replaced by this replica's dim-0 shard (``d0/n``-sized).
    Replicated leaves pass through untouched — the caller psums those.

    Each leaf reshapes to ``(n, d0/n * rest)`` so the concatenated bucket
    scatters along dim 0: replica ``j`` receives exactly the rows the
    ZeRO-1 ``P(axis)`` placement assigns it, summed across replicas."""
    import jax.numpy as jnp

    from ml_trainer_tpu.parallel import collectives

    out = list(leaves)
    for bi, idxs in enumerate(plan.buckets):
        parts = [leaves[i].reshape(plan.n, -1) for i in idxs]
        widths = [p.shape[1] for p in parts]
        flat = collectives.reduce_scatter(
            jnp.concatenate(parts, axis=1), axis, scatter_axis=0,
            bucket=f"b{bi}",
        ).reshape(-1)
        off = 0
        for i, w in zip(idxs, widths):
            shape = (leaves[i].shape[0] // plan.n,) + tuple(
                leaves[i].shape[1:]
            )
            out[i] = flat[off:off + w].reshape(shape)
            off += w
    return out


def bucketed_all_gather(local_leaves, plan: GradBucketPlan, full_shapes,
                        axis: str = "data"):
    """Inverse of :func:`bucketed_reduce_scatter` for the fresh weights:
    all-gather each bucket of locally-updated parameter shards in one
    collective, returning the list with every sharded leaf restored to
    its full (replicated) shape.  Gathers untiled — device ``j``'s chunk
    lands at row ``j``, which is exactly the dim-0 block order."""
    import jax.numpy as jnp

    from ml_trainer_tpu.parallel import collectives

    out = list(local_leaves)
    for bi, idxs in enumerate(plan.buckets):
        parts = [local_leaves[i].reshape(-1) for i in idxs]
        widths = [p.shape[0] for p in parts]
        gathered = collectives.all_gather(
            jnp.concatenate(parts), axis, tiled=False, bucket=f"b{bi}"
        )  # [n, sum(widths)]
        off = 0
        for i, w in zip(idxs, widths):
            out[i] = gathered[:, off:off + w].reshape(full_shapes[i])
            off += w
    return out


def zero1_opt_shardings(opt_shapes, mesh: Mesh, axis: str = "data"):
    """Target shardings for a pure-DP ZeRO-1 optimizer state, decided from
    ``jax.eval_shape(tx.init, params)`` so init can be jitted with
    ``out_shardings`` and the moments are born partitioned (never
    materialized replicated).  Shape-based rule: leading dim divisible by
    the axis size → P(axis); everything else replicated.  Only valid when
    params are replicated (no TP/FSDP rules) — rule-sharded params need the
    materialized-placement path instead."""
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), opt_shapes)
    n = mesh.shape[axis]

    def target(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) and shape[0] % n == 0 and shape[0] > 0:
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    return jax.tree.map(target, opt_shapes)
