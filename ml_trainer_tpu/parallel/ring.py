"""Ring attention — sequence/context parallelism over a mesh axis.

The reference "scales sequence length" not at all (SURVEY.md §5
long-context); this module makes it first-class.  Each device holds a
``S/n``-length shard of Q, K and V.  K/V shards rotate around the ring via
``lax.ppermute`` (ICI neighbour hops) while every device folds each visiting
block into its local online-softmax accumulators — full attention over
sequences n× longer than one chip could hold, with O(S/n) local memory and
communication that overlaps compute.

Built on ``shard_map`` so the same module composes with data/tensor
sharding on the other mesh axes, and the inner block math reuses the same
online-softmax recurrence as the Pallas flash kernel (ops/attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ml_trainer_tpu.parallel.collectives import ppermute_ring
from ml_trainer_tpu.parallel.comm_stats import account as _comm_account
from jax.sharding import Mesh, PartitionSpec as P
from ml_trainer_tpu.parallel.compat import axis_size, shard_map


def _block_attend(q, k, v, m_prev, l_prev, o_prev, q_offset, k_offset,
                  causal, scale):
    """Fold one visiting K/V block into the online-softmax accumulators.
    q: [B, H, Sq, D]; k, v: [B, H, Sk, D]; offsets are global positions."""
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + q_offset
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1) + k_offset
        scores = jnp.where(row >= col, scores, jnp.finfo(jnp.float32).min)
    m_cur = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o_prev * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, *, axis_name, causal, scale):
    """Runs per-shard inside shard_map.  q/k/v: [B, H, S_local, D]."""
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_local = q.shape[-2]
    q32 = q.astype(jnp.float32)
    q_offset = my * s_local

    def step(i, carry):
        m, l, o, kk, vv = carry
        # kk/vv currently hold the block that started on device (my - i) % n.
        src = jnp.mod(my - i, n)
        m, l, o = _block_attend(
            q32, kk.astype(jnp.float32), vv, m, l, o,
            q_offset, src * s_local, causal, scale,
        )
        # Rotate: send our current block to the next device on the ring.
        kk = ppermute_ring(kk, axis_name)
        vv = ppermute_ring(vv, axis_name)
        return m, l, o, kk, vv

    b, h, _, d = q.shape
    init = (
        jnp.full((b, h, s_local, 1), jnp.finfo(jnp.float32).min, jnp.float32),
        jnp.zeros((b, h, s_local, 1), jnp.float32),
        jnp.zeros((b, h, s_local, d), jnp.float32),
        k,
        v,
    )
    # The two ppermute_ring hops in step() trace ONCE inside fori_loop but
    # execute n times each; top the comm accounting up by the remaining
    # n-1 iterations (parallel/comm_stats.py).
    _comm_account("ppermute", (k, v), axis_name, times=n - 1)
    m, l, o, _, _ = lax.fori_loop(0, n, step, init)
    return (o / l).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """Sequence-parallel attention over [B, H, S, D] arrays whose S dim is
    (or will be) sharded over ``mesh[axis_name]``.

    ``batch_axis`` names the mesh axis the batch dim is sharded over (so the
    ring composes with data parallelism without an implicit all-gather);
    axes absent from the mesh are ignored."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if batch_axis is not None and batch_axis not in mesh.axis_names:
        batch_axis = None
    spec = P(batch_axis, None, axis_name, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal,
            scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
