"""Pipeline parallelism — GPipe-style microbatch schedule over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2C: "not required for
parity"); this fills the reserved ``stage`` mesh axis with a real,
TPU-idiomatic implementation: every device holds ONE stage's parameters
(stacked pytree sharded over ``stage``), activations hop stage→stage over
ICI via ``lax.ppermute``, and the whole schedule is a single ``lax.scan``
over clock ticks inside ``shard_map`` — one compiled program, no host-side
stage loop, reverse-differentiable (scan + ppermute both are).

Schedule: with S stages and M microbatches the scan runs S+M-1 ticks; at
tick t stage s computes microbatch t-s (devices idle in the ramp-up/down
triangles, the standard GPipe bubble of (S-1)/(S+M-1)).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ml_trainer_tpu.parallel.comm_stats import account as _account
from ml_trainer_tpu.parallel.compat import axis_size, shard_map


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-stage parameter pytrees (identical structure)
    into one pytree with a leading stage dim — the layout that shards over
    the ``stage`` mesh axis with ``P('stage', ...)``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _pipeline_local(params, x, *, stage_fn, axis_name, n_micro, remat):
    """Per-device body under shard_map.

    params: this device's stage params (leading stage dim of size 1).
    x: the full [n_micro, mb, ...] microbatched input (replicated).
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], params)  # drop the stage dim
    mb_shape = x.shape[1:]
    fwd_perm = [(s, s + 1) for s in range(n_stages - 1)]
    if remat:
        # Differentiating through the scan stores every tick's stage
        # activations for the backward — O(S + M - 1) ticks of them per
        # device.  Checkpointing the stage body keeps only the scan carry
        # and recomputes the body during the reverse pass: activation
        # memory drops to O(1) ticks for one extra forward of compute,
        # the standard pipeline-training trade.
        stage_fn = jax.checkpoint(stage_fn)

    def tick(carry, t):
        prev_out, outputs = carry
        # Activations computed last tick hop to the next stage.
        recv = lax.ppermute(prev_out, axis_name, fwd_perm)
        # Stage 0 injects microbatch t (zeros past the ramp); others consume
        # the hop.  Indexing is clamped — masked ticks compute garbage that
        # is never written anywhere.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        my_in = jnp.where(
            stage == 0,
            lax.dynamic_index_in_dim(x, mb_idx, keepdims=False),
            recv,
        )
        out = stage_fn(params, my_in)
        # The last stage finishes microbatch t-(S-1) at tick t.
        done_idx = t - (n_stages - 1)
        is_done = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
        outputs = lax.cond(
            is_done,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.clip(done_idx, 0, n_micro - 1), 0
            ),
            lambda o: o,
            outputs,
        )
        return (out, outputs), None

    init = (
        jnp.zeros(mb_shape, x.dtype),
        jnp.zeros((n_micro,) + mb_shape, x.dtype),
    )
    # The hop inside tick() traces once but runs every scan iteration:
    # account it here with the static tick count instead.
    _account("ppermute", init[0], axis_name, times=n_micro + n_stages - 1)
    (_, outputs), _ = lax.scan(
        tick, init, jnp.arange(n_micro + n_stages - 1)
    )
    # Only the last stage holds real outputs; psum broadcasts them (every
    # other stage contributes zeros), matching the replicated out_spec.
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    _account("psum", outputs, axis_name)
    return lax.psum(outputs, axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "stage",
    n_microbatches: int = None,
    batch_axis: str = "data",
    remat: bool = False,
) -> jax.Array:
    """Run ``x`` through ``n_stages`` sequential stages, pipelined.

    ``stage_fn(params_for_one_stage, microbatch) -> microbatch_out`` must
    preserve the activation shape (classic equal-width pipeline).
    ``stage_params``: pytree whose leaves have leading dim n_stages
    (see ``stack_stage_params``).  ``x``: [batch, ...] — split into
    ``n_microbatches`` equal microbatches (default: one per stage).
    Semantically equivalent to folding ``stage_fn`` serially; the pipeline
    only changes WHERE each stage runs and WHEN.  ``remat=True``
    recomputes stage bodies in the backward pass instead of storing every
    tick's activations (math unchanged — see ``_pipeline_local``).

    When the mesh also has a live ``batch_axis`` (dp × pp), each
    microbatch's batch dim shards over it — the data-parallel replicas
    pipeline their own slices and the gradient psum over ``data`` happens
    outside, exactly as with any other sharded batch.
    """
    n_stages = mesh.shape[axis_name]
    n_micro = n_microbatches or n_stages
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(
            f"batch {batch} not divisible into {n_micro} microbatches"
        )
    if batch_axis is not None and batch_axis not in mesh.axis_names:
        batch_axis = None
    xm = x.reshape((n_micro, batch // n_micro) + x.shape[1:])
    x_spec = P(None, batch_axis) if batch_axis else P()
    fn = shard_map(
        functools.partial(
            _pipeline_local,
            stage_fn=stage_fn,
            axis_name=axis_name,
            n_micro=n_micro,
            remat=remat,
        ),
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(axis_name), stage_params),
            x_spec,
        ),
        out_specs=x_spec,
        check_vma=False,
    )
    out = fn(stage_params, xm)
    return out.reshape((batch,) + out.shape[2:])
