"""Pipeline parallelism — tick-table microbatch schedules over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2C); this module
fills the reserved ``stage`` mesh axis with a family of TPU-idiomatic
schedules over ONE stacked-params representation: every device holds one
(or ``n_virtual``) stage's parameters (stacked pytree sharded over
``stage``), activations hop stage→stage over ICI via ``lax.ppermute``,
and each schedule is a single ``lax.scan`` over a **precomputed static
tick table** inside ``shard_map`` — one compiled program, no host-side
stage loop.  All schedules compute exactly the serial fold of the
stages (same math, different WHERE/WHEN — the trajectory-equality
discipline pins this).

Schedules (``pipeline_apply(..., schedule=)``; taxonomy per arXiv
2412.14374):

``gpipe``
    The original scan: at tick t stage s computes microbatch t-s, the
    backward is jax autodiff of the scan (reversed replay).  Bubble
    fraction (S-1)/(S+M-1) per pass; autodiff stores O(S+M-1) ticks of
    scan state per device unless ``remat=True``.
``1f1b``
    One-forward-one-backward over the tick-table engine: the backward
    pass is hand-scheduled (``jax.custom_vjp``), draining cotangents as
    soon as they arrive instead of replaying the forward scan in
    reverse.  With ``remat=True`` the backward interleaves forward
    recomputes with backwards, keeping the in-flight activation stash
    bounded at ~S microbatches (host-verified slot allocation) — the
    memory win over GPipe.  With ``remat=False`` the value pass stashes
    only the per-stage *boundary* activations ([V, M] microbatch inputs
    per device) and the backward is a lean reverse pipeline — still far
    below GPipe-autodiff's full per-tick residuals.
``interleaved``
    1F1B with ``n_virtual`` virtual stages per device (stacked params
    carry V stages per device, assigned round-robin so hops stride the
    stage ring); the ramp shrinks by ~V, cutting the bubble toward
    (S-1)/(V·(S+M-1)).
``zb``
    Zero-bubble-style split backward (experimental): the backward of
    each stage is split into an input-grad half (critical path) and a
    weight-grad half (fills former bubble slots), per the zero-bubble
    schedule family.  Same math — the two vjp halves sum to the full
    vjp.

Every hop and broadcast self-accounts analytic bytes at trace time
through ``parallel/comm_stats.py``, attributed per schedule and hop
kind (``comm_bytes_by_hop{schedule=,hop=}``), and each built schedule
records its analytic bubble fraction (idle tick-table slots) into
``pipeline_schedule_info()`` and the
``train_pipeline_bubble_fraction{schedule=}`` gauge.
"""

from __future__ import annotations

import functools
import heapq
import threading
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ml_trainer_tpu.parallel.comm_stats import (
    _tree_bytes,
    account as _account,
    record_collective as _record_collective,
    record_hop as _record_hop,
)
from ml_trainer_tpu.parallel.compat import axis_size, shard_map

SCHEDULES = ("gpipe", "1f1b", "interleaved", "zb")
PIPELINE_SCHEDULES = SCHEDULES  # public alias (parallel/__init__.py)

# Tick-table action codes.  ``zb`` splits the backward: B_X produces the
# input cotangent (critical path), B_W the weight gradient (bubble
# filler); other schedules use the fused B.
_IDLE, _F, _B, _BW = 0, 1, 2, 3

_info_lock = threading.Lock()
_SCHEDULE_INFO: Dict[str, dict] = {}


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-stage parameter pytrees (identical structure)
    into one pytree with a leading stage dim — the layout that shards over
    the ``stage`` mesh axis with ``P('stage', ...)``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_schedule_info() -> Dict[str, dict]:
    """Per-schedule build info recorded at trace time: tick counts,
    analytic bubble (idle tick-table slot) fractions, and stash sizing.
    Keyed by schedule name; the latest build per schedule wins."""
    with _info_lock:
        return {k: dict(v) for k, v in _SCHEDULE_INFO.items()}


def reset_pipeline_info() -> None:
    with _info_lock:
        _SCHEDULE_INFO.clear()


def _record_info(schedule: str, info: dict) -> None:
    with _info_lock:
        _SCHEDULE_INFO[schedule] = dict(info)
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        default_registry().gauge(
            "train_pipeline_bubble_fraction",
            "analytic pipeline bubble: fraction of device-tick slots "
            "idle in the schedule's tick tables (forward + backward)",
            ("schedule",),
        ).labels(schedule=schedule).set(float(info["bubble_fraction"]))
    except Exception:  # registry trouble must never break a trace
        pass


# --------------------------------------------------------------- scheduler
class _Tables:
    """Static tick tables for one pass of one schedule (host numpy)."""

    def __init__(self, n_ticks, n_dev, n_f_slots, n_b_slots):
        shape = (max(n_ticks, 1), n_dev)
        z = lambda: np.zeros(shape, np.int32)
        self.kind = z()
        self.mb = z()
        self.vs = z()
        self.first = z()
        self.last = z()
        self.ycap = z()
        self.dxcap = z()
        self.arg_f = z()
        self.arg_b = z()
        # Default recv slot = trash row (index n_slots): payloads nobody
        # scheduled (idle-tick zeros, the last stage's unconsumed output)
        # land there and are never read.
        self.recv_f = np.full(shape, n_f_slots, np.int32)
        self.recv_b = np.full(shape, n_b_slots, np.int32)
        self.n_ticks = n_ticks
        self.n_f_slots = n_f_slots
        self.n_b_slots = n_b_slots
        self.n_actions = 0
        # Per-kind action counts for the executed-compute waste model.
        self.n_f = 0
        self.n_b = 0
        self.n_w = 0

    def as_jnp(self) -> dict:
        return {
            k: jnp.asarray(getattr(self, k))
            for k in ("kind", "mb", "vs", "first", "last", "ycap",
                      "dxcap", "arg_f", "arg_b", "recv_f", "recv_b")
        }

    @property
    def idle_fraction(self) -> float:
        total = self.n_ticks * self.kind.shape[1]
        return 1.0 - self.n_actions / total if total else 0.0


def _alloc_slots(payloads: dict):
    """Assign buffer slots to payloads: ``payloads`` maps key ->
    (arrival_tick, device, last_use_tick).  A slot consumed at tick t is
    reusable for arrivals at t+1 (the scan body stores the arriving hop
    BEFORE computing, so a same-tick reuse would clobber the value being
    read).  Returns (recv{(tick, dev): slot}, slot_of{key: slot},
    n_slots)."""
    by_dev: Dict[int, list] = {}
    for key, (arrive, dev, last_use) in payloads.items():
        by_dev.setdefault(dev, []).append((arrive, last_use, key))
    recv, slot_of, n_slots = {}, {}, 0
    for dev, plist in by_dev.items():
        plist.sort()
        active: list = []  # (last_use, slot) min-heap
        free: list = []
        hi = 0
        for arrive, last_use, key in plist:
            while active and active[0][0] < arrive:
                heapq.heappush(free, heapq.heappop(active)[1])
            slot = heapq.heappop(free) if free else hi
            if not free and slot == hi:
                hi += 1
            heapq.heappush(active, (last_use, slot))
            recv[(arrive, dev)] = slot
            slot_of[key] = slot
        n_slots = max(n_slots, hi)
    return recv, slot_of, n_slots


@functools.lru_cache(maxsize=64)
def _build_tables(schedule: str, n_dev: int, n_virtual: int, n_micro: int,
                  mode: str) -> _Tables:
    """Greedy list-schedule one pass of ``schedule`` into static tick
    tables.  ``mode``:

    * ``'fwd'`` — the value pass: forwards only.
    * ``'bwd_stash'`` — backward over stashed boundary activations
      (``remat=False``): backwards only, a lean reverse pipeline.
    * ``'bwd_recompute'`` — combined pass (``remat=True``): forward
      recomputes interleaved with backwards, in-flight stash bounded at
      ~S microbatches by construction (1F1B's memory contract).

    Dependencies model the scan's communication exactly: an action's
    output hops at the START of the next tick, so a consumer on the
    neighbouring device is ready at ``producer_tick + 1`` (and may fire
    that very tick — the body stores arrivals before computing).
    """
    S, V, M = int(n_dev), int(n_virtual), int(n_micro)
    G = S * V
    zb = schedule == "zb" and mode != "fwd"

    if mode == "fwd":
        f_need = {(g, i) for g in range(G) for i in range(M)}
    elif mode == "bwd_recompute":
        # The last global stage's recompute is folded into its B's vjp
        # (jax.vjp re-runs the forward to linearize) — scheduling it
        # separately would be pure waste.
        f_need = {(g, i) for g in range(G - 1) for i in range(M)}
    else:
        f_need = set()
    b_need = (set() if mode == "fwd"
              else {(g, i) for g in range(G) for i in range(M)})
    w_need = set(b_need) if zb else set()

    done_f: dict = {}
    done_b: dict = {}
    done_w: dict = {}
    b_count = [0] * G  # completed B (B_X) per stage — the 1F1B cap releaser
    acts: Dict[int, Dict[int, tuple]] = {}
    t, limit = 0, 16 * (G + M + 4) * (V + 2)
    while f_need or b_need or w_need:
        if t > limit:
            raise RuntimeError(
                f"pipeline scheduler stuck: {schedule} S={S} V={V} M={M} "
                f"mode={mode}"
            )
        for d in range(S):
            best = None
            # B (or B_X) first: drain cotangents as soon as they arrive —
            # the 1F1B discipline (and what bounds the stash).
            for (g, i) in b_need:
                if g % S != d:
                    continue
                if (mode == "bwd_recompute" and g > 0
                        and done_f.get((g - 1, i), t) + 1 > t):
                    continue  # stage input not recomputed/arrived yet
                if g < G - 1 and done_b.get((g + 1, i), t) + 1 > t:
                    continue  # cotangent not arrived yet
                key = (i, -g)
                if best is None or key < best[0]:
                    best = (key, "B", g, i)
            if best is None:
                for (g, i) in f_need:
                    if g % S != d:
                        continue
                    if g > 0 and done_f.get((g - 1, i), t) + 1 > t:
                        continue
                    if i > 0 and (g, i - 1) not in done_f:
                        continue  # per-stage microbatch order
                    # 1F1B warmup cap: stage g keeps at most G-g
                    # microbatches in flight, so the stash stays O(S·V).
                    if b_need and i - b_count[g] >= G - g:
                        continue
                    key = (i, g)
                    if best is None or key < best[0]:
                        best = (key, "F", g, i)
            if best is None:
                # Weight-grad halves (zb) fill whatever slots remain.
                for (g, i) in w_need:
                    if g % S != d:
                        continue
                    if done_b.get((g, i), t) + 1 > t:
                        continue
                    key = (i, -g)
                    if best is None or key < best[0]:
                        best = (key, "W", g, i)
            if best is None:
                continue
            _, what, g, i = best
            acts.setdefault(t, {})[d] = (what, g, i)
            if what == "F":
                done_f[(g, i)] = t
                f_need.discard((g, i))
            elif what == "B":
                done_b[(g, i)] = t
                b_need.discard((g, i))
                b_count[g] += 1
            else:
                done_w[(g, i)] = t
                w_need.discard((g, i))
        t += 1

    n_ticks = (max(acts) + 1) if acts else 0

    # Payload lifetimes -> buffer slots.  Forward payload (g -> g+1, i):
    # produced by F(g, i), consumed by F(g+1, i) and/or the backward of
    # stage g+1 (both halves under zb).
    f_pay: dict = {}
    for (g, i), tf in done_f.items():
        if g + 1 > G - 1:
            continue  # the last stage's output is y, captured not hopped
        uses = [done_x[(g + 1, i)]
                for done_x in (done_f, done_b, done_w)
                if (g + 1, i) in done_x]
        if uses:
            f_pay[(g, i)] = (tf + 1, (g + 1) % S, max(uses))
    b_pay: dict = {}
    for (g, i), tb in done_b.items():
        if g == 0:
            continue  # dx, captured not hopped
        uses = [done_x[(g - 1, i)]
                for done_x in (done_b, done_w)
                if (g - 1, i) in done_x]
        if uses:
            b_pay[(g, i)] = (tb + 1, (g - 1) % S, max(uses))
    recv_f, slot_f, nf = _alloc_slots(f_pay)
    recv_b, slot_b, nb = _alloc_slots(b_pay)

    tabs = _Tables(n_ticks, S, nf, nb)
    for (arrive, dev), slot in recv_f.items():
        if arrive < n_ticks:
            tabs.recv_f[arrive, dev] = slot
    for (arrive, dev), slot in recv_b.items():
        if arrive < n_ticks:
            tabs.recv_b[arrive, dev] = slot
    for t, per_dev in acts.items():
        for d, (what, g, i) in per_dev.items():
            tabs.n_actions += 1
            if what == "F":
                tabs.kind[t, d] = _F
                tabs.n_f += 1
            elif what == "B":
                tabs.kind[t, d] = _B
                tabs.n_b += 1
            else:
                tabs.kind[t, d] = _BW
                tabs.n_w += 1
            tabs.mb[t, d] = i
            tabs.vs[t, d] = g // S
            tabs.first[t, d] = int(g == 0)
            tabs.last[t, d] = int(g == G - 1)
            if mode == "fwd":
                tabs.ycap[t, d] = int(what == "F" and g == G - 1)
            if what == "B" and g == 0:
                tabs.dxcap[t, d] = 1
            if g > 0 and what in ("F", "B", "W") and (g - 1, i) in slot_f:
                tabs.arg_f[t, d] = slot_f[(g - 1, i)]
            if what in ("B", "W") and g < G - 1 and (g + 1, i) in slot_b:
                tabs.arg_b[t, d] = slot_b[(g + 1, i)]
    return tabs


# ------------------------------------------------------------- primitives
def _ring_broadcast(val, root: int, axis_name: str, *, schedule: str,
                    hop: str):
    """Broadcast ``val`` from ``root`` to every device on the axis by
    recursive doubling over partial ``ppermute`` perms: ceil(log2 S)
    calls, (S-1)·size total wire bytes — half the ring all-reduce the
    old output ``psum`` paid (and no reduction compute).  Each call's
    analytic bytes are recorded per participant (size · active pairs /
    S) against the schedule's hop ledger."""
    n = axis_size(axis_name)
    if n <= 1:
        return val
    stage = lax.axis_index(axis_name)
    dist = (stage - root) % n
    size = _tree_bytes(val)
    k = 1
    while k < n:
        pairs = [((root + i) % n, (root + i + k) % n)
                 for i in range(k) if i + k < n]
        recv = lax.ppermute(val, axis_name, pairs)
        val = jnp.where((dist >= k) & (dist < 2 * k), recv, val)
        try:
            b = float(size) * len(pairs) / n
            _record_collective("ppermute", b, calls=1)
            _record_hop(schedule, hop, b, calls=1)
        except Exception:
            pass
        k *= 2
    return val


# ------------------------------------------------------------ gpipe (scan)
def _pipeline_local(params, x, *, stage_fn, axis_name, n_micro, remat):
    """Per-device GPipe body under shard_map (the original schedule).

    params: this device's stage params (leading stage dim of size 1).
    x: the full [n_micro, mb, ...] microbatched input (replicated).
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], params)  # drop the stage dim
    mb_shape = x.shape[1:]
    fwd_perm = [(s, s + 1) for s in range(n_stages - 1)]
    if remat:
        # Differentiating through the scan stores every tick's stage
        # activations for the backward — O(S + M - 1) ticks of them per
        # device.  Checkpointing the stage body keeps only the scan carry
        # and recomputes the body during the reverse pass: activation
        # memory drops to O(1) ticks for one extra forward of compute,
        # the standard pipeline-training trade.
        stage_fn = jax.checkpoint(stage_fn)

    def tick(carry, t):
        prev_out, outputs = carry
        # Activations computed last tick hop to the next stage.
        recv = lax.ppermute(prev_out, axis_name, fwd_perm)
        # Stage 0 injects microbatch t (zeros past the ramp); others consume
        # the hop.  Indexing is clamped — masked ticks compute garbage that
        # is never written anywhere.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        my_in = jnp.where(
            stage == 0,
            lax.dynamic_index_in_dim(x, mb_idx, keepdims=False),
            recv,
        )
        out = stage_fn(params, my_in)
        # The last stage finishes microbatch t-(S-1) at tick t.
        done_idx = t - (n_stages - 1)
        is_done = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
        outputs = lax.cond(
            is_done,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.clip(done_idx, 0, n_micro - 1), 0
            ),
            lambda o: o,
            outputs,
        )
        return (out, outputs), None

    init = (
        jnp.zeros(mb_shape, x.dtype),
        jnp.zeros((n_micro,) + mb_shape, x.dtype),
    )
    # The hop inside tick() traces once but runs every scan iteration:
    # account it here with the static tick count instead.
    _account("ppermute", init[0], axis_name,
             times=n_micro + n_stages - 1, hop=("gpipe", "fwd"))
    (_, outputs), _ = lax.scan(
        tick, init, jnp.arange(n_micro + n_stages - 1)
    )
    # Only the last stage holds real outputs.  The old implementation
    # psum-broadcast the full [n_micro, mb, ...] tensor from EVERY stage
    # (all but one contributing zeros — 2·(S-1)/S·size per participant);
    # a last-stage ring broadcast moves half the bytes and adds nothing.
    return _ring_broadcast(outputs, n_stages - 1, axis_name,
                           schedule="gpipe", hop="output_broadcast")


# ----------------------------------------------------- tick-table engine
def _row_at(tables: dict, stage):
    """This device's scalar entries of one tick's table row."""
    return {k: v[stage] for k, v in tables.items()}


def _engine_fwd_local(params, x, *, stage_fn, axis_name, tables, n_f_slots,
                      n_ticks, n_virtual, want_stash, schedule):
    """Value pass: forwards only, idle slots genuinely skipped
    (``lax.switch``), finished microbatches captured on the last stage
    and ring-broadcast at the end.  With ``want_stash`` every stage
    input is also written into a [V, M] boundary-activation stash — the
    ``remat=False`` backward's residuals."""
    S = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro, mb_shape = x.shape[0], x.shape[1:]
    fwd_perm = [(s, (s + 1) % S) for s in range(S)]
    zero_mb = jnp.zeros(mb_shape, x.dtype)
    _account("ppermute", zero_mb, axis_name, times=n_ticks,
             hop=(schedule, "fwd"))

    carry = {
        "msg": zero_mb,
        "buf": jnp.zeros((n_f_slots + 1,) + mb_shape, x.dtype),
        "y": jnp.zeros((n_micro,) + mb_shape, x.dtype),
    }
    if want_stash:
        carry["stash"] = jnp.zeros((n_virtual, n_micro) + mb_shape, x.dtype)

    def tick(carry, row):
        r = _row_at(row, stage)
        recv = lax.ppermute(carry["msg"], axis_name, fwd_perm)
        buf = lax.dynamic_update_index_in_dim(
            carry["buf"], recv, r["recv_f"], 0
        )
        a_in = jnp.where(
            r["first"] > 0,
            lax.dynamic_index_in_dim(x, r["mb"], keepdims=False),
            lax.dynamic_index_in_dim(buf, r["arg_f"], keepdims=False),
        )
        pv = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, r["vs"], keepdims=False),
            params,
        )
        out = lax.switch(r["kind"], (
            lambda op: jnp.zeros(mb_shape, x.dtype),
            lambda op: stage_fn(op[0], op[1]).astype(x.dtype),
        ), (pv, a_in))
        y = lax.cond(
            r["ycap"] > 0,
            lambda yy: lax.dynamic_update_index_in_dim(yy, out, r["mb"], 0),
            lambda yy: yy,
            carry["y"],
        )
        new = {"msg": out, "buf": buf, "y": y}
        if "stash" in carry:
            new["stash"] = lax.cond(
                r["kind"] > 0,
                lambda ss: lax.dynamic_update_slice(
                    ss, a_in[None, None],
                    (r["vs"], r["mb"]) + (0,) * len(mb_shape),
                ),
                lambda ss: ss,
                carry["stash"],
            )
        return new, None

    carry, _ = lax.scan(tick, carry, tables)
    y = _ring_broadcast(carry["y"], S - 1, axis_name,
                        schedule=schedule, hop="output_broadcast")
    return (y, carry["stash"]) if want_stash else (y,)


def _engine_bwd_local(params, x, stash, dy, *, stage_fn, axis_name, tables,
                      n_f_slots, n_b_slots, n_ticks, recompute, schedule,
                      batch_axis=None):
    """Backward pass: the hand-scheduled scan over the combined
    (``recompute=True``) or backward-only (stash) tick table.  Each tick
    at most one action per device via ``lax.switch``: forward recompute,
    fused backward (``jax.vjp`` of the stage), or the zb split halves.
    Param grads accumulate per local virtual stage; the input cotangent
    is captured on device 0 and ring-broadcast out."""
    S = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro, mb_shape = x.shape[0], x.shape[1:]
    fwd_perm = [(s, (s + 1) % S) for s in range(S)]
    bwd_perm = [(s, (s - 1) % S) for s in range(S)]
    zero_mb = jnp.zeros(mb_shape, x.dtype)
    zero_dp = jax.tree.map(lambda p: jnp.zeros(p.shape[1:], p.dtype), params)
    _account("ppermute", zero_mb, axis_name, times=n_ticks,
             hop=(schedule, "bwd"))
    if recompute:
        _account("ppermute", zero_mb, axis_name, times=n_ticks,
                 hop=(schedule, "fwd_recompute"))

    carry = {
        "mb_": zero_mb,  # backward-direction message (cotangent hop)
        "bbuf": jnp.zeros((n_b_slots + 1,) + mb_shape, x.dtype),
        "grads": jax.tree.map(jnp.zeros_like, params),
        "dx": jnp.zeros_like(x),
    }
    if recompute:
        carry["mf"] = zero_mb
        carry["fbuf"] = jnp.zeros((n_f_slots + 1,) + mb_shape, x.dtype)

    def tick(carry, row):
        r = _row_at(row, stage)
        recv_b = lax.ppermute(carry["mb_"], axis_name, bwd_perm)
        bbuf = lax.dynamic_update_index_in_dim(
            carry["bbuf"], recv_b, r["recv_b"], 0
        )
        if recompute:
            recv_f = lax.ppermute(carry["mf"], axis_name, fwd_perm)
            fbuf = lax.dynamic_update_index_in_dim(
                carry["fbuf"], recv_f, r["recv_f"], 0
            )
            a_in = jnp.where(
                r["first"] > 0,
                lax.dynamic_index_in_dim(x, r["mb"], keepdims=False),
                lax.dynamic_index_in_dim(fbuf, r["arg_f"], keepdims=False),
            )
        else:
            fbuf = None
            # Boundary activations were stashed in the value pass —
            # including stage 0's (== x[mb]), so no injection mux.
            a_in = lax.dynamic_slice(
                stash, (r["vs"], r["mb"]) + (0,) * len(mb_shape),
                (1, 1) + mb_shape,
            ).reshape(mb_shape)
        g_in = jnp.where(
            r["last"] > 0,
            lax.dynamic_index_in_dim(dy, r["mb"], keepdims=False),
            lax.dynamic_index_in_dim(bbuf, r["arg_b"], keepdims=False),
        )
        pv = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, r["vs"], keepdims=False),
            params,
        )

        def br_idle(op):
            return zero_mb, zero_mb, zero_dp

        def br_fwd(op):
            pvv, a, g = op
            return stage_fn(pvv, a).astype(x.dtype), zero_mb, zero_dp

        def br_bwd(op):
            pvv, a, g = op
            out, pull = jax.vjp(stage_fn, pvv, a)
            dp, da = pull(g.astype(out.dtype))
            return zero_mb, da.astype(x.dtype), dp

        def br_bwd_x(op):
            pvv, a, g = op
            out, pull = jax.vjp(lambda aa: stage_fn(pvv, aa), a)
            (da,) = pull(g.astype(out.dtype))
            return zero_mb, da.astype(x.dtype), zero_dp

        def br_bwd_w(op):
            pvv, a, g = op
            out, pull = jax.vjp(lambda pp: stage_fn(pp, a), pvv)
            (dp,) = pull(g.astype(out.dtype))
            return zero_mb, zero_mb, dp

        branches = (
            (br_idle, br_fwd, br_bwd_x, br_bwd_w)
            if schedule == "zb" else (br_idle, br_fwd, br_bwd)
        )
        out_f, out_b, dp = lax.switch(r["kind"], branches, (pv, a_in, g_in))
        grads = jax.tree.map(
            lambda acc, d: acc.at[r["vs"]].add(d), carry["grads"], dp
        )
        dx = lax.cond(
            r["dxcap"] > 0,
            lambda dd: lax.dynamic_update_index_in_dim(dd, out_b, r["mb"], 0),
            lambda dd: dd,
            carry["dx"],
        )
        new = {"mb_": out_b, "bbuf": bbuf, "grads": grads, "dx": dx}
        if recompute:
            new["mf"] = out_f
            new["fbuf"] = fbuf
        return new, None

    carry, _ = lax.scan(tick, carry, tables)
    grads = carry["grads"]
    if batch_axis is not None:
        # dp x pp composition: each data replica backpropagated only its
        # own batch shard — the stage grads must sum across replicas.
        # The legacy gpipe path gets this psum from shard_map's
        # transpose of the replicated param in_spec; the hand-written
        # backward inserts (and accounts) it explicitly.
        _account("psum", grads, batch_axis)
        grads = lax.psum(grads, batch_axis)
    dx = _ring_broadcast(carry["dx"], 0, axis_name,
                         schedule=schedule, hop="grad_input_broadcast")
    return grads, dx


# ------------------------------------------------------------- public API
def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "stage",
    n_microbatches: Optional[int] = None,
    batch_axis: str = "data",
    remat: bool = False,
    schedule: str = "gpipe",
    n_virtual: int = 1,
) -> jax.Array:
    """Run ``x`` through the stacked stages sequentially, pipelined.

    ``stage_fn(params_for_one_stage, microbatch) -> microbatch_out`` must
    preserve the activation shape (classic equal-width pipeline).
    ``stage_params``: pytree whose leaves have leading dim
    ``n_stages_total = mesh.shape[axis_name] * n_virtual`` (see
    ``stack_stage_params``).  ``x``: [batch, ...] — split into
    ``n_microbatches`` equal microbatches (default: one per stage).
    Semantically equivalent to folding ``stage_fn`` serially; every
    schedule only changes WHERE each stage runs and WHEN.

    ``schedule``: one of ``SCHEDULES`` (module docstring).  ``n_virtual``
    (``interleaved`` only): virtual stages per device — stage ``g`` lives
    on device ``g % S``, so hops stride the stage ring.

    ``remat=True`` recomputes stage bodies in the backward pass instead
    of storing activations: for ``gpipe`` via ``jax.checkpoint`` on the
    scan body; for the engine schedules via the combined backward table
    whose in-flight stash is bounded at ~S microbatches.  Math is
    unchanged either way.

    When the mesh also has a live ``batch_axis`` (dp × pp), each
    microbatch's batch dim shards over it — the data-parallel replicas
    pipeline their own slices and the gradient psum over ``data`` happens
    outside, exactly as with any other sharded batch.
    """
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown schedule {schedule!r}; expected one of {SCHEDULES}"
        )
    if n_virtual < 1:
        raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
    if n_virtual > 1 and schedule != "interleaved":
        raise ValueError(
            "n_virtual > 1 is the interleaved schedule's knob; pass "
            f"schedule='interleaved' (got schedule={schedule!r})"
        )
    n_dev = mesh.shape[axis_name]
    n_total = n_dev * n_virtual
    leaves = jax.tree.leaves(stage_params)
    bad = [l.shape for l in leaves if l.ndim < 1 or l.shape[0] != n_total]
    if bad:
        raise ValueError(
            f"stage_params leaves must carry a leading stage dim of "
            f"{n_total} (= {n_dev} devices x {n_virtual} virtual); got "
            f"leading dims {sorted({s[0] if s else None for s in bad})}"
        )
    n_micro = n_microbatches or n_total
    if n_micro < n_total:
        raise ValueError(
            f"n_microbatches={n_micro} < n_stages={n_total}: every "
            "schedule here needs a full ramp (GPipe's bubble degenerates "
            "and 1F1B's in-flight stash sizing assumes M >= S); raise "
            "n_microbatches or lower the stage count"
        )
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(
            f"batch {batch} not divisible into {n_micro} microbatches"
        )
    if batch_axis is not None and batch_axis not in mesh.axis_names:
        batch_axis = None
    xm = x.reshape((n_micro, batch // n_micro) + x.shape[1:])
    x_spec = P(None, batch_axis) if batch_axis else P()
    p_specs = jax.tree.map(lambda _: P(axis_name), stage_params)

    if schedule == "gpipe":
        t_g = n_micro + n_dev - 1
        # Executed-compute waste (units: forward=1, backward-proper=2,
        # relinearize/recompute=1): the GPipe scan computes on EVERY
        # device EVERY tick — ramp slots execute garbage rather than
        # idling — and its autodiff backward replays all ticks (plus a
        # full recompute under remat).
        executed = n_dev * t_g * (1.0 + (3.0 if remat else 2.0))
        useful = 3.0 * n_micro * n_total
        _record_info("gpipe", {
            "schedule": "gpipe", "n_devices": n_dev, "n_virtual": 1,
            "n_stages": n_total, "n_micro": n_micro, "remat": bool(remat),
            "fwd_ticks": t_g,
            "bwd_ticks": t_g,
            # Classic ramp bubble, identical in the autodiff-mirrored
            # backward pass (no idle skipping in either).
            "bubble_fraction": round((n_dev - 1) / t_g, 4),
            "wasted_compute_fraction": round(1.0 - useful / executed, 4),
        })
        fn = shard_map(
            functools.partial(
                _pipeline_local,
                stage_fn=stage_fn,
                axis_name=axis_name,
                n_micro=n_micro,
                remat=remat,
            ),
            mesh=mesh,
            in_specs=(p_specs, x_spec),
            out_specs=x_spec,
            check_vma=False,
        )
        out = fn(stage_params, xm)
        return out.reshape((batch,) + out.shape[2:])

    # ------------------------------------------------ tick-table engine
    fwd_tabs = _build_tables(schedule, n_dev, n_virtual, n_micro, "fwd")
    bwd_mode = "bwd_recompute" if remat else "bwd_stash"
    bwd_tabs = _build_tables(schedule, n_dev, n_virtual, n_micro, bwd_mode)
    total_slots = (fwd_tabs.n_ticks + bwd_tabs.n_ticks) * n_dev
    busy = fwd_tabs.n_actions + bwd_tabs.n_actions
    # Executed-compute waste (same unit model as gpipe's): idle slots are
    # genuinely SKIPPED by the engine (lax.switch), so only scheduled
    # actions execute — a fused backward costs 3 units (1 relinearize +
    # 2 backward-proper), the zb halves 2 each.
    executed = (
        fwd_tabs.n_f + bwd_tabs.n_f
        + (2.0 * bwd_tabs.n_b + 2.0 * bwd_tabs.n_w if schedule == "zb"
           else 3.0 * bwd_tabs.n_b)
    )
    useful = 3.0 * n_micro * n_total
    _record_info(schedule, {
        "schedule": schedule, "n_devices": n_dev, "n_virtual": n_virtual,
        "n_stages": n_total, "n_micro": n_micro, "remat": bool(remat),
        "fwd_ticks": fwd_tabs.n_ticks, "bwd_ticks": bwd_tabs.n_ticks,
        "fwd_idle_fraction": round(fwd_tabs.idle_fraction, 4),
        "bwd_idle_fraction": round(bwd_tabs.idle_fraction, 4),
        "bubble_fraction": round(1.0 - busy / total_slots, 4),
        "wasted_compute_fraction": round(1.0 - useful / executed, 4),
        "stash_slots": bwd_tabs.n_f_slots if remat else None,
        "boundary_stash_microbatches": None if remat else n_micro,
    })

    if n_virtual > 1:
        # Round-robin placement: device d owns global stages {v*S + d}.
        # shard_map splits the leading dim contiguously, so permute the
        # stack to [stages of dev 0 | stages of dev 1 | ...] first; the
        # take's transpose un-permutes the grads automatically.
        perm = np.asarray(
            [v * n_dev + d for d in range(n_dev) for v in range(n_virtual)],
            np.int32,
        )
        p_sched = jax.tree.map(
            lambda p: jnp.take(p, perm, axis=0), stage_params
        )
    else:
        p_sched = stage_params

    stash_spec = (
        P(axis_name, None, batch_axis) if batch_axis else P(axis_name)
    )

    fwd_shard = shard_map(
        functools.partial(
            _engine_fwd_local,
            stage_fn=stage_fn, axis_name=axis_name,
            tables=fwd_tabs.as_jnp(), n_f_slots=fwd_tabs.n_f_slots,
            n_ticks=fwd_tabs.n_ticks, n_virtual=n_virtual,
            want_stash=not remat, schedule=schedule,
        ),
        mesh=mesh,
        in_specs=(p_specs, x_spec),
        out_specs=(x_spec, stash_spec) if not remat else (x_spec,),
        check_vma=False,
    )
    bwd_kwargs = dict(
        stage_fn=stage_fn, axis_name=axis_name,
        tables=bwd_tabs.as_jnp(), n_f_slots=bwd_tabs.n_f_slots,
        n_b_slots=bwd_tabs.n_b_slots, n_ticks=bwd_tabs.n_ticks,
        recompute=remat, schedule=schedule, batch_axis=batch_axis,
    )
    if remat:
        def _bwd_body(p, xx, dy):
            return _engine_bwd_local(p, xx, None, dy, **bwd_kwargs)

        bwd_shard = shard_map(
            _bwd_body,
            mesh=mesh,
            in_specs=(p_specs, x_spec, x_spec),
            out_specs=(p_specs, x_spec),
            check_vma=False,
        )
    else:
        def _bwd_body(p, xx, stash, dy):
            return _engine_bwd_local(p, xx, stash, dy, **bwd_kwargs)

        bwd_shard = shard_map(
            _bwd_body,
            mesh=mesh,
            in_specs=(p_specs, x_spec, stash_spec, x_spec),
            out_specs=(p_specs, x_spec),
            check_vma=False,
        )

    @jax.custom_vjp
    def _engine(p, xx):
        return fwd_shard(p, xx)[0]

    if remat:
        def _engine_fwd(p, xx):
            (y,) = fwd_shard(p, xx)
            return y, (p, xx)

        def _engine_bwd(res, dy):
            p, xx = res
            return bwd_shard(p, xx, dy)
    else:
        def _engine_fwd(p, xx):
            y, stash = fwd_shard(p, xx)
            return y, (p, xx, stash)

        def _engine_bwd(res, dy):
            p, xx, stash = res
            return bwd_shard(p, xx, stash, dy)

    _engine.defvjp(_engine_fwd, _engine_bwd)
    out = _engine(p_sched, xm)
    return out.reshape((batch,) + out.shape[2:])
