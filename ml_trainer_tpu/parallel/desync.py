"""Replica-desync detection — the framework's "race detector".

The reference has no sanitizer story (SURVEY.md §5: determinism is one
``torch.manual_seed`` call; DDP desync goes unnoticed until loss diverges).
A JAX program is deterministic by construction, so the remaining failure
mode is cross-host divergence: a host stepping with different data/config
silently corrupts the replicated state.  ``param_fingerprint`` reduces the
parameter tree to one scalar; ``check_desync`` compares it across hosts via
a broadcast from host 0 and raises on mismatch — cheap enough to run every
epoch (or every N steps via the Trainer's ``desync_every_steps`` knob).

Forensics (docs/observability.md, "Distributed"): before raising,
``check_desync`` publishes this host's fingerprint into the metrics
registry (``cluster_param_fingerprint{host=...}``), bumps
``cluster_desync_events_total``, and records + dumps a flight-recorder
``desync`` event naming the diverging host and step — so the post-mortem
starts from WHICH host diverged and WHEN, not from a bare RuntimeError
in one rank's logs.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np


def param_fingerprint(tree) -> float:
    """Cheap order-stable scalar digest of a pytree of arrays.

    Computed from each host's LOCAL device buffers (``addressable_data``) —
    on a multi-host mesh the global array is not addressable, and reading
    the local replica is exactly what desync detection needs: if one host's
    copy of replicated state silently diverged, its local buffer (and only
    its) differs.  Intentionally model-sharded leaves (TP/FSDP rules) are
    skipped: their per-host shards differ by design.
    """
    leaves = jax.tree.leaves(tree)
    acc = 0.0
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and not sharding.is_fully_replicated:
                continue
            x = np.asarray(leaf.addressable_data(0), dtype=np.float32)
        else:
            x = np.asarray(leaf, dtype=np.float32)
        acc += (i + 1) * float(np.sum(x * x)) + float(np.sum(x))
    return acc


def check_desync(tree, atol: float = 1e-4, *, step: Optional[int] = None,
                 registry=None, flight=None, dump: bool = True) -> None:
    """Raise RuntimeError when any host's params diverge from host 0's.

    No-op in single-process runs.  The comparison crosses hosts with a
    broadcast_one_to_all (DCN), so the cost is one scalar per call.

    Every call publishes this host's fingerprint as
    ``cluster_param_fingerprint{host=<i>}``; on mismatch the diverging
    host records a ``desync`` flight event (and dumps the ring, unless
    ``dump=False``) naming itself, ``step``, and both fingerprints —
    BEFORE the RuntimeError unwinds the process.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    mine = param_fingerprint(tree)
    pid = jax.process_index()
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        r.gauge(
            "cluster_param_fingerprint",
            "per-host replicated-parameter fingerprint (desync detector)",
            ("host",),
        ).labels(host=pid).set(mine)
    except Exception:
        r = None  # forensics must never break the check itself
    host0 = float(
        multihost_utils.broadcast_one_to_all(np.asarray(mine, np.float64))
    )
    if abs(mine - host0) > atol * max(1.0, abs(host0)):
        try:
            if r is not None:
                r.counter(
                    "cluster_desync_events_total",
                    "cross-host fingerprint divergences detected",
                ).inc()
            from ml_trainer_tpu.telemetry.flight import get_recorder

            fr = flight if flight is not None else get_recorder()
            info = {
                "host": int(pid),
                "step": int(step) if step is not None else None,
                "fingerprint": mine,
                "host0_fingerprint": host0,
            }
            fr.record("desync", **info)
            if dump:
                fr.dump("desync", **info)
        except Exception:
            pass
        raise RuntimeError(
            f"replica desync detected: host {pid} fingerprint "
            f"{mine!r} != host 0 fingerprint {host0!r}"
            + (f" (step {step})" if step is not None else "")
        )
