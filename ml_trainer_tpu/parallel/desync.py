"""Replica-desync detection — the framework's "race detector".

The reference has no sanitizer story (SURVEY.md §5: determinism is one
``torch.manual_seed`` call; DDP desync goes unnoticed until loss diverges).
A JAX program is deterministic by construction, so the remaining failure
mode is cross-host divergence: a host stepping with different data/config
silently corrupts the replicated state.  ``param_fingerprint`` reduces the
parameter tree to one scalar; ``check_desync`` compares it across hosts via
a broadcast from host 0 and raises on mismatch — cheap enough to run every
epoch.
"""

from __future__ import annotations

import jax
import numpy as np


def param_fingerprint(tree) -> float:
    """Cheap order-stable scalar digest of a pytree of arrays.

    Computed from each host's LOCAL device buffers (``addressable_data``) —
    on a multi-host mesh the global array is not addressable, and reading
    the local replica is exactly what desync detection needs: if one host's
    copy of replicated state silently diverged, its local buffer (and only
    its) differs.  Intentionally model-sharded leaves (TP/FSDP rules) are
    skipped: their per-host shards differ by design.
    """
    leaves = jax.tree.leaves(tree)
    acc = 0.0
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and not sharding.is_fully_replicated:
                continue
            x = np.asarray(leaf.addressable_data(0), dtype=np.float32)
        else:
            x = np.asarray(leaf, dtype=np.float32)
        acc += (i + 1) * float(np.sum(x * x)) + float(np.sum(x))
    return acc


def check_desync(tree, atol: float = 1e-4) -> None:
    """Raise RuntimeError when any host's params diverge from host 0's.

    No-op in single-process runs.  The comparison crosses hosts with a
    broadcast_one_to_all (DCN), so the cost is one scalar per call.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    mine = param_fingerprint(tree)
    host0 = float(
        multihost_utils.broadcast_one_to_all(np.asarray(mine, np.float64))
    )
    if abs(mine - host0) > atol * max(1.0, abs(host0)):
        raise RuntimeError(
            f"replica desync detected: host {jax.process_index()} fingerprint "
            f"{mine!r} != host 0 fingerprint {host0!r}"
        )
