"""Ulysses-style sequence parallelism — all-to-all head/sequence exchange.

The second of the two standard long-context strategies (alongside
``parallel/ring.py``; the reference has neither, SURVEY.md §5): with the
sequence dim sharded over ``n`` devices, one ``all_to_all`` re-partitions
[B, H, S/n, D] into [B, H/n, S, D] — every device then holds the FULL
sequence for its slice of heads, runs an ordinary (flash-able) attention
locally with no cross-device math in the softmax, and a second
``all_to_all`` restores the sequence sharding.

Trade-off vs ring: two bulk a2a collectives (ICI-friendly) instead of n
pipelined ppermute hops, and the local attention is an ordinary full-
sequence call — it dispatches through ``ops.attention`` in 'auto' mode, so
on TPU the Pallas flash kernel applies (O(S) local memory) and elsewhere
the XLA path runs.  Requires the sequence-axis size to divide the head
count (``H % n == 0``).

Built on ``shard_map`` like the ring, so it composes with data/tensor
sharding on the other mesh axes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from ml_trainer_tpu.parallel.collectives import all_to_all
from jax.sharding import Mesh, PartitionSpec as P
from ml_trainer_tpu.parallel.compat import shard_map


def _ulysses_local(q, k, v, *, axis_name, causal, scale, attend):
    """Per-shard body.  q/k/v: [B, H, S_local, D] -> same shape."""
    # Scatter heads, gather sequence: [B, H, S/n, D] -> [B, H/n, S, D].
    def a2a_fwd(x):
        return all_to_all(x, axis_name, split_axis=1, concat_axis=2)

    def a2a_bwd(x):
        return all_to_all(x, axis_name, split_axis=2, concat_axis=1)

    qg, kg, vg = a2a_fwd(q), a2a_fwd(k), a2a_fwd(v)
    # Full sequence present locally: plain causal attention, no offsets.
    out = attend(qg, kg, vg, causal=causal, scale=scale)
    return a2a_bwd(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """Sequence-parallel attention over [B, H, S, D] arrays whose S dim is
    (or will be) sharded over ``mesh[axis_name]``; same contract as
    ``ring_attention``.  The sequence-axis size must divide the head
    count."""
    from ml_trainer_tpu.ops import attention as attention_ops

    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = mesh.shape[axis_name]
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"ulysses needs heads % sequence-axis == 0, got H={h}, n={n}"
        )
    if batch_axis is not None and batch_axis not in mesh.axis_names:
        batch_axis = None

    def attend(qg, kg, vg, *, causal, scale):
        # 'auto' picks the Pallas flash kernel on TPU when shapes allow,
        # the XLA path otherwise — the a2a layout makes this an ordinary
        # single-device attention call.
        return attention_ops.attention(
            qg, kg, vg, causal=causal, scale=scale, implementation="auto"
        )

    spec = P(batch_axis, None, axis_name, None)
    fn = shard_map(
        functools.partial(
            _ulysses_local, axis_name=axis_name, causal=causal, scale=scale,
            attend=attend,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
