"""jax version compat shims for the parallel package.

Three drifts between jax 0.4.x and newer jax broke this repo:

* ``shard_map`` moved from ``jax.experimental.shard_map`` to the
  top-level ``jax`` namespace;
* its replication-check kwarg was renamed ``check_rep`` → ``check_vma``;
* ``lax.axis_size`` (the named-axis size inside shard_map/pmap bodies)
  does not exist on 0.4.x — ``psum(1, axis)`` is the portable spelling.

Everything in this repo imports ``shard_map`` from here, written against
the NEW spelling (``check_vma=``); on an old jax the wrapper maps the
kwarg back down.

    from ml_trainer_tpu.parallel.compat import shard_map
"""

from __future__ import annotations

import functools
import inspect

try:  # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


@functools.wraps(_shard_map)
def shard_map(*args, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)


if hasattr(__import__("jax").lax, "axis_size"):
    from jax.lax import axis_size
else:
    def axis_size(axis_name) -> int:
        """Size of a named mesh axis from inside a shard_map/pmap body.
        jax 0.4.x fallback: ``psum`` of a literal constant-folds to a
        plain Python int, so callers can keep building static artifacts
        (permutation lists, loop bounds) from it."""
        from jax import lax

        return lax.psum(1, axis_name)


__all__ = ["shard_map", "axis_size"]
