"""Collective-comms accounting — bytes per collective, from static shapes.

A sharding bug usually announces itself as a comms/compute ratio that is
wildly off (MegaScale-style fleet forensics: a layer all-gathering weights
it should have kept sharded doubles the step's ICI traffic long before it
shows up in loss curves).  XLA knows the traffic but buries it in HLO cost
analysis; this module makes the explicit-collective layer self-accounting
instead: every wrapper in ``parallel/collectives.py`` (and the pipeline
schedule's hops) reports its analytic byte count HERE, **at trace time**.

Trace-time discipline (the same one the on-device step stats follow):

* shapes, dtypes and mesh-axis sizes are all static during tracing, so the
  byte math runs in plain host Python exactly once per compiled program —
  zero runtime cost, zero extra compiled programs, the executed HLO is
  byte-identical to the unaccounted call;
* accounting can therefore never desynchronize from the program: a
  retrace (new shapes) re-records automatically;
* the recorded number is *bytes moved per execution* of the traced
  program — for a train step that compiles once and runs every step, that
  IS bytes-per-step.

Per-op analytic formulas (``n`` = collective axis size, ``size`` = bytes
of one participant's input):

=================  ==========================  =============================
op                 bytes per participant       rationale
=================  ==========================  =============================
psum / pmean       ``2 * size * (n-1)/n``      ring all-reduce
                                               (reduce-scatter + all-gather)
all_gather         ``size * (n-1)``            receives every other shard
reduce_scatter     ``size * (n-1)/n``          ring reduce-scatter
ppermute           ``size``                    one neighbour hop
all_to_all         ``size * (n-1)/n``          keeps 1/n locally
=================  ==========================  =============================

Recording never raises: a collective traced outside a mapped context (no
axis size to read) or with exotic leaves simply skips accounting — the
program always comes first.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

import numpy as np

_lock = threading.Lock()
_bytes: Dict[str, float] = {}
_calls: Dict[str, int] = {}
# Per-bucket breakdown for bucketed collectives (the overlapped
# reduce-scatter backward issues one collective per gradient bucket;
# attributing bytes per bucket is how a mis-sized bucket plan shows up
# on /metrics).  Keyed (op, bucket-label); mirrored into the registry as
# ``comm_bucket_bytes_total{op=,bucket=}``.
_bucket_bytes: Dict[Tuple[str, str], float] = {}
# Per-hop breakdown for the pipeline schedules (the same view-not-ledger
# pattern as buckets, one level up): forward activation hops, backward
# cotangent hops, the recompute feed, and the output/input-grad
# broadcasts are separately attributed per schedule, so a schedule that
# moves more bytes than its tick table promises shows up on /metrics.
# Keyed (schedule, hop-label); mirrored as
# ``comm_hop_bytes_total{schedule=,hop=}``.
_hop_bytes: Dict[Tuple[str, str], float] = {}
_hop_calls: Dict[Tuple[str, str], int] = {}

_FACTORS = {
    "psum": lambda size, n: 2.0 * size * (n - 1) / n,
    "pmean": lambda size, n: 2.0 * size * (n - 1) / n,
    "all_gather": lambda size, n: float(size) * (n - 1),
    "reduce_scatter": lambda size, n: float(size) * (n - 1) / n,
    "ppermute": lambda size, n: float(size),
    "all_to_all": lambda size, n: float(size) * (n - 1) / n,
}


def collective_bytes(op: str, size_bytes: int, axis_n: int) -> float:
    """Analytic bytes one participant moves for ``op`` over an axis of
    ``axis_n`` devices, given ``size_bytes`` of local input."""
    if op not in _FACTORS:
        raise ValueError(f"unknown collective op {op!r}")
    if axis_n <= 1:
        return 0.0
    return _FACTORS[op](float(size_bytes), int(axis_n))


def _tree_bytes(x) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(x):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod([int(d) for d in shape], initial=1)) * int(
            np.dtype(dtype).itemsize
        )
    return total


def record_collective(op: str, n_bytes: float, calls: int = 1,
                      bucket: str = None) -> None:
    """Accumulate ``n_bytes`` against ``op`` and mirror the running totals
    into the default registry (``comm_bytes_total{op=...}`` /
    ``comm_calls_total{op=...}`` gauges — gauges, not counters, because
    ``reset_comm_stats`` legally zeroes them between bench legs).  With
    ``bucket`` set the bytes additionally land in the per-bucket
    breakdown (``comm_bucket_bytes_total{op=,bucket=}``) — the op totals
    always include bucketed traffic, so the breakdown is a view, not a
    second ledger."""
    bb = None
    with _lock:
        _bytes[op] = _bytes.get(op, 0.0) + float(n_bytes)
        _calls[op] = _calls.get(op, 0) + int(calls)
        b, c = _bytes[op], _calls[op]
        if bucket is not None:
            key = (op, str(bucket))
            _bucket_bytes[key] = _bucket_bytes.get(key, 0.0) + float(n_bytes)
            bb = _bucket_bytes[key]
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = default_registry()
        r.gauge(
            "comm_bytes_total",
            "analytic bytes moved by explicit collectives (trace-time)",
            ("op",),
        ).labels(op=op).set(b)
        r.gauge(
            "comm_calls_total",
            "traced explicit-collective call sites",
            ("op",),
        ).labels(op=op).set(c)
        if bb is not None:
            r.gauge(
                "comm_bucket_bytes_total",
                "per-bucket analytic bytes of bucketed collectives "
                "(the overlapped reduce-scatter backward)",
                ("op", "bucket"),
            ).labels(op=op, bucket=str(bucket)).set(bb)
    except Exception:  # registry trouble must never break a trace
        pass


def record_hop(schedule: str, hop: str, n_bytes: float,
               calls: int = 1) -> None:
    """Accumulate ``n_bytes`` against one pipeline hop kind (``fwd`` /
    ``bwd`` / ``fwd_recompute`` / ``output_broadcast`` /
    ``grad_input_broadcast``) for ``schedule``, and mirror the running
    total into the registry as ``comm_hop_bytes_total{schedule=,hop=}``
    (a gauge, like the other comm mirrors, because ``reset_comm_stats``
    legally zeroes it between bench legs).  The hop breakdown is a VIEW
    beside the per-op totals — pipeline call sites record the same
    bytes into both, so op totals already include hop traffic."""
    key = (str(schedule), str(hop))
    with _lock:
        _hop_bytes[key] = _hop_bytes.get(key, 0.0) + float(n_bytes)
        _hop_calls[key] = _hop_calls.get(key, 0) + int(calls)
        b, c = _hop_bytes[key], _hop_calls[key]
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = default_registry()
        r.gauge(
            "comm_hop_bytes_total",
            "analytic bytes moved per pipeline-schedule hop kind "
            "(trace-time)",
            ("schedule", "hop"),
        ).labels(schedule=key[0], hop=key[1]).set(b)
        r.gauge(
            "comm_hop_calls_total",
            "executed hop count per pipeline-schedule hop kind",
            ("schedule", "hop"),
        ).labels(schedule=key[0], hop=key[1]).set(c)
    except Exception:  # registry trouble must never break a trace
        pass


def account(op: str, x, axis, times: int = 1, bucket: str = None,
            hop: Tuple[str, str] = None) -> None:
    """Trace-time accounting hook: compute the analytic byte count of one
    ``op`` over ``axis`` for input ``x`` and record it ``times`` times.
    ``times`` exists for collectives traced once inside a ``scan`` /
    ``fori_loop`` body but executed on every iteration — the loop owner
    tops the count up with the static trip count (ring attention rotates
    K/V ``n`` times; the pipeline hops ``S+M-1`` ticks).  ``hop`` is an
    optional ``(schedule, hop_kind)`` pair that additionally lands the
    same bytes in the per-hop pipeline breakdown (``record_hop``).
    Best-effort by design — any failure (untracked axis, abstract
    leaves) is swallowed so the wrapped collective always executes
    unchanged."""
    try:
        from ml_trainer_tpu.parallel.compat import axis_size as _axis_size

        if isinstance(axis, (tuple, list)):
            n = 1
            for a in axis:
                n *= int(_axis_size(a))
        else:
            n = int(_axis_size(axis))
        n_bytes = collective_bytes(op, _tree_bytes(x), n) * int(times)
        record_collective(op, n_bytes, calls=int(times), bucket=bucket)
        if hop is not None:
            record_hop(hop[0], hop[1], n_bytes, calls=int(times))
    except Exception:
        pass


def comm_bytes() -> Dict[str, float]:
    """Per-op cumulative analytic bytes (copy)."""
    with _lock:
        return dict(_bytes)


def comm_calls() -> Dict[str, int]:
    with _lock:
        return dict(_calls)


def comm_bucket_bytes() -> Dict[str, Dict[str, float]]:
    """Per-bucket cumulative analytic bytes, grouped by op:
    ``{op: {bucket: bytes}}`` (copy; empty when nothing bucketed ran)."""
    with _lock:
        out: Dict[str, Dict[str, float]] = {}
        for (op, bucket), b in _bucket_bytes.items():
            out.setdefault(op, {})[bucket] = b
        return out


def comm_hop_bytes() -> Dict[str, Dict[str, float]]:
    """Per-hop cumulative analytic bytes of the pipeline schedules,
    grouped by schedule: ``{schedule: {hop: bytes}}`` (copy; empty when
    no pipeline ran)."""
    with _lock:
        out: Dict[str, Dict[str, float]] = {}
        for (schedule, hop), b in _hop_bytes.items():
            out.setdefault(schedule, {})[hop] = b
        return out


def comm_hop_calls() -> Dict[str, Dict[str, int]]:
    """Executed hop counts, same grouping as :func:`comm_hop_bytes`."""
    with _lock:
        out: Dict[str, Dict[str, int]] = {}
        for (schedule, hop), c in _hop_calls.items():
            out.setdefault(schedule, {})[hop] = c
        return out


def comm_bytes_total() -> float:
    """Total analytic collective bytes across all ops."""
    with _lock:
        return float(sum(_bytes.values()))


def comm_delta(since: Dict[str, float]) -> Dict[str, float]:
    """Per-op bytes recorded since a previous ``comm_bytes()`` snapshot
    (ops with zero delta omitted)."""
    now = comm_bytes()
    out = {}
    for op, b in now.items():
        d = b - since.get(op, 0.0)
        if d > 0:
            out[op] = d
    return out


def reset_comm_stats() -> None:
    """Zero the accumulators (and their registry mirrors) — bench legs and
    the multichip dryrun reset between measurements."""
    with _lock:
        ops: Tuple[str, ...] = tuple(_bytes)
        buckets = tuple(_bucket_bytes)
        hops = tuple(_hop_bytes)
        _bytes.clear()
        _calls.clear()
        _bucket_bytes.clear()
        _hop_bytes.clear()
        _hop_calls.clear()
    try:
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = default_registry()
        for op in ops:
            r.gauge("comm_bytes_total", "", ("op",)).labels(op=op).set(0.0)
            r.gauge("comm_calls_total", "", ("op",)).labels(op=op).set(0.0)
        for op, bucket in buckets:
            r.gauge(
                "comm_bucket_bytes_total", "", ("op", "bucket")
            ).labels(op=op, bucket=bucket).set(0.0)
        for schedule, hop in hops:
            r.gauge(
                "comm_hop_bytes_total", "", ("schedule", "hop")
            ).labels(schedule=schedule, hop=hop).set(0.0)
            r.gauge(
                "comm_hop_calls_total", "", ("schedule", "hop")
            ).labels(schedule=schedule, hop=hop).set(0.0)
    except Exception:
        pass
