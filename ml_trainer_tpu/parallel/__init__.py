"""Mesh-native parallelism: device meshes, sharding rules, distributed
rendezvous and collectives.

This package is the TPU-native replacement for the reference's entire
distributed stack — ``torch.distributed`` + SMDDP backend registration +
DistributedDataParallel (ref: src/trainer.py:43-44, 59-64, 97-101,
152-158).  Instead of a process-group API with explicit all-reduce, the
framework builds a ``jax.sharding.Mesh`` over the slice and lets XLA insert
the collectives implied by sharding annotations; gradient averaging is the
``psum`` the compiler schedules inside the step (overlapped with backward
compute the way DDP's bucketed reducer overlaps it, but fused by the XLA
latency-hiding scheduler rather than hand-written buckets).
"""

from ml_trainer_tpu.parallel.mesh import (
    create_hybrid_mesh,
    create_mesh,
    default_mesh,
    mesh_shape_for,
)
from ml_trainer_tpu.parallel.distributed import (
    initialize_distributed,
    process_count,
    process_index,
)
from ml_trainer_tpu.parallel.sharding import (
    batch_sharding,
    bucketed_all_gather,
    bucketed_reduce_scatter,
    fit_sharding_to_rank,
    GradBucketPlan,
    place_tree,
    plan_grad_buckets,
    replicated,
    respec_sharding,
    shard_opt_state,
    shard_params,
    zero1_opt_shardings,
    logical_to_shardings,
)
from ml_trainer_tpu.parallel import collectives
from ml_trainer_tpu.parallel.desync import check_desync, param_fingerprint
from ml_trainer_tpu.parallel.pipeline import (
    PIPELINE_SCHEDULES,
    pipeline_apply,
    pipeline_schedule_info,
    stack_stage_params,
)
from ml_trainer_tpu.parallel.ring import ring_attention
from ml_trainer_tpu.parallel.ulysses import ulysses_attention
from ml_trainer_tpu.parallel.tp_rules import (
    FSDP_RULES,
    TRANSFORMER_TP_RULES,
    rules_for,
)

__all__ = [
    "check_desync",
    "param_fingerprint",
    "PIPELINE_SCHEDULES",
    "pipeline_apply",
    "pipeline_schedule_info",
    "stack_stage_params",
    "ring_attention",
    "ulysses_attention",
    "FSDP_RULES",
    "TRANSFORMER_TP_RULES",
    "rules_for",
    "create_hybrid_mesh",
    "create_mesh",
    "default_mesh",
    "mesh_shape_for",
    "initialize_distributed",
    "process_count",
    "process_index",
    "batch_sharding",
    "bucketed_all_gather",
    "bucketed_reduce_scatter",
    "fit_sharding_to_rank",
    "GradBucketPlan",
    "place_tree",
    "plan_grad_buckets",
    "replicated",
    "respec_sharding",
    "shard_opt_state",
    "shard_params",
    "zero1_opt_shardings",
    "logical_to_shardings",
    "collectives",
]
