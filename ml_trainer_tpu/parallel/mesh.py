"""Device mesh construction.

The mesh is the framework's world: every parallelism axis — data, fsdp,
tensor, sequence, expert — is a named mesh dimension, and all collectives
ride it.  This replaces the reference's flat ``world_size``/``rank``
process-group model (ref: src/trainer.py:59-64): where DDP sees N equal
ranks, the mesh distinguishes ICI-adjacent axes (fast, for
tensor/sequence-parallel collectives) from DCN-spanning axes (slower,
for data parallelism across hosts) by construction, because
``jax.devices()`` orders devices host-major.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: outermost (DCN-friendly) to innermost (ICI-friendly).
AXIS_ORDER = ("data", "fsdp", "stage", "expert", "sequence", "tensor")


def mesh_shape_for(
    n_devices: int,
    *,
    tensor: int = 1,
    sequence: int = 1,
    expert: int = 1,
    fsdp: int = 1,
    stage: int = 1,
) -> Dict[str, int]:
    """Fill the data axis with whatever the model axes don't use."""
    model = tensor * sequence * expert * fsdp * stage
    if n_devices % model:
        raise ValueError(
            f"{n_devices} devices not divisible by model-parallel factor {model}"
        )
    return {
        "data": n_devices // model,
        "fsdp": fsdp,
        "stage": stage,
        "expert": expert,
        "sequence": sequence,
        "tensor": tensor,
    }


def create_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh.  Default: 1-D ``data`` mesh over every device —
    pure data parallelism, the reference's only strategy (SURVEY.md §2C)."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = {"data": len(devices)}
    axes = [a for a in AXIS_ORDER if shape.get(a, 1) > 1] or ["data"]
    dims = [shape.get(a, 1) for a in axes]
    if int(np.prod(dims)) != len(devices):
        raise ValueError(f"mesh shape {shape} does not cover {len(devices)} devices")
    return Mesh(np.asarray(devices).reshape(dims), axis_names=tuple(axes))


def default_mesh() -> Mesh:
    return create_mesh()


def _split_dcn(axes, dims, dcn_axes, num_slices):
    """Factor the slice count out of the mesh dims.

    Slices are factored greedily across the dcn axes, outermost first:
    each dcn axis absorbs ``gcd(axis_size, slices_left)`` slices and
    keeps its intra-slice remainder on ICI — e.g. 2 slices x 16 chips
    with axes data=8, tensor=4 becomes dcn data=2, ici data=4, ici
    tensor=4; and 4 slices with data=2, fsdp=2 and
    dcn_axes=('data','fsdp') becomes dcn (2, 2), ici (1, 1).
    (mesh_utils requires prod(dcn_mesh_shape) == num_slices exactly.)
    Returns (ici_dims, dcn_dims), elementwise product == dims."""
    import math

    ici, dcn = [], []
    slices_left = num_slices
    for a, size in zip(axes, dims):
        if a in dcn_axes and slices_left > 1:
            g = math.gcd(size, slices_left)
            dcn.append(g)
            ici.append(size // g)
            slices_left //= g
        else:
            dcn.append(1)
            ici.append(size)
    if slices_left > 1:
        raise ValueError(
            f"mesh dims {dict(zip(axes, dims))} cannot span {num_slices} "
            f"slices: the axes in dcn_axes={tuple(dcn_axes)} only absorb "
            f"{num_slices // slices_left} of them"
        )
    return ici, dcn


def create_hybrid_mesh(
    shape: Dict[str, int],
    *,
    dcn_axes: Sequence[str] = ("data",),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: DCN-spanning axes get whole slices, ICI axes stay
    inside a slice.

    On a multi-slice TPU deployment (N pods joined over data-center
    network), collectives on an axis that crosses slice boundaries run at
    DCN bandwidth — orders of magnitude below ICI.  ``create_mesh``'s
    host-major reshape already tends that way, but only
    ``mesh_utils.create_hybrid_device_mesh`` consults the real slice
    topology (it groups devices by ``slice_index``).  ``dcn_axes`` names
    the axes allowed to cross slices (default: data parallelism — the
    standard multi-slice recipe: gradient all-reduce tolerates DCN
    latency, tensor/sequence/expert collectives do not).

    Single-slice processes (including the CPU-simulated mesh, which has
    no slice_index) fall back to ``create_mesh`` — same axes, same
    semantics, so code written against the hybrid helper rehearses
    unchanged on the test mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if num_slices <= 1:
        return create_mesh(shape, devices)
    from jax.experimental import mesh_utils

    axes = [a for a in AXIS_ORDER if shape.get(a, 1) > 1] or ["data"]
    dims = [shape.get(a, 1) for a in axes]
    ici, dcn = _split_dcn(axes, dims, dcn_axes, num_slices)
    mesh_devices = mesh_utils.create_hybrid_device_mesh(
        ici, dcn, devices=devices,
    )
    if list(mesh_devices.shape) != dims:
        # Never reshape here: a raw C-order reshape would scramble the
        # slice-aware placement this function exists to produce.
        raise ValueError(
            f"hybrid device mesh came back {mesh_devices.shape}, "
            f"expected {tuple(dims)}"
        )
    return Mesh(mesh_devices, axis_names=tuple(axes))
