"""Device mesh construction.

The mesh is the framework's world: every parallelism axis — data, fsdp,
tensor, sequence, expert — is a named mesh dimension, and all collectives
ride it.  This replaces the reference's flat ``world_size``/``rank``
process-group model (ref: src/trainer.py:59-64): where DDP sees N equal
ranks, the mesh distinguishes ICI-adjacent axes (fast, for
tensor/sequence-parallel collectives) from DCN-spanning axes (slower,
for data parallelism across hosts) by construction, because
``jax.devices()`` orders devices host-major.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: outermost (DCN-friendly) to innermost (ICI-friendly).
AXIS_ORDER = ("data", "fsdp", "stage", "expert", "sequence", "tensor")


def mesh_shape_for(
    n_devices: int,
    *,
    tensor: int = 1,
    sequence: int = 1,
    expert: int = 1,
    fsdp: int = 1,
    stage: int = 1,
) -> Dict[str, int]:
    """Fill the data axis with whatever the model axes don't use."""
    model = tensor * sequence * expert * fsdp * stage
    if n_devices % model:
        raise ValueError(
            f"{n_devices} devices not divisible by model-parallel factor {model}"
        )
    return {
        "data": n_devices // model,
        "fsdp": fsdp,
        "stage": stage,
        "expert": expert,
        "sequence": sequence,
        "tensor": tensor,
    }


def create_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh.  Default: 1-D ``data`` mesh over every device —
    pure data parallelism, the reference's only strategy (SURVEY.md §2C)."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = {"data": len(devices)}
    axes = [a for a in AXIS_ORDER if shape.get(a, 1) > 1] or ["data"]
    dims = [shape.get(a, 1) for a in axes]
    if int(np.prod(dims)) != len(devices):
        raise ValueError(f"mesh shape {shape} does not cover {len(devices)} devices")
    return Mesh(np.asarray(devices).reshape(dims), axis_names=tuple(axes))


def default_mesh() -> Mesh:
    return create_mesh()
