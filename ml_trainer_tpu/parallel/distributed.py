"""Multi-host rendezvous — the ``dist.init_process_group`` analog.

The reference rendezvous is ``dist.init_process_group(backend='smddp')``
(ref: src/trainer.py:59), with backend strings naming collective libraries
(SMDDP/NCCL/gloo, ref: main.py:72-73).  The TPU-native equivalent is
``jax.distributed.initialize()``: each host joins a coordination service,
after which ``jax.devices()`` spans the whole slice/pod and a single mesh
covers ICI and DCN uniformly.  Backend strings are kept for config parity
but select behaviour, not a library: ``tpu`` expects real TPU hosts (env
auto-detection), ``cpu`` is the simulated-mesh path used by tests —
the analog of the reference's gloo/local_gpu staging story (SURVEY.md §4).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_INITIALIZED = False


def initialize_distributed(
    backend: str = "tpu",
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Idempotent multi-host init.  Single-process runs are a no-op, exactly
    as the reference skips ``init_process_group`` when ``is_parallel`` is
    False (ref: src/trainer.py:57-71)."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    if backend == "cpu":
        # Simulated mesh on the host platform; no rendezvous needed.
        _INITIALIZED = True
        return
    explicit = coordinator_address is not None
    auto = any(
        v in os.environ
        for v in ("COORDINATOR_ADDRESS", "CLOUD_TPU_TASK_ID", "TPU_WORKER_ID")
    )
    if explicit or auto:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    _INITIALIZED = True


def process_count() -> int:
    """World size analog (ref: src/trainer.py:60-63 ``dist.get_world_size``),
    counted in hosts — intra-host parallelism is the mesh's job."""
    return jax.process_count()


def process_index() -> int:
    """Rank analog (ref: src/trainer.py:61 ``dist.get_rank``)."""
    return jax.process_index()


def is_primary() -> bool:
    """Rank-0 check used for checkpoint/history writes
    (ref: src/trainer.py:252-254)."""
    return jax.process_index() == 0
