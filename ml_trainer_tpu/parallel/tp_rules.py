"""Tensor/FSDP PartitionSpec rule sets for the model zoo.

No analog in the reference (data parallelism is its only strategy,
SURVEY.md §2C).  Rules are (regex over param path, PartitionSpec) pairs
consumed by ``parallel.sharding.logical_to_shardings``: they place the big
matmuls of the transformer blocks in the Megatron arrangement — qkv/mlp-in
column-parallel, proj/mlp-out row-parallel — and shard embeddings over the
vocab dim.  Under ``jax.jit`` these are *placements*, not programs: XLA
propagates them through the step and inserts the matching all-reduces over
the ``tensor`` axis (ICI), which is exactly how the reference's
NCCL-all-reduce role is meant to be filled on TPU.

Axes referenced here that a mesh doesn't have are dropped automatically
(see sharding.logical_to_shardings), so one rule set serves dp-only,
dp×tp and dp×fsdp×tp meshes.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

# Megatron-style tensor parallelism for the shared transformer blocks
# (models/layers.py) + embeddings.  Order matters: first match wins.
TRANSFORMER_TP_RULES = [
    # attention: qkv column-parallel, output projection row-parallel
    (r"attn/qkv/kernel$", P(None, "tensor")),
    (r"attn/qkv/bias$", P("tensor")),
    (r"attn/proj/kernel$", P("tensor", None)),
    # mlp: in column-parallel, out row-parallel
    (r"mlp/fc_in/kernel$", P(None, "tensor")),
    (r"mlp/fc_in/bias$", P("tensor")),
    (r"mlp/fc_out/kernel$", P("tensor", None)),
    # llama family (models/llama.py): separate q/k/v projections
    # column-parallel (GQA caveat: the tensor degree should divide
    # num_kv_heads, or the narrow k/v kernels split mid-head), SwiGLU
    # gate/up column-parallel, down row-parallel, untied lm_head
    # column-parallel over the vocab dim (32000-class vocabs divide
    # cleanly, unlike GPT-2's 50257).
    (r"attn/(q|k|v)/kernel$", P(None, "tensor")),
    (r"block\d+/(gate|up)/kernel$", P(None, "tensor")),
    (r"block\d+/down/kernel$", P("tensor", None)),
    (r"lm_head$", P(None, "tensor")),
    # embeddings: shard the FEATURE dim.  Vocab-dim (Megatron-row) sharding
    # would need the vocab padded to a multiple of the tensor degree —
    # GPT-2's 50257 is not — so the embed dim (a multiple of the head count)
    # is the always-divisible choice; the tied LM head then reduces over the
    # sharded feature dim with one psum.  (GPT-2's pos_embed is a raw
    # [1, L, E] param, BERT's pos/seg are nn.Embed tables — both covered.)
    (r"(tok_embed|pos_embed|seg_embed)/embedding$", P(None, "tensor")),
    (r"pos_embed$", P(None, None, "tensor")),
    # everything else (layernorms, biases, heads) replicates by default
]

# FSDP: shard every ≥2-D kernel's first dim over the fsdp axis; XLA turns
# the placements into all-gather-on-use / reduce-scatter-on-grad.
# Embedding tables shard the feature dim (vocab sizes like GPT-2's 50257
# rarely divide the axis; the feature dim always does).
FSDP_RULES = [
    (r"kernel$", P("fsdp", None)),
    (r"embedding$", P(None, "fsdp")),
    # llama's untied head is a raw [E, V] param (no /kernel suffix).
    (r"lm_head$", P("fsdp", None)),
]

# Expert parallelism: the stacked MoE expert weights [E, ...] shard their
# leading (expert) dim over the expert mesh axis; the router replicates.
# XLA turns the placement into the token dispatch/combine all-to-alls
# (models/moe.py uses the dense GShard einsum formulation).
EP_RULES = [
    (r"mlp/wi$", P("expert", None, None)),
    (r"mlp/wo$", P("expert", None, None)),
]

# Pipeline parallelism: the stacked per-stage trunk params [n_stages, ...]
# of models.gpt2.GPT2Pipelined shard their leading (stage) dim; embedding /
# head / final-LN replicate (they run outside the pipeline).  The pipeline
# schedule itself lives in parallel.pipeline (shard_map + ppermute).
PP_RULES = [
    (r"(^|/)blocks/", P("stage")),
]


def validate_tp_mesh(model, mesh) -> None:
    """Reject meshes whose ``tensor`` degree would split attention heads.

    The llama GQA rule column-shards the narrow k/v kernels
    ([E, Hkv*D]); a tensor degree that does not divide ``num_kv_heads``
    (e.g. tensor=8 over 4 kv heads) splits a head across shards — XLA
    accepts the layout but the per-shard attention math is no longer
    head-aligned.  Raise here, where the model config and the mesh first
    meet, instead of relying on a comment (ADVICE r4)."""
    axis = dict(zip(mesh.axis_names, mesh.devices.shape)).get("tensor", 1)
    if axis <= 1:
        return
    for attr in ("num_kv_heads", "num_heads"):
        n = getattr(model, attr, None)
        if n is not None and n % axis:
            raise ValueError(
                f"mesh tensor axis ({axis}) must divide {attr} ({n}) — "
                f"a {axis}-way split of {n} heads shards mid-head. "
                "Use a smaller tensor degree or a model with more "
                "(kv) heads."
            )


def rules_for(model_name: str, strategy: str = "tp"):
    """Pick a rule set by model family + strategy
    ('tp' | 'fsdp' | 'tp+fsdp' | 'ep' | 'pp').  EP rules ride along with
    tp-family sets — they only bite on meshes with a live ``expert`` axis
    (absent axes are dropped by logical_to_shardings)."""
    if strategy == "fsdp":
        return FSDP_RULES
    if strategy == "ep":
        return list(EP_RULES)
    if strategy == "pp":
        return list(PP_RULES)
    rules = list(TRANSFORMER_TP_RULES) + list(EP_RULES)
    if strategy == "tp+fsdp":
        rules += FSDP_RULES
    return rules
