"""Collective primitives over mesh axes.

The reference's collective surface is DDP's implicit bucketed all-reduce
plus a dead manual ``dist.all_reduce(SUM)/world`` loop
(ref: src/trainer.py:98, 152-158).  Here the same operations are XLA
collective primitives bound to named mesh axes — usable inside
``shard_map``-decorated kernels (ring attention, expert dispatch) while
ordinary data parallelism never calls them explicitly (sharding annotations
imply them).

Every wrapper reports its analytic byte count to ``comm_stats.account``
AT TRACE TIME (shapes and axis sizes are static there), so the registry's
``comm_bytes_total{op=...}`` gauges attribute traffic per collective with
zero runtime cost and no change to the compiled program — the
distributed-observability leg of docs/observability.md.
"""

from __future__ import annotations

from typing import Union, Sequence

from jax import lax

from ml_trainer_tpu.parallel.comm_stats import account as _account
from ml_trainer_tpu.parallel.compat import axis_size as _axis_size

AxisName = Union[str, Sequence[str]]


def psum(x, axis: AxisName):
    """Sum across an axis — the ``dist.all_reduce(SUM)`` analog
    (ref: src/trainer.py:157)."""
    _account("psum", x, axis)
    return lax.psum(x, axis)


def pmean(x, axis: AxisName):
    """Mean across an axis — all_reduce(SUM)/world in one op, the exact
    semantics of the reference's ``_average_gradients``
    (ref: src/trainer.py:152-158)."""
    _account("pmean", x, axis)
    return lax.pmean(x, axis)


def all_gather(x, axis: AxisName, *, axis_index: int = 0, tiled: bool = True,
               bucket: str = None):
    """``bucket`` labels this call in the per-bucket comm breakdown
    (``comm_bucket_bytes_total{op=,bucket=}``) — the bucketed weight
    all-gather of the sharded update path tags each bucket's traffic."""
    _account("all_gather", x, axis, bucket=bucket)
    return lax.all_gather(x, axis, axis=axis_index, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0,
                   bucket: str = None):
    """``bucket`` labels this call in the per-bucket comm breakdown — the
    overlapped backward issues one reduce-scatter per gradient bucket."""
    _account("reduce_scatter", x, axis, bucket=bucket)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ppermute_ring(x, axis: AxisName, shift: int = 1):
    """Send each shard to its ring neighbour over ICI — the building block
    of ring attention (parallel/ring.py rotates K/V through it)."""
    n = _axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    _account("ppermute", x, axis)
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: AxisName, *, split_axis: int, concat_axis: int):
    """Re-partition one array dim across another — the Ulysses
    head/sequence exchange (parallel/ulysses.py runs a pair of these)."""
    _account("all_to_all", x, axis)
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName):
    return _axis_size(axis)
