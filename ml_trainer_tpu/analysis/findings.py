"""Findings, reports, and baselines — the contract every checker shares.

A static-analysis pass is only useful when its output is (a) machine
diff-able, so CI can hard-fail on *new* findings without arguing about
old ones, and (b) human-readable enough that the finding itself explains
the fix.  This module owns that surface for both graft-lint front ends
(the jaxpr contract checker and the AST lint pack):

* :class:`Finding` — one violation: rule id, severity, a stable
  ``location`` (``path:line`` for AST rules, the traced program's name
  for jaxpr rules), a one-line message, and a details dict for the
  machine report (byte prices, per-branch collective sequences, lock
  cycles).
* :class:`Report` — an ordered collection with JSON and terminal
  rendering.
* Baselines — ``baseline_payload`` / ``diff_against_baseline``: the
  committed artifact (``docs/graft_lint_baseline.json``) records the
  finding *keys* plus a fingerprint hash; the gate fails on keys not in
  the baseline, so a clean tree stays clean and an intentional
  suppression is an explicit artifact update, never a silent drift.

Line numbers are deliberately NOT part of a finding's baseline key:
unrelated edits move lines, and a gate that fires on every shifted line
trains people to ignore it.  The key is (rule, file-or-program,
message-core).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Any, Dict, List, Optional, Sequence

SEVERITIES = ("error", "warn", "perf")


@dataclasses.dataclass
class Finding:
    """One static-analysis violation."""

    rule: str
    severity: str
    location: str  # "relpath:line" (AST) or "program:<name>" (jaxpr)
    message: str
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got "
                f"{self.severity!r}"
            )

    @property
    def file(self) -> str:
        """Location with the line number stripped (the baseline-stable
        half)."""
        return re.sub(r":\d+$", "", self.location)

    def key(self) -> str:
        """Baseline identity: stable under unrelated line shifts."""
        return f"{self.rule}|{self.file}|{self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "location": self.location,
            "message": self.message,
            **({"details": self.details} if self.details else {}),
        }


class Report:
    """Ordered findings + rendering.  Checkers append; the CLI renders
    and diffs."""

    def __init__(self, findings: Optional[Sequence[Finding]] = None):
        self.findings: List[Finding] = list(findings or [])

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings: Sequence[Finding]) -> None:
        self.findings.extend(findings)

    def __len__(self) -> int:
        return len(self.findings)

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def sorted(self) -> List[Finding]:
        order = {s: i for i, s in enumerate(SEVERITIES)}
        return sorted(
            self.findings,
            key=lambda f: (order.get(f.severity, 9), f.rule, f.location),
        )

    def as_dict(self) -> dict:
        return {
            "findings": [f.as_dict() for f in self.sorted()],
            "counts": {
                "total": len(self.findings),
                **{s: sum(1 for f in self.findings if f.severity == s)
                   for s in SEVERITIES},
                "by_rule": self.by_rule(),
            },
        }

    def render(self, max_details: int = 4) -> str:
        """Human report: one block per finding, severity-ordered."""
        if not self.findings:
            return "graft-lint: clean (0 findings)"
        lines = [f"graft-lint: {len(self.findings)} finding(s)"]
        for f in self.sorted():
            lines.append(f"  [{f.severity}] {f.rule} @ {f.location}")
            lines.append(f"      {f.message}")
            for i, (k, v) in enumerate(sorted(f.details.items())):
                if i >= max_details:
                    lines.append(
                        f"      ... ({len(f.details) - max_details} more "
                        "detail fields in the JSON report)"
                    )
                    break
                lines.append(f"      {k}: {v}")
        return "\n".join(lines)


# ---------------------------------------------------------------- baseline
def fingerprint(findings: Sequence[Finding]) -> str:
    """Order-independent hash of the finding keys — the one value the
    flight recorder attaches to dumps (`lint_baseline` context)."""
    keys = sorted(f.key() for f in findings)
    return hashlib.sha256("\n".join(keys).encode("utf-8")).hexdigest()[:16]


def baseline_payload(report: Report) -> dict:
    """The committed artifact shape (docs/graft_lint_baseline.json)."""
    return {
        "fingerprint": fingerprint(report.findings),
        "keys": sorted(f.key() for f in report.findings),
        "counts": report.by_rule(),
    }


def diff_against_baseline(report: Report,
                          baseline: Optional[dict]) -> dict:
    """New-vs-baseline decision for the gate.

    ``new``: findings whose key is absent from the baseline — these fail
    the gate.  ``fixed``: baseline keys no longer found — informational
    (the gate prints them; refreshing the artifact is a deliberate
    ``--update-baseline`` run).  No baseline at all means every finding
    is new (a missing artifact must not silently pass a dirty tree).
    """
    known = set((baseline or {}).get("keys", []))
    new = [f for f in report.findings if f.key() not in known]
    current = {f.key() for f in report.findings}
    fixed = sorted(k for k in known if k not in current)
    return {
        "ok": not new,
        "new": [f.as_dict() for f in Report(new).sorted()],
        "fixed": fixed,
        "baseline_fingerprint": (baseline or {}).get("fingerprint"),
        "fresh_fingerprint": fingerprint(report.findings),
    }


def load_baseline(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as fp:
            return json.load(fp)
    except (OSError, ValueError):
        return None
