"""graft-lint: static analysis for the SPMD programs and the host code.

Two front ends over one findings/report/baseline surface:

* jaxpr contract checks (``jaxpr_checks``) — traced-program invariants:
  collective uniformity across switch branches, bf16 dtype policy,
  donation/aliasing audit, trace-time host-sync detection;
* the AST lint pack (``ast_checks``) — host-side concurrency and
  hygiene: lock-order cycles, unguarded shared state, device ops in
  host-only modules, host syncs in hot loops, unused imports.

``scripts/graft_lint.py`` is the CLI; ``docs/graft_lint_baseline.json``
the committed clean-tree artifact; ``scripts/bench_gate.py gate_lint``
the hard gate on new findings.
"""

from __future__ import annotations

import os
from typing import Optional

from ml_trainer_tpu.analysis.findings import (  # noqa: F401
    Finding,
    Report,
    baseline_payload,
    diff_against_baseline,
    fingerprint,
    load_baseline,
)
from ml_trainer_tpu.analysis.ast_checks import (  # noqa: F401
    LintConfig,
    modules_from_sources,
    run_ast_checks,
    scan_tree,
)
from ml_trainer_tpu.analysis.jaxpr_checks import (  # noqa: F401
    audit_donation,
    check_collective_uniformity,
    check_dtype_policy,
    check_program,
    check_traceable,
    collective_sequence,
)

BASELINE_RELPATH = os.path.join("docs", "graft_lint_baseline.json")


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def default_baseline_path() -> str:
    return os.path.join(repo_root(), BASELINE_RELPATH)


def lint_baseline_payload() -> dict:
    """Flight-recorder context provider: the committed lint baseline's
    fingerprint rides along on every dump, so post-mortems know exactly
    which contract set the crashed build was checked against."""
    baseline = load_baseline(default_baseline_path())
    if baseline is None:
        return {"present": False}
    return {
        "present": True,
        "fingerprint": baseline.get("fingerprint"),
        "findings": sum((baseline.get("counts") or {}).values()),
    }


def register_flight_context(flight=None) -> None:
    """Attach the lint-baseline fingerprint to future flight dumps."""
    if flight is None:
        from ml_trainer_tpu.telemetry.flight import get_recorder

        flight = get_recorder()
    flight.register_context_provider("lint_baseline", lint_baseline_payload)
