"""AST lint pack — the host-side concurrency front end of graft-lint.

The serving stack is explicitly threaded (HTTP handlers submit, the
engine loop admits/steps, the watchdog and exporters read), and its
safety rests on conventions the type system cannot see: which attributes
a lock guards, in what order locks nest, which modules must never touch
a device, and which loops must never sync the host.  This module checks
those conventions statically over the real source tree:

* **lock-order graph + cycle detection** (``lock-order-cycle``) — every
  ``with <lock>`` region is walked; a call made while holding lock A to
  code that (transitively) acquires lock B adds the edge A→B.  A cycle
  in that graph is a potential deadlock the moment two threads interleave
  — including the length-1 cycle of re-acquiring a non-reentrant
  ``threading.Lock`` already held.
* **unguarded shared state** (``unguarded-shared-state``) — in a class
  that owns a lock, an attribute assigned under the lock anywhere is
  *guarded*; assigning it outside a lock region (in any method except
  ``__init__``, and except private helpers only ever called from
  lock-held regions — the ``# Caller holds the lock`` idiom, which is
  also honored as a comment) is a race.
* **device ops in host-only modules** (``device-op-in-host-module``) —
  the scheduler, page pool, and prefix cache are host-side data
  structures on the serving hot path; importing ``jax`` there invites
  silent dispatches into admission control.
* **host-sync in hot loops** (``host-sync-hot-loop``) — in the
  registered hot functions (the engine step loops, the trainer epoch
  loops), every ``.item()``, ``jax.device_get``, single-argument
  ``np.asarray``/``np.array``, and ``float()`` coercion is flagged
  unless annotated: ``# graft-lint: sync-ok`` marks an *intentional*
  fence (the one sync the loop is designed around), ``# graft-lint:
  host-value`` marks a provably host-side value.  New syncs in a hot
  loop therefore fail the gate until someone writes down why.
* **import hygiene** (``unused-import``) — the F401 subset of the ruff
  configuration in pyproject.toml, implemented in-tree so the gate
  enforces it even where ruff is not installed (this container bakes
  the jax toolchain, not ruff).  ``__init__.py`` re-export surfaces are
  exempt; ``# noqa`` is honored.

Suppression syntax (all rules): ``# graft-lint: disable=<rule>[,<rule>]``
on the offending line, or alone on the line above.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ml_trainer_tpu.analysis.findings import Finding

_SUPPRESS_RE = re.compile(
    r"#\s*graft-lint:\s*(disable=(?P<rules>[\w,.-]+)|(?P<alias>sync-ok|host-value))"
)
_NOQA_RE = re.compile(r"#\s*noqa\b", re.IGNORECASE)
_CALLER_HOLDS_RE = re.compile(r"#\s*caller holds the lock", re.IGNORECASE)

# Known factory functions -> the class their return value behaves as
# (for resolving ``self.x = get_recorder(); ... self.x.record()``).
FACTORY_TYPES = {
    "get_recorder": "FlightRecorder",
    "default_registry": "MetricsRegistry",
    "default_sink": "JsonlSink",
}


@dataclasses.dataclass
class LintConfig:
    """What the AST pack checks where.  Paths are repo-relative and
    matched by suffix so the pack works from any checkout root."""

    # (path suffix, qualified function name) pairs whose bodies are
    # treated as device-dispatch hot loops.
    hot_functions: Tuple[Tuple[str, str], ...] = (
        ("serving/engine.py", "SlotDecodeEngine.step"),
        ("serving/engine.py", "SlotDecodeEngine._step_spec"),
        ("trainer.py", "Trainer._train_one_epoch"),
        ("trainer.py", "Trainer._train_one_epoch_multi"),
    )
    # Host-side data-structure modules that must never import jax.
    host_only_modules: Tuple[str, ...] = (
        "serving/scheduler.py",
        "serving/kv_pool.py",
        "serving/prefix_cache.py",
    )
    # Modules exempt from the unused-import rule (re-export surfaces).
    import_exempt: Tuple[str, ...] = ("__init__.py",)


@dataclasses.dataclass
class ModuleInfo:
    relpath: str
    source: str
    tree: ast.Module
    # lineno -> suppressed rule names ('*' for the bare aliases).
    suppressions: Dict[int, Set[str]]
    lock_held_comment_lines: Set[int]


def _parse_suppressions(source: str):
    sup: Dict[int, Set[str]] = {}
    holds: Set[int] = set()
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            if m.group("alias"):
                rules = {"host-sync-hot-loop"}
            else:
                rules = {r.strip() for r in m.group("rules").split(",")}
            target = sup.setdefault(i, set())
            target |= rules
            if line.strip().startswith("#"):
                # Standalone comment: applies to the next line too.
                sup.setdefault(i + 1, set()).update(rules)
        if _NOQA_RE.search(line):
            sup.setdefault(i, set()).add("unused-import")
        if _CALLER_HOLDS_RE.search(line):
            holds.add(i)
    return sup, holds


def load_module(relpath: str, source: str) -> Optional[ModuleInfo]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    sup, holds = _parse_suppressions(source)
    return ModuleInfo(relpath, source, tree, sup, holds)


def scan_tree(root: str,
              subdirs: Sequence[str] = ("ml_trainer_tpu", "scripts"),
              ) -> Dict[str, ModuleInfo]:
    """Parse every ``.py`` under ``root``'s configured subdirs into
    ModuleInfos keyed by repo-relative path."""
    modules: Dict[str, ModuleInfo] = {}
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, _, files in os.walk(base):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, root)
                try:
                    with open(path, encoding="utf-8") as fp:
                        src = fp.read()
                except OSError:
                    continue
                info = load_module(rel, src)
                if info is not None:
                    modules[rel] = info
    return modules


def modules_from_sources(sources: Dict[str, str]) -> Dict[str, ModuleInfo]:
    """Test hook: build the module map from in-memory sources."""
    out = {}
    for rel, src in sources.items():
        info = load_module(rel, src)
        if info is not None:
            out[rel] = info
    return out


def _suppressed(info: ModuleInfo, lineno: int, rule: str) -> bool:
    rules = info.suppressions.get(lineno, ())
    return rule in rules or "*" in rules


# ---------------------------------------------------------------- lock IR
@dataclasses.dataclass
class _ClassIR:
    name: str
    module: str
    lock_attrs: Dict[str, str]          # attr -> "Lock" | "RLock"
    attr_types: Dict[str, str]          # self.attr -> class name
    methods: Dict[str, ast.FunctionDef]


@dataclasses.dataclass
class _LockIR:
    """Cross-module index the concurrency rules share."""

    classes: Dict[str, _ClassIR]                 # class name -> IR
    module_locks: Dict[str, Dict[str, str]]      # relpath -> name -> kind
    module_funcs: Dict[str, Dict[str, ast.FunctionDef]]


def _lock_kind(node: ast.expr) -> Optional[str]:
    """'Lock'/'RLock' when ``node`` is a ``threading.[R]Lock()`` call."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return name if name in ("Lock", "RLock") else None


def _called_class(node: ast.expr,
                  known_classes: Set[str]) -> Optional[str]:
    """Class name a constructor-ish call resolves to, if known."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    if name is None:
        return None
    if name in known_classes:
        return name
    return FACTORY_TYPES.get(name)


def _build_lock_ir(modules: Dict[str, ModuleInfo]) -> _LockIR:
    classes: Dict[str, _ClassIR] = {}
    module_locks: Dict[str, Dict[str, str]] = {}
    module_funcs: Dict[str, Dict[str, ast.FunctionDef]] = {}
    known_classes: Set[str] = set()
    for info in modules.values():
        for node in info.tree.body:
            if isinstance(node, ast.ClassDef):
                known_classes.add(node.name)
    for rel, info in modules.items():
        module_locks[rel] = {}
        module_funcs[rel] = {}
        for node in info.tree.body:
            if isinstance(node, ast.Assign):
                kind = _lock_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            module_locks[rel][t.id] = kind
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                module_funcs[rel][node.name] = node
            elif isinstance(node, ast.ClassDef):
                ir = _ClassIR(node.name, rel, {}, {}, {})
                for item in node.body:
                    if isinstance(item,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                        ir.methods[item.name] = item
                        # Annotated params type cross-object references
                        # (``def __init__(self, engine: "Engine")``).
                        param_types = {}
                        for arg in item.args.args:
                            ann = arg.annotation
                            name = None
                            if isinstance(ann, ast.Name):
                                name = ann.id
                            elif (isinstance(ann, ast.Constant)
                                  and isinstance(ann.value, str)):
                                name = ann.value.strip("'\"")
                            if name in known_classes:
                                param_types[arg.arg] = name
                        for sub in ast.walk(item):
                            if not isinstance(sub, ast.Assign):
                                continue
                            for t in sub.targets:
                                if (isinstance(t, ast.Attribute)
                                        and isinstance(t.value, ast.Name)
                                        and t.value.id == "self"):
                                    kind = _lock_kind(sub.value)
                                    if kind:
                                        ir.lock_attrs[t.attr] = kind
                                    cls = _called_class(
                                        sub.value, known_classes
                                    )
                                    if (cls is None
                                            and isinstance(sub.value,
                                                           ast.Name)):
                                        cls = param_types.get(
                                            sub.value.id
                                        )
                                    if cls:
                                        ir.attr_types[t.attr] = cls
                classes[node.name] = ir
    return _LockIR(classes, module_locks, module_funcs)


def _lock_id_of(expr: ast.expr, rel: str, cls: Optional[_ClassIR],
                ir: _LockIR) -> Optional[Tuple[str, str]]:
    """Resolve a ``with`` item to (lock id, kind), or None.

    Forms: ``self._lock`` (class lock), ``name`` (module lock),
    ``self.attr._lock`` (lock of a typed attribute's class)."""
    if isinstance(expr, ast.Name):
        kind = ir.module_locks.get(rel, {}).get(expr.id)
        if kind:
            return f"{os.path.basename(rel)}:{expr.id}", kind
        return None
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self" and cls:
            kind = cls.lock_attrs.get(expr.attr)
            if kind:
                return f"{cls.name}.{expr.attr}", kind
            return None
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and cls):
            target_cls = cls.attr_types.get(base.attr)
            t_ir = ir.classes.get(target_cls) if target_cls else None
            if t_ir:
                kind = t_ir.lock_attrs.get(expr.attr)
                if kind:
                    return f"{t_ir.name}.{expr.attr}", kind
    return None


def _resolve_call(node: ast.Call, rel: str, cls: Optional[_ClassIR],
                  ir: _LockIR) -> Optional[Tuple[str, str]]:
    """(class name or '', method/function name) a call resolves to —
    only for targets the IR knows about."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "self" and cls:
            if fn.attr in cls.methods:
                return cls.name, fn.attr
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and cls):
            target_cls = cls.attr_types.get(base.attr)
            t_ir = ir.classes.get(target_cls) if target_cls else None
            if t_ir and fn.attr in t_ir.methods:
                return t_ir.name, fn.attr
    elif isinstance(fn, ast.Name):
        if fn.id in ir.module_funcs.get(rel, {}):
            return "", f"{rel}:{fn.id}"
    return None


def _function_key(cls_name: str, fn_name: str) -> str:
    return f"{cls_name}.{fn_name}" if cls_name else fn_name


def _direct_acquires(fn: ast.AST, rel: str, cls: Optional[_ClassIR],
                     ir: _LockIR) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                got = _lock_id_of(item.context_expr, rel, cls, ir)
                if got:
                    out.add(got[0])
    return out


def _callees(fn: ast.AST, rel: str, cls: Optional[_ClassIR],
             ir: _LockIR) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            got = _resolve_call(node, rel, cls, ir)
            if got:
                out.add(_function_key(*got))
    return out


def _acquire_summaries(ir: _LockIR) -> Dict[str, Set[str]]:
    """Fixpoint: every known function/method -> locks it may acquire,
    directly or through calls the IR can resolve."""
    fns: Dict[str, Tuple[ast.AST, str, Optional[_ClassIR]]] = {}
    for cls in ir.classes.values():
        for name, fn in cls.methods.items():
            fns[_function_key(cls.name, name)] = (fn, cls.module, cls)
    for rel, funcs in ir.module_funcs.items():
        for name, fn in funcs.items():
            fns[f"{rel}:{name}"] = (fn, rel, None)
    acquires = {
        key: _direct_acquires(fn, rel, cls, ir)
        for key, (fn, rel, cls) in fns.items()
    }
    callee_map = {
        key: _callees(fn, rel, cls, ir) & set(fns)
        for key, (fn, rel, cls) in fns.items()
    }
    changed = True
    while changed:
        changed = False
        for key, callees in callee_map.items():
            before = len(acquires[key])
            for c in callees:
                acquires[key] |= acquires[c]
            if len(acquires[key]) != before:
                changed = True
    return acquires


# ------------------------------------------------------- lock-order rule
def check_lock_order(modules: Dict[str, ModuleInfo],
                     config: Optional[LintConfig] = None) -> List[Finding]:
    """Build the lock-order graph and report cycles (incl. self-cycles
    on non-reentrant locks)."""
    ir = _build_lock_ir(modules)
    summaries = _acquire_summaries(ir)
    lock_kinds: Dict[str, str] = {}
    for cls in ir.classes.values():
        for attr, kind in cls.lock_attrs.items():
            lock_kinds[f"{cls.name}.{attr}"] = kind
    for rel, locks in ir.module_locks.items():
        for name, kind in locks.items():
            lock_kinds[f"{os.path.basename(rel)}:{name}"] = kind

    edges: Dict[Tuple[str, str], str] = {}  # (A, B) -> sample site

    def walk(node, held: Tuple[str, ...], rel: str,
             cls: Optional[_ClassIR], info: ModuleInfo):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                got = _lock_id_of(item.context_expr, rel, cls, ir)
                if got:
                    acquired.append(got[0])
                    for h in held:
                        if not (h == got[0] and got[1] == "RLock"):
                            edges.setdefault(
                                (h, got[0]), f"{rel}:{node.lineno}"
                            )
            inner = held + tuple(a for a in acquired if a not in held)
            for child in node.body:
                walk(child, inner, rel, cls, info)
            return
        if isinstance(node, ast.Call) and held:
            got = _resolve_call(node, rel, cls, ir)
            if got:
                key = _function_key(*got)
                for m in summaries.get(key, ()):
                    for h in held:
                        if not (h == m and lock_kinds.get(m) == "RLock"):
                            edges.setdefault(
                                (h, m), f"{rel}:{node.lineno}"
                            )
        for child in ast.iter_child_nodes(node):
            walk(child, held, rel, cls, info)

    for rel, info in modules.items():
        for node in info.tree.body:
            if isinstance(node, ast.ClassDef):
                cls = ir.classes.get(node.name)
                for item in node.body:
                    walk(item, (), rel, cls, info)
            else:
                walk(node, (), rel, None, info)

    # Cycle detection over the edge graph (self-edges included).
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    findings: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cycle = tuple(sorted(path))
                if cycle not in seen_cycles:
                    seen_cycles.add(cycle)
                    sites = [
                        edges.get((path[i], path[(i + 1) % len(path)]))
                        for i in range(len(path))
                    ]
                    findings.append(Finding(
                        rule="lock-order-cycle",
                        severity="error",
                        location=sites[0] or "lock-graph",
                        message=(
                            "lock-order cycle: "
                            + " -> ".join(path + [path[0]])
                            + (" (non-reentrant re-acquisition)"
                               if len(path) == 1 else
                               " — two threads interleaving these "
                               "acquisitions deadlock")
                        ),
                        details={
                            "cycle": path + [path[0]],
                            "sites": sites,
                        },
                    ))
            elif nxt not in path and nxt > start:
                # Only explore nodes > start so each cycle is found from
                # its smallest node exactly once.
                dfs(start, nxt, path + [nxt])

    for a in sorted(graph):
        dfs(a, a, [a])
    return findings


# -------------------------------------------------- shared-state rule
def check_shared_state(modules: Dict[str, ModuleInfo],
                       config: Optional[LintConfig] = None
                       ) -> List[Finding]:
    """Attributes guarded by a class's lock must not be assigned outside
    it (except in ``__init__`` and in helpers only ever called under the
    lock)."""
    ir = _build_lock_ir(modules)
    findings: List[Finding] = []
    for cls in ir.classes.values():
        if not cls.lock_attrs:
            continue
        info = modules[cls.module]

        def assigned_attrs(node) -> List[Tuple[str, int]]:
            out = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            else:
                return out
            for t in targets:
                # self.attr = / self.attr[k] = ...
                base = t
                if isinstance(base, ast.Subscript):
                    base = base.value
                if (isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                        and base.attr not in cls.lock_attrs):
                    out.append((base.attr, node.lineno))
            return out

        # Pass 1: which attrs are ever assigned under the lock.
        guarded: Set[str] = set()

        def collect(node, held: bool):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                takes = any(
                    _lock_id_of(i.context_expr, cls.module, cls, ir)
                    for i in node.items
                )
                for child in node.body:
                    collect(child, held or takes)
                return
            for attr, _ in assigned_attrs(node):
                if held:
                    guarded.add(attr)
            for child in ast.iter_child_nodes(node):
                collect(child, held)

        for name, fn in cls.methods.items():
            for item in fn.body:
                collect(item, False)

        # Methods treated as lock-held contexts: annotated with
        # "# Caller holds the lock", or private AND only called from
        # held regions / other held-context methods (fixpoint).
        annotated = {
            name for name, fn in cls.methods.items()
            if any(
                ln in info.lock_held_comment_lines
                for ln in range(fn.lineno, fn.lineno + 8)
            )
        }
        # method -> [(caller, caller-held-the-lock-at-the-call)].
        call_sites: Dict[str, list] = {m: [] for m in cls.methods}

        def record_calls(node, held: bool, caller: str):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                takes = any(
                    _lock_id_of(i.context_expr, cls.module, cls, ir)
                    for i in node.items
                )
                for child in node.body:
                    record_calls(child, held or takes, caller)
                return
            if isinstance(node, ast.Call):
                fn_node = node.func
                if (isinstance(fn_node, ast.Attribute)
                        and isinstance(fn_node.value, ast.Name)
                        and fn_node.value.id == "self"
                        and fn_node.attr in call_sites):
                    call_sites[fn_node.attr].append((caller, held))
            for child in ast.iter_child_nodes(node):
                record_calls(child, held, caller)

        for name, fn in cls.methods.items():
            for item in fn.body:
                record_calls(item, False, name)

        held_context = set(annotated)
        changed = True
        while changed:
            changed = False
            for name, sites in call_sites.items():
                if name in held_context or not name.startswith("_"):
                    continue
                if not sites:
                    continue
                if all(h or c in held_context for c, h in sites):
                    held_context.add(name)
                    changed = True

        # Pass 2: flag unguarded assignments of guarded attrs.
        def flag(node, held: bool, method: str):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                takes = any(
                    _lock_id_of(i.context_expr, cls.module, cls, ir)
                    for i in node.items
                )
                for child in node.body:
                    flag(child, held or takes, method)
                return
            if not held and method not in ("__init__",) \
                    and method not in held_context:
                for attr, lineno in assigned_attrs(node):
                    if attr in guarded and not _suppressed(
                        info, lineno, "unguarded-shared-state"
                    ):
                        findings.append(Finding(
                            rule="unguarded-shared-state",
                            severity="error",
                            location=f"{cls.module}:{lineno}",
                            message=(
                                f"{cls.name}.{method} assigns "
                                f"self.{attr} without holding the lock "
                                f"that guards it elsewhere"
                            ),
                            details={
                                "class": cls.name, "attr": attr,
                                "method": method,
                            },
                        ))
            for child in ast.iter_child_nodes(node):
                flag(child, held, method)

        for name, fn in cls.methods.items():
            if name == "__init__" or name in held_context:
                continue
            for item in fn.body:
                flag(item, False, name)
    return findings


# ------------------------------------------------ host-only-module rule
def check_host_only_modules(modules: Dict[str, ModuleInfo],
                            config: Optional[LintConfig] = None
                            ) -> List[Finding]:
    cfg = config or LintConfig()
    findings: List[Finding] = []
    for rel, info in modules.items():
        if not any(rel.endswith(sfx) for sfx in cfg.host_only_modules):
            continue
        for node in ast.walk(info.tree):
            roots = []
            if isinstance(node, ast.Import):
                roots = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                roots = [node.module.split(".")[0]]
            if "jax" in roots and not _suppressed(
                info, node.lineno, "device-op-in-host-module"
            ):
                findings.append(Finding(
                    rule="device-op-in-host-module",
                    severity="error",
                    location=f"{rel}:{node.lineno}",
                    message=(
                        f"{rel} is a host-side scheduler/pool module on "
                        "the serving hot path; importing jax here "
                        "invites device dispatches into admission "
                        "control"
                    ),
                    details={"module": rel},
                ))
    return findings


# ------------------------------------------------------ host-sync rule
def _qualnames(tree: ast.Module):
    """Yield (qualname, FunctionDef) for module- and class-level defs."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield f"{node.name}.{item.name}", item


def _is_literal(node: ast.expr) -> bool:
    return isinstance(node, (ast.Constant, ast.Num, ast.Str))


def check_host_sync(modules: Dict[str, ModuleInfo],
                    config: Optional[LintConfig] = None) -> List[Finding]:
    cfg = config or LintConfig()
    findings: List[Finding] = []
    for rel, info in modules.items():
        wanted = {
            qn for sfx, qn in cfg.hot_functions if rel.endswith(sfx)
        }
        if not wanted:
            continue
        for qualname, fn in _qualnames(info.tree):
            if qualname not in wanted:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                hit = None
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr == "item" and not node.args:
                        hit = ".item() fetches a device scalar"
                    elif (f.attr == "device_get"
                          and isinstance(f.value, ast.Name)
                          and f.value.id == "jax"):
                        hit = "jax.device_get blocks on the device"
                    elif (f.attr in ("asarray", "array")
                          and isinstance(f.value, ast.Name)
                          and f.value.id == "np"
                          and len(node.args) == 1
                          and not node.keywords):
                        hit = ("np.asarray of a device value fences "
                               "the dispatch stream")
                elif isinstance(f, ast.Name) and f.id == "float":
                    if node.args and not _is_literal(node.args[0]):
                        hit = ("float() coercion syncs if its operand "
                               "is a device array")
                if hit is None:
                    continue
                if _suppressed(info, node.lineno, "host-sync-hot-loop"):
                    continue
                findings.append(Finding(
                    rule="host-sync-hot-loop",
                    severity="warn",
                    location=f"{rel}:{node.lineno}",
                    message=(
                        f"{qualname}: {hit} — annotate the intentional "
                        "fence with '# graft-lint: sync-ok' (or "
                        "'host-value' for provably host data), or move "
                        "it off the hot path"
                    ),
                    details={"function": qualname},
                ))
    return findings


# -------------------------------------------------- import-hygiene rule
def check_unused_imports(modules: Dict[str, ModuleInfo],
                         config: Optional[LintConfig] = None
                         ) -> List[Finding]:
    cfg = config or LintConfig()
    findings: List[Finding] = []
    for rel, info in modules.items():
        if any(rel.endswith(sfx) for sfx in cfg.import_exempt):
            continue
        bindings: List[Tuple[str, int, str]] = []  # (name, line, shown-as)
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    bindings.append((name, node.lineno, a.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    bindings.append(
                        (name, node.lineno,
                         f"{node.module or '.'}.{a.name}")
                    )
        if not bindings:
            continue
        used: Set[str] = set()
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # the root is a Name node, already captured
        # __all__ re-exports count as uses.
        for node in info.tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in node.targets)
                    and isinstance(node.value, (ast.List, ast.Tuple))):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant):
                        used.add(str(elt.value))
        for name, lineno, shown in bindings:
            if name in used or name == "_":
                continue
            if _suppressed(info, lineno, "unused-import"):
                continue
            findings.append(Finding(
                rule="unused-import",
                severity="warn",
                location=f"{rel}:{lineno}",
                message=f"'{shown}' imported but unused",
                details={"name": name},
            ))
    return findings


# ------------------------------------------------------------ aggregation
def run_ast_checks(modules: Dict[str, ModuleInfo],
                   config: Optional[LintConfig] = None) -> List[Finding]:
    cfg = config or LintConfig()
    findings: List[Finding] = []
    findings += check_lock_order(modules, cfg)
    findings += check_shared_state(modules, cfg)
    findings += check_host_only_modules(modules, cfg)
    findings += check_host_sync(modules, cfg)
    findings += check_unused_imports(modules, cfg)
    # Per-line disable= works for every rule (sync-ok/host-value are
    # host-sync-specific aliases handled in _parse_suppressions).
    return [
        f for f in findings
        if not _line_suppressed(modules, f)
    ]


def _line_suppressed(modules: Dict[str, ModuleInfo],
                     finding: Finding) -> bool:
    m = re.match(r"(.+):(\d+)$", finding.location)
    if not m:
        return False
    info = modules.get(m.group(1))
    if info is None:
        return False
    return _suppressed(info, int(m.group(2)), finding.rule)
