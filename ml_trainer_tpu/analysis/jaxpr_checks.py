"""Jaxpr-level program contract checker — the SPMD front end of graft-lint.

The repo's hand-scheduled SPMD programs (pipeline tick tables with
``lax.switch`` dispatch, bucketed reduce-scatter, ring/Ulysses SP, the
paged decode path) carry invariants that only hold *per compiled
program*: every branch of a switch must issue the same collectives, the
bf16 policy must not leak fp32 compute, donatable state buffers must
actually be donated.  Tests enforce these dynamically, one configuration
at a time; this module checks them statically, on the jaxpr of the very
closures the Trainer and serving engine build (``jit.trace(...)`` /
``jax.make_jaxpr``), before any device runs.

Checks (each returns :class:`~.findings.Finding` objects):

* :func:`check_collective_uniformity` — every ``cond``/``switch``
  anywhere in the program (including inside ``shard_map`` bodies and
  ``scan`` ticks) must issue the SAME collective sequence in every
  branch: same primitive, same axes, same ppermute perm, same payload
  shape/dtype.  A mismatch is the classic SPMD deadlock: devices taking
  different branches post mismatched collectives and the program hangs
  at scale (the pipeline tick tables are exactly this shape — idle
  branches must stay collective-free).
* :func:`check_dtype_policy` — under ``precision='bf16'`` no
  ``dot_general``/``conv_general_dilated`` may consume fp32 operands
  (compute must be bf16; precision.py casts once at the loss-fn top),
  and no cross-replica gradient reduction (``psum``/``reduce_scatter``)
  may run in bf16 (reductions stay fp32 — bf16 accumulation loses the
  gradient signal the policy exists to protect).
* :func:`audit_donation` — diff donatable input buffers (an undonated
  input whose shape/dtype matches an otherwise-unmatched output could
  have been aliased) against the actual ``donate_argnums``, pricing the
  wasted bytes through the PR9 memory ledger; optionally verify against
  the lowered module that declared donations really produced
  input/output aliases (a silently dropped donation doubles the state's
  HBM).
* :func:`check_traceable` — tracing IS the host-sync check for device
  code: ``.item()`` / ``float()`` / bool coercion of a traced array
  raises at trace time, which this converts into a finding instead of a
  stack trace.  (Host-side step-loop code is the AST pack's half —
  ``ast_checks.py``.)
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax

from ml_trainer_tpu.analysis.findings import Finding

# Cross-device collectives: mismatching these across switch branches (or
# losing one on some replicas) is the deadlock class this checker exists
# for.  pbroadcast is shard_map's replication bookkeeping, not a wire
# collective, and axis_index is free — both excluded.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
})

# Compute-heavy primitives the bf16 policy governs.
_COMPUTE_PRIMS = frozenset({"dot_general", "conv_general_dilated"})

# Cross-replica reductions that must stay fp32 under a bf16 policy.
_REDUCTION_PRIMS = frozenset({"psum", "reduce_scatter", "psum_scatter"})


# ------------------------------------------------------------- jaxpr walk
def _as_jaxpr(obj):
    """Unwrap ClosedJaxpr -> Jaxpr; pass Jaxpr through; else None."""
    if hasattr(obj, "eqns"):
        return obj
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def sub_jaxprs(eqn) -> List[Tuple[str, int, Any]]:
    """Every (param_name, index, jaxpr) nested in one equation — covers
    cond branches, scan/while bodies, pjit/remat/custom_vjp calls and
    shard_map bodies uniformly."""
    out = []
    for name, value in eqn.params.items():
        values = value if isinstance(value, (list, tuple)) else [value]
        for i, v in enumerate(values):
            j = _as_jaxpr(v)
            if j is not None:
                out.append((name, i, j))
    return out


def iter_eqns(jaxpr):
    """Depth-first over every equation in the program, branches included."""
    j = _as_jaxpr(jaxpr)
    if j is None:
        return
    for eqn in j.eqns:
        yield eqn
        for _, _, sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _eqn_location(eqn, program: str) -> str:
    """``relpath:line`` of the user frame that traced this equation, or
    the program name when source info is unavailable."""
    try:
        tb = eqn.source_info.traceback
        for frame in tb.frames:
            fname = frame.file_name
            if "ml_trainer_tpu" in fname or "/tests/" in fname:
                short = fname[fname.index("ml_trainer_tpu"):] if (
                    "ml_trainer_tpu" in fname
                ) else fname
                return f"{short}:{frame.start_line}"
    except Exception:
        pass
    return f"program:{program}"


def collective_signature(eqn) -> dict:
    """What must match across switch branches for one collective: the
    primitive, the mesh axes, the ppermute perm, and the payload
    shape/dtype (a psum of f32[8,4] and a psum of f32[4] are different
    wire programs)."""
    p = eqn.params
    axes = p.get("axes", p.get("axis_name"))
    if isinstance(axes, (list, tuple)):
        axes = tuple(str(a) for a in axes)
    else:
        axes = (str(axes),)
    sig = {
        "op": eqn.primitive.name,
        "axes": axes,
        "payload": tuple(
            str(v.aval) for v in eqn.invars if hasattr(v, "aval")
        ),
    }
    if "perm" in p:
        sig["perm"] = tuple(tuple(pair) for pair in p["perm"])
    return sig


def collective_sequence(jaxpr) -> List[dict]:
    """Ordered collective signatures of a (sub)program, recursing into
    everything — for a switch branch this is exactly 'what the branch
    posts on the wire, in order'."""
    return [
        collective_signature(e)
        for e in iter_eqns(jaxpr)
        if e.primitive.name in COLLECTIVE_PRIMS
    ]


# ----------------------------------------------------- collective checker
def check_collective_uniformity(jaxpr, program: str) -> List[Finding]:
    """Every ``cond`` (which ``lax.switch`` lowers to) must issue the
    same collective sequence in every branch."""
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "cond":
            continue
        branches = eqn.params.get("branches") or ()
        seqs = [collective_sequence(b) for b in branches]
        if not any(seqs):
            continue
        if all(s == seqs[0] for s in seqs[1:]):
            continue
        findings.append(Finding(
            rule="collective-mismatch",
            severity="error",
            location=_eqn_location(eqn, program),
            message=(
                f"switch branches issue mismatched collective sequences "
                f"in {program} — devices taking different branches will "
                f"deadlock"
            ),
            details={
                "program": program,
                "branch_collectives": [
                    [f"{s['op']}{list(s['axes'])}"
                     + (f" perm={s['perm']}" if "perm" in s else "")
                     + f" {'/'.join(s['payload'])}"
                     for s in seq]
                    for seq in seqs
                ],
            },
        ))
    return findings


# --------------------------------------------------------- dtype checker
def check_dtype_policy(jaxpr, program: str,
                       policy: str = "bf16") -> List[Finding]:
    """bf16-policy conformance: compute in bf16, reductions in fp32.

    ``policy='fp32'`` programs are exempt by definition (the fp32 path
    is pinned bit-identical to the pre-policy program; there is nothing
    to conform to)."""
    if policy not in ("bf16", "bfloat16", "mixed_bf16"):
        return []
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _COMPUTE_PRIMS:
            op_dtypes = {
                str(v.aval.dtype) for v in eqn.invars
                if hasattr(v, "aval") and hasattr(v.aval, "dtype")
            }
            if "float32" in op_dtypes:
                findings.append(Finding(
                    rule="fp32-compute-under-bf16",
                    severity="error",
                    location=_eqn_location(eqn, program),
                    message=(
                        f"{name} consumes fp32 operands in the bf16 "
                        f"program {program} — the precision policy casts "
                        "compute to bf16 at the loss-fn top; an fp32 "
                        "matmul here halves MXU throughput silently"
                    ),
                    details={
                        "program": program,
                        "primitive": name,
                        "operand_dtypes": sorted(op_dtypes),
                        "shapes": [
                            str(v.aval) for v in eqn.invars
                            if hasattr(v, "aval")
                        ],
                    },
                ))
        elif name in _REDUCTION_PRIMS:
            op_dtypes = {
                str(v.aval.dtype) for v in eqn.invars
                if hasattr(v, "aval") and hasattr(v.aval, "dtype")
            }
            if "bfloat16" in op_dtypes:
                findings.append(Finding(
                    rule="bf16-gradient-reduction",
                    severity="error",
                    location=_eqn_location(eqn, program),
                    message=(
                        f"{name} reduces bf16 values across replicas in "
                        f"{program} — gradient reductions stay fp32 "
                        "(precision.py): bf16 accumulation flushes the "
                        "small gradients the loss scale exists to keep"
                    ),
                    details={"program": program, "primitive": name},
                ))
    return findings


# ------------------------------------------------------- donation auditor
def _aval_bytes(shape, dtype) -> int:
    from ml_trainer_tpu.telemetry.memory import nbytes_of

    return nbytes_of(shape, dtype)


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts) or "<arg>"


def audit_donation(traced, program: str, min_bytes: int = 1 << 16,
                   lowered_text: Optional[str] = None) -> List[Finding]:
    """Donation/aliasing audit of one traced program.

    ``traced`` is the ``jax.jit(...).trace(*args)`` result: its
    ``args_info`` carries per-leaf donated flags, its jaxpr carries the
    output avals.  An input leaf is *donatable-but-undonated* when it is
    not donated, at least ``min_bytes`` big, and its (shape, dtype)
    matches an output aval not already claimed by a donated input — XLA
    could have aliased it and reused the buffer, so the undonated copy
    is pure HBM waste, priced here through the memory ledger.

    With ``lowered_text`` (``traced.lower().as_text()``) the audit also
    verifies declared donations materialized as input/output aliases
    (``tf.aliasing_output``): jax silently drops donation when layouts
    or shardings prevent aliasing, which doubles the state's footprint
    without any visible error.
    """
    flat_info = jax.tree_util.tree_flatten_with_path(traced.args_info)[0]
    out_avals = [
        (tuple(a.shape), str(a.dtype))
        for a in traced.jaxpr.out_avals
        if hasattr(a, "shape")
    ]
    # Outputs still available for aliasing = all outputs minus one slot
    # per donated input of that (shape, dtype).
    pool: dict = {}
    for key in out_avals:
        pool[key] = pool.get(key, 0) + 1
    donated_total = 0
    for _, info in flat_info:
        if getattr(info, "donated", False):
            donated_total += 1
            key = (tuple(info.shape), str(info.dtype))
            if pool.get(key, 0) > 0:
                pool[key] -= 1
    findings: List[Finding] = []
    wasted: List[Tuple[str, int]] = []
    for path, info in flat_info:
        if getattr(info, "donated", False):
            continue
        shape = tuple(getattr(info, "shape", ()) or ())
        dtype = str(getattr(info, "dtype", ""))
        nbytes = _aval_bytes(shape, dtype) if dtype else 0
        if nbytes < min_bytes:
            continue
        key = (shape, dtype)
        if pool.get(key, 0) > 0:
            pool[key] -= 1
            wasted.append((_path_str(path), nbytes))
    if wasted:
        total = sum(b for _, b in wasted)
        findings.append(Finding(
            rule="undonated-buffer",
            severity="perf",
            location=f"program:{program}",
            message=(
                f"{len(wasted)} donatable input buffer(s) not donated in "
                f"{program} — {total / 2 ** 20:.2f} MiB of aliasable "
                "state held twice across the dispatch"
            ),
            details={
                "program": program,
                "undonated_bytes": total,
                "buffers": [
                    {"arg": p, "bytes": b}
                    for p, b in sorted(wasted, key=lambda x: -x[1])[:16]
                ],
            },
        ))
    if lowered_text is not None and donated_total:
        aliased = lowered_text.count("tf.aliasing_output")
        if aliased == 0:
            findings.append(Finding(
                rule="donation-dropped",
                severity="error",
                location=f"program:{program}",
                message=(
                    f"{program} declares {donated_total} donated "
                    "argument(s) but the lowered module aliases none of "
                    "them — donation was silently dropped (layout or "
                    "sharding mismatch), doubling the state's HBM"
                ),
                details={"program": program, "declared": donated_total},
            ))
    return findings


# --------------------------------------------------------- trace checker
def check_traceable(build_trace, program: str) -> List[Finding]:
    """Run ``build_trace()`` (a thunk returning a Traced / jaxpr) and
    convert trace-time concretization errors — ``.item()``, ``float()``,
    ``if`` on a traced array — into a host-sync finding.  Device code
    that traces clean cannot host-sync by construction."""
    try:
        build_trace()
        return []
    except Exception as e:  # ConcretizationTypeError and friends
        name = type(e).__name__
        if "Concretization" not in name and "TracerBool" not in name \
                and "Tracer" not in name:
            raise
        return [Finding(
            rule="host-sync-in-program",
            severity="error",
            location=f"program:{program}",
            message=(
                f"tracing {program} forced a device value to the host "
                "(.item()/float()/bool on a traced array) — a per-step "
                "sync inside the compiled region"
            ),
            details={"program": program, "error": str(e).split("\n")[0]},
        )]


# ------------------------------------------------------------ aggregation
def check_program(traced, program: str, *, policy: str = "fp32",
                  min_donation_bytes: int = 1 << 16,
                  lowered_text: Optional[str] = None) -> List[Finding]:
    """All jaxpr checks over one traced program."""
    jaxpr = traced.jaxpr
    findings = []
    findings += check_collective_uniformity(jaxpr, program)
    findings += check_dtype_policy(jaxpr, program, policy)
    findings += audit_donation(
        traced, program, min_bytes=min_donation_bytes,
        lowered_text=lowered_text,
    )
    return findings
