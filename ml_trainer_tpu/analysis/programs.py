"""Real-program builders: the closures graft-lint's jaxpr checks trace.

A contract checker that validates hand-written fixture programs proves
nothing about the tree; these builders construct the SAME closures the
Trainer and the serving engine run in production — ``Trainer.__init__``
builds ``_train_step`` (fused or sharded, fp32 or bf16), a
``SlotDecodeEngine`` builds its decode / prefill / paged-continuation /
verify programs — and hand each back as a :class:`ProgramSpec` carrying
the ``jit.trace(...)`` result (jaxpr + per-arg donation flags, NO
compilation) plus the policy the checkers should hold it to.

Everything is sized for tracing speed (MLModel on synthetic CIFAR,
gpt2_tiny serving at ``max_len=64``): tracing is shape arithmetic, so
the contracts verified here are the same ones the full-size programs
carry — the structure of the jaxpr does not depend on widths.
"""

from __future__ import annotations

import dataclasses
import tempfile
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class ProgramSpec:
    """One traced program + the contract knobs the checkers need."""

    name: str
    traced: Any                       # jax.stages.Traced
    policy: str = "fp32"              # dtype policy the program runs under
    min_donation_bytes: int = 1 << 16
    # Thunk producing the lowered module text (for the aliasing audit);
    # None skips that half (lowering costs more than tracing).
    lower_text: Optional[Callable[[], str]] = None


def _lower_text_thunk(traced):
    def thunk():
        return traced.lower().as_text()

    return thunk


# ------------------------------------------------------------- train side
def build_train_specs(precisions=("fp32", "bf16"),
                      with_lowered: bool = False,
                      sharded: Optional[bool] = None) -> List[ProgramSpec]:
    """Trace the Trainer's per-batch train step and eval step for each
    precision policy — the very ``self._train_step`` the epoch loop
    dispatches.  With ``sharded`` (default: whenever >= 2 devices) the
    PR7 ``dp_update='sharded'`` flavor is traced too at bf16: the
    bucketed reduce-scatter + sharded update + per-bucket all-gather is
    where the collective walk and the bf16-reduction rule have real
    targets."""
    from ml_trainer_tpu import MLModel, Trainer
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    if sharded is None:
        sharded = jax.device_count() >= 2
    specs: List[ProgramSpec] = []
    t0 = custom_pre_process_function()
    flavors = [(p, "fused") for p in precisions]
    if sharded:
        flavors.append(("bf16", "sharded"))
        flavors.append(("fp32", "sharded_fused"))

    def sets():
        return (
            SyntheticCIFAR10(size=32, seed=0, transform=t0),
            SyntheticCIFAR10(size=16, seed=1, transform=t0),
        )

    for precision, dp_update in flavors:
        extra = {}
        label = precision
        optimizer = "adamw"
        if dp_update in ("sharded", "sharded_fused"):
            # The mesh must cover the host's devices (2 in the CLI's
            # forced-virtual-device process, 8 on the test harness).
            extra = {
                "dp_update": "sharded",
                "mesh_shape": {"data": jax.device_count()},
            }
            label = f"{precision},sharded"
        if dp_update == "sharded_fused":
            # The fused optimizer tail (ops/kernels/fused_adam.py) only
            # engages for bare adam at weight_decay=0; force it on so
            # the kernel-backed update program is held to the same
            # donation / collective contracts as the optax one.
            optimizer = "adam"
            extra["fused_adam"] = True
            label = f"{precision},sharded,fused_adam"
        trainer = Trainer(
            MLModel(), datasets=sets(),
            epochs=1, batch_size=16, lr=0.01, optimizer=optimizer,
            metric=None, precision=precision,
            model_dir=tempfile.mkdtemp(prefix="graft_lint_train_"),
            **extra,
        )
        x, y = next(iter(trainer.train_loader))
        lr_scale = jnp.asarray(1.0, jnp.float32)
        traced = trainer._train_step.trace(
            trainer.state, jnp.asarray(x), jnp.asarray(y), lr_scale
        )
        specs.append(ProgramSpec(
            name=f"train_step[{label}]",
            traced=traced,
            policy=precision,
            lower_text=_lower_text_thunk(traced) if with_lowered else None,
        ))
        if dp_update != "fused":
            continue  # one eval step per precision is enough
        ev = trainer._eval_step.trace(
            trainer._state_variables(), jnp.asarray(x), jnp.asarray(y)
        )
        specs.append(ProgramSpec(
            name=f"eval_step[{label}]",
            traced=ev,
            policy=precision,
        ))
    return specs


# ------------------------------------------------------------ decode side
def _tiny_lm(max_len: int = 64):
    from ml_trainer_tpu.models import get_model

    model = get_model("gpt2_tiny", max_len=max_len)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)},
        np.zeros((1, 8), np.int32), train=False,
    )
    return model, variables


def build_decode_specs(paged: bool = True, spec_k: int = 2,
                       with_lowered: bool = False) -> List[ProgramSpec]:
    """Trace the serving engine's compiled programs: the contiguous
    decode step, its paged twin, a prefill bucket, the paged
    continuation window, and the speculative verify step — each pulled
    off a real ``SlotDecodeEngine`` so the traced closure IS the served
    one."""
    from ml_trainer_tpu.serving.engine import SlotDecodeEngine

    model, variables = _tiny_lm()
    specs: List[ProgramSpec] = []

    def decode_args(eng):
        return (eng.params, eng.cache, eng.tok, eng._temps, eng._rngs,
                eng._steps)

    eng = SlotDecodeEngine(model, variables, max_batch=2)
    traced = eng._decode.trace(*decode_args(eng))
    specs.append(ProgramSpec(
        name="serve_decode[contiguous]", traced=traced,
        lower_text=_lower_text_thunk(traced) if with_lowered else None,
    ))
    # The contiguous batch-1 prefill at one representative bucket.
    bucket = 8
    prefill = eng._program(
        ("serve_prefill", eng.model, bucket),
        lambda: eng._build_prefill(bucket),
    )
    specs.append(ProgramSpec(
        name=f"serve_prefill[b{bucket}]",
        traced=prefill.trace(
            eng.params, np.zeros((1, bucket), np.int32), np.int32(5),
            jnp.asarray(0.0, jnp.float32),
            np.zeros((2,), np.uint32), np.int32(0),
        ),
    ))

    if paged:
        peng = SlotDecodeEngine(
            model, variables, max_batch=2, kv_page_size=16,
        )
        traced_p = peng._decode.trace(*decode_args(peng))
        specs.append(ProgramSpec(
            name="serve_decode[paged]", traced=traced_p,
            lower_text=_lower_text_thunk(traced_p) if with_lowered
            else None,
        ))
        cont = peng._program(
            ("serve_prefill_paged", peng._key_model, bucket),
            lambda: peng._build_prefill_paged(bucket),
        )
        specs.append(ProgramSpec(
            name=f"serve_prefill_paged[b{bucket}]",
            traced=cont.trace(
                peng.cache, peng.tok, peng.params,
                np.zeros((1, bucket), np.int32), np.int32(5),
                np.int32(16), np.zeros((4,), np.int32),
                jnp.asarray(0.0, jnp.float32),
                np.zeros((2,), np.uint32), np.int32(0), np.int32(0),
            ),
        ))
        # The kernel-backed paged decode (ops/kernels/paged_attention.py
        # behind ``paged_kernel=True``): same engine surface, but the
        # page-table gather is fused into the attention program — trace
        # it so the Pallas path carries the same donation and dtype
        # contracts as the gather twin it replaces.
        keng = SlotDecodeEngine(
            model, variables, max_batch=2, kv_page_size=16,
            paged_kernel=True,
        )
        traced_pk = keng._decode.trace(*decode_args(keng))
        specs.append(ProgramSpec(
            name="serve_decode[paged_kernel]", traced=traced_pk,
            lower_text=_lower_text_thunk(traced_pk) if with_lowered
            else None,
        ))

    if spec_k:
        seng = SlotDecodeEngine(
            model, variables, max_batch=2, spec_k=spec_k,
        )
        specs.append(ProgramSpec(
            name=f"spec_verify[k{spec_k}]",
            traced=seng._verify.trace(
                seng.params, seng.cache,
                jnp.zeros((2, spec_k + 1), jnp.int32),
                jnp.asarray(seng._pos), jnp.asarray(seng._caps),
                seng._temps, seng._rngs, seng._steps,
            ),
        ))

    # The int8 weight-quantized decode (ops/kernels/int8_matmul.py
    # behind ``quant_int8=True``): the quant collection rides as an
    # ordinary non-donated program input so hot-swapping scales never
    # recompiles — the trace pins that calling convention.
    qeng = SlotDecodeEngine(model, variables, max_batch=2,
                            quant_int8=True)
    traced_q = qeng._decode.trace(*decode_args(qeng), qeng._quant)
    specs.append(ProgramSpec(
        name="serve_decode[int8]", traced=traced_q,
        lower_text=_lower_text_thunk(traced_q) if with_lowered else None,
    ))

    # Batched-LoRA programs (serving/adapter_pool.py): the per-row
    # adapter-gathered decode step and the adapter-aware prefill — the
    # same contracts (collective uniformity, dtype policy, donation)
    # must hold with the pool stacks as ordinary inputs.
    from ml_trainer_tpu.serving.adapter_pool import AdapterConfig

    leng = SlotDecodeEngine(
        model, variables, max_batch=2,
        adapters=AdapterConfig(slots=3, rank=4, targets=("qkv", "proj")),
    )
    lora_vars = leng._lora_vars(leng._adapter_rows)
    traced_l = leng._decode.trace(
        leng.params, leng.cache, leng.tok, leng._temps, leng._rngs,
        leng._steps, lora_vars,
    )
    specs.append(ProgramSpec(
        name="serve_decode[lora]", traced=traced_l,
        lower_text=_lower_text_thunk(traced_l) if with_lowered else None,
    ))
    lprefill = leng._program(
        ("serve_prefill", leng._prefill_model, bucket),
        lambda: leng._build_prefill(bucket, lora=True),
    )
    specs.append(ProgramSpec(
        name=f"serve_prefill[lora,b{bucket}]",
        traced=lprefill.trace(
            leng.params, np.zeros((1, bucket), np.int32), np.int32(5),
            jnp.asarray(0.0, jnp.float32),
            np.zeros((2,), np.uint32), np.int32(0),
            leng._lora_vars(leng._adapter_rows[:1]),
        ),
    ))
    return specs


# ---------------------------------------------------------- pipeline side
def build_pipeline_specs(schedule: str = "1f1b",
                         n_micro: int = 4) -> List[ProgramSpec]:
    """Trace the tick-table pipeline engine's train program — the one
    place in the tree where ``lax.switch`` dispatch and ``ppermute``
    hops coexist, i.e. the program the collective-uniformity check
    exists for.  Needs >= 2 devices (a stage mesh); returns [] on a
    single-device host so the CLI degrades instead of failing."""
    if jax.device_count() < 2:
        return []
    from ml_trainer_tpu.parallel import create_mesh
    from ml_trainer_tpu.parallel.pipeline import (
        pipeline_apply,
        stack_stage_params,
    )

    mesh = create_mesh({"stage": 2}, devices=jax.devices()[:2])
    d = 8
    key = jax.random.PRNGKey(0)
    stage_params = stack_stage_params([
        {"w": jax.random.normal(jax.random.fold_in(key, s), (d, d))
              / np.sqrt(d)}
        for s in range(2)
    ])

    def stage_fn(p, mb):
        return jnp.tanh(mb @ p["w"])

    def loss(p, x):
        return pipeline_apply(
            stage_fn, p, x, mesh, schedule=schedule,
            n_microbatches=n_micro,
        ).sum()

    x = jnp.ones((n_micro * 2, d))
    traced = jax.jit(jax.value_and_grad(loss)).trace(stage_params, x)
    return [ProgramSpec(
        name=f"pipeline_train[{schedule}]",
        traced=traced,
        # Grad-of-params probe, not a full optimizer step: params are
        # live after it, so nothing here is donatable by design.
        min_donation_bytes=1 << 20,
    )]


def build_all_specs(with_lowered: bool = False) -> List[ProgramSpec]:
    return (
        build_train_specs(with_lowered=with_lowered)
        + build_decode_specs(with_lowered=with_lowered)
        + build_pipeline_specs()
    )
