"""Pallas kernel layer (ml_trainer_tpu/ops/kernels/).

Every kernel ships pinned to a lax reference: the Pallas body run in
interpret mode must equal the reference BIT-FOR-BIT on CPU (both sides
under jit — the mode every caller runs in; eager-vs-traced differs by
FMA fusion noise no real path sees).  On top of the kernel-level pins:
the real Server streams identical bytes with ``paged_kernel`` on/off,
the real Trainer walks a bit-identical trajectory with the fused Adam
tail on/off, opt-in knobs refuse unsupported configs up front, and the
int8 decode path clears the argmax-agreement quality gate on a
peaked-logit model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.ops.kernels import (
    adam_scalars,
    fused_adam_update,
    int8_matmul,
    paged_attention,
    paged_attention_reference,
    quantize_per_channel,
    quantize_tree,
    unscale_sqsum,
)


def _jrun(fn, *args, **kw):
    return jax.jit(lambda *a: fn(*a, **kw))(*args)


def _bits_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _paged_case(rng, b, h, d, ps, P, dtype, lengths):
    n_pages = b * P + 1  # + trash page 0
    q = jnp.asarray(rng.normal(size=(b, h, d)) * 0.5, dtype)
    kp, vp = (
        jnp.asarray(rng.normal(size=(n_pages, h, ps, d)) * 0.5, dtype)
        for _ in range(2)
    )
    table = jnp.asarray(
        1 + rng.permutation(n_pages - 1).reshape(b, P), jnp.int32
    )
    return q, kp, vp, table, jnp.asarray(lengths, jnp.int32)


# --------------------------------------------------- kernel-level pins
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize(
    "b,h,d,ps,P",
    [(2, 2, 8, 8, 2), (3, 4, 32, 16, 4)],  # VPU-lane and MXU-ish buckets
)
def test_paged_attention_interpret_parity(dtype, b, h, d, ps, P):
    """Ragged lengths — full row, length-1 (trash-page reads masked),
    partial last page — bit-equal to the gather reference."""
    rng = np.random.default_rng(0)
    lengths = [ps * P, 1, ps + 1][:b] + [ps * P] * max(0, b - 3)
    q, kp, vp, table, ln = _paged_case(rng, b, h, d, ps, P, dtype, lengths)
    got = _jrun(paged_attention, q, kp, vp, table, ln,
                implementation="pallas", interpret=True)
    want = _jrun(paged_attention_reference, q, kp, vp, table, ln)
    assert got.dtype == want.dtype
    assert _bits_equal(got, want)


def test_paged_attention_chain_fills_table():
    """Every non-trash page referenced exactly once (the pool exactly
    sized, nothing spare) and an all-trash table row: the mask, not the
    table contents, must decide what contributes."""
    rng = np.random.default_rng(1)
    q, kp, vp, table, ln = _paged_case(
        rng, 4, 2, 16, 8, 3, jnp.float32, [24, 24, 24, 1]
    )
    # Row 3 reads only token 0 of its first page; point the REST of its
    # row at the trash page — contents must not matter.
    table = table.at[3, 1:].set(0)
    got = _jrun(paged_attention, q, kp, vp, table, ln,
                implementation="pallas", interpret=True)
    want = _jrun(paged_attention_reference, q, kp, vp, table, ln)
    assert _bits_equal(got, want)


@pytest.mark.parametrize(
    "shape", [(7,), (128,), (3, 5), (64, 16), (2, 3, 4)]
)
def test_unscale_sqsum_shape_sweep(shape):
    """The division matches bitwise and the square-sum reduces in the
    reference's association order — including multi-axis leaves, whose
    per-axis reduction is shape-sensitive."""
    rng = np.random.default_rng(2)
    g = jnp.asarray(rng.normal(size=shape), jnp.float32)
    for denom in (2.0, jnp.float32(8.0)):
        for compute_sq in (True, False):
            got = _jrun(unscale_sqsum, g, denom, compute_sq=compute_sq,
                        implementation="pallas", interpret=True)
            want = _jrun(unscale_sqsum, g, denom, compute_sq=compute_sq,
                         implementation="reference")
            assert _bits_equal(got, want)
            assert (got[1] is None) == (not compute_sq)


def test_fused_adam_trajectory_matches_optax():
    """8 jitted steps of the fused tail (unscale -> global clip ->
    adam_scalars -> fused_adam_update -> opt_state rebuild) vs the
    unfused optax chain: params AND opt_state bit-identical at every
    step, so checkpoints are interchangeable mid-run."""
    shapes = {"w": (32, 16), "b": (16,), "emb": (64, 8)}
    keys = jax.random.split(jax.random.PRNGKey(4), len(shapes) + 1)
    params = {
        n: jax.random.normal(k, s, jnp.float32) * 0.02
        for (n, s), k in zip(shapes.items(), keys)
    }
    lr, clip, denom = 1e-2, 1.0, 4.0

    def sched(_count):
        return jnp.asarray(lr, jnp.float32)

    tx = optax.chain(optax.identity(), optax.adam(sched))
    one = jnp.asarray(1.0, jnp.float32)

    @jax.jit
    def ref_tail(g, p, st):
        g = jax.tree.map(lambda t: t / denom, g)
        sq = sum(
            jnp.sum(jnp.square(t.astype(jnp.float32)))
            for t in jax.tree.leaves(g)
        )
        factor = clip / jnp.maximum(jnp.sqrt(sq), clip)
        g = jax.tree.map(lambda t: t * factor, g)
        updates, new_st = tx.update(g, st, p)
        return optax.apply_updates(p, updates), new_st

    @jax.jit
    def fused_tail(g, p, st):
        _e, (adam_st, sched_st) = st
        g_def = jax.tree.structure(g)
        gs, sq = [], 0.0
        for t in jax.tree.leaves(g):
            th, s = unscale_sqsum(t, denom, compute_sq=True)
            gs.append(th)
            sq = sq + s
        factor = clip / jnp.maximum(jnp.sqrt(sq), clip)
        count_inc, bc1, bc2, step_size, sched_inc = adam_scalars(
            adam_st.count, sched_st.count, sched
        )
        outs = [
            fused_adam_update(t, pv, mu, nu, bc1=bc1, bc2=bc2,
                              step_size=step_size, lr_scale=one,
                              factor=factor)
            for t, pv, mu, nu in zip(
                gs, jax.tree.leaves(p),
                jax.tree.leaves(adam_st.mu), jax.tree.leaves(adam_st.nu),
            )
        ]
        new_p = jax.tree.unflatten(g_def, [o[0] for o in outs])
        new_st = (optax.EmptyState(), (
            optax.ScaleByAdamState(
                count=count_inc,
                mu=jax.tree.unflatten(g_def, [o[1] for o in outs]),
                nu=jax.tree.unflatten(g_def, [o[2] for o in outs]),
            ),
            optax.ScaleByScheduleState(count=sched_inc),
        ))
        return new_p, new_st

    p_ref = p_fused = params
    st_ref = st_fused = tx.init(params)
    for step in range(8):
        grads = {
            n: jax.random.normal(
                jax.random.fold_in(keys[-1], step * 10 + i), s,
                jnp.float32,
            )
            for i, (n, s) in enumerate(shapes.items())
        }
        p_ref, st_ref = ref_tail(grads, p_ref, st_ref)
        p_fused, st_fused = fused_tail(grads, p_fused, st_fused)
        assert _bits_equal(p_ref, p_fused), f"params diverged at {step}"
        assert _bits_equal(st_ref, st_fused), f"state diverged at {step}"


def test_int8_matmul_parity_and_quantize():
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 48)) * 0.1, jnp.float32)
    w = w.at[:, 0].set(0.0)  # all-zero column: scale must stay finite
    w_q, scale = quantize_per_channel(w)
    assert w_q.dtype == jnp.int8 and scale.shape == (48,)
    assert np.all(np.asarray(scale) > 0)
    # Symmetric per-channel round-trip: within half a quantization step.
    err = np.abs(np.asarray(w) - np.asarray(w_q, np.float32) * scale)
    assert np.all(err <= np.asarray(scale) * 0.5 + 1e-7)
    got = _jrun(int8_matmul, x, w_q, scale, implementation="pallas",
                interpret=True)
    want = _jrun(int8_matmul, x, w_q, scale, implementation="reference")
    assert _bits_equal(got, want)
    with pytest.raises(ValueError, match="int8"):
        int8_matmul(x, w.astype(jnp.float32), scale)


def test_quantize_tree_structure():
    model = get_model("gpt2_tiny", max_len=32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    quant = quantize_tree(variables["params"])

    def leaf_keys(d, out):
        for k, v in d.items():
            (leaf_keys(v, out) if isinstance(v, dict) else out.add(k))
        return out

    names = leaf_keys(quant, set())
    # Every target contributed its w/scale/b triple somewhere.
    for t in ("qkv", "proj", "fc_in", "fc_out"):
        assert {f"{t}_w", f"{t}_scale", f"{t}_b"} <= names, names
    # Nothing matched -> {} (callers refuse, never serve unquantized).
    assert quantize_tree(variables["params"], targets=("nope",)) == {}
    with pytest.raises(TypeError):
        quantize_tree([1, 2, 3])


# ------------------------------------------------ engine + trainer pins
@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, 1024, n), np.int32
    )


def _run_requests(model, variables, **server_kw):
    from ml_trainer_tpu.serving import Server

    prompts = [_prompt(s, n) for s, n in
               ((0, 5), (1, 3), (2, 12), (3, 7), (4, 17), (5, 9))]
    outs = []
    with Server(model, variables, max_batch=4, kv_page_size=16,
                **server_kw) as server:
        streams = [
            server.submit(p, 10, temperature=0.7, rng=42)
            if i == 3 else server.submit(p, 10)
            for i, p in enumerate(prompts)
        ]
        for s in streams:
            outs.append(np.asarray(s.result(timeout=300)))
    return outs


def test_server_paged_kernel_byte_identity(model_and_vars):
    """The fused-gather decode program streams the same bytes as the
    gather+flash program across ragged join/leave traffic, and its
    steady-state decode loop compiles nothing."""
    from ml_trainer_tpu.serving.engine import SlotDecodeEngine
    from ml_trainer_tpu.telemetry import compile_watch

    model, variables = model_and_vars
    base = _run_requests(model, variables, paged_kernel=False)
    paged = _run_requests(model, variables, paged_kernel=True)
    for a, b in zip(base, paged):
        np.testing.assert_array_equal(a, b)

    eng = SlotDecodeEngine(model, variables, max_batch=4,
                           kv_page_size=16, paged_kernel=True)
    cache, tok = eng.cache, eng.tok
    for _ in range(2):  # warmup builds the decode program
        cache, tok = eng._decode(
            eng.params, cache, tok, eng._temps, eng._rngs, eng._steps
        )
    jax.block_until_ready(tok)
    with compile_watch.expect_no_compiles("paged_kernel decode loop"):
        for _ in range(6):
            cache, tok = eng._decode(
                eng.params, cache, tok, eng._temps, eng._rngs,
                eng._steps,
            )
        jax.block_until_ready(tok)


def test_server_quant_int8_serves_deterministically(model_and_vars):
    """The int8 decode program is a different program (different bytes
    are fine — quantization changes the math) but a stable one: two
    identical runs stream identical bytes."""
    model, variables = model_and_vars
    a = _run_requests(model, variables, quant_int8=True)
    b = _run_requests(model, variables, quant_int8=True)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_kernel_knob_refusals(model_and_vars):
    from ml_trainer_tpu.serving.engine import SlotDecodeEngine

    model, variables = model_and_vars
    with pytest.raises(ValueError, match="paged_kernel needs paged KV"):
        SlotDecodeEngine(model, variables, max_batch=2, paged_kernel=True)
    with pytest.raises(ValueError, match="spec_k"):
        SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=16,
                         quant_int8=True, spec_k=2)
    with pytest.raises(ValueError, match="adapters"):
        SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=16,
                         quant_int8=True, adapters=object())


def test_trainer_fused_adam_refusals(tmp_path):
    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.data import SyntheticTokens

    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=0)
    common = dict(datasets=(ds, ds), epochs=1, batch_size=16,
                  metric=None, backend="cpu")
    with pytest.raises(ValueError, match="dp_update='sharded'"):
        Trainer(get_model("gpt2_tiny", vocab_size=256),
                model_dir=str(tmp_path / "a"), fused_adam=True,
                optimizer="adam", **common)
    with pytest.raises(ValueError, match="optimizer='adam'"):
        Trainer(get_model("gpt2_tiny", vocab_size=256),
                model_dir=str(tmp_path / "b"), fused_adam=True,
                optimizer="adamw", is_parallel=True,
                dp_update="sharded", **common)
    with pytest.raises(ValueError, match="weight_decay"):
        Trainer(get_model("gpt2_tiny", vocab_size=256),
                model_dir=str(tmp_path / "c"), fused_adam=True,
                optimizer="adam", weight_decay=0.1, is_parallel=True,
                dp_update="sharded", **common)


def test_trainer_fused_adam_golden_and_checkpoint_roundtrip(tmp_path):
    """sharded+adam auto-enables the fused tail; the trajectory — every
    loss AND every param bit — is identical to the unfused optax tail,
    one compiled program, and the fused run's state round-trips through
    the v2 checkpoint format unchanged (opt_state layout untouched)."""
    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.checkpoint import checkpoint as ckpt
    from ml_trainer_tpu.data import SyntheticTokens

    ds = SyntheticTokens(size=64, seq_len=32, vocab_size=256, seed=0)
    common = dict(
        datasets=(ds, ds), epochs=2, batch_size=16, seed=3, lr=0.01,
        optimizer="adam", metric=None, is_parallel=True, backend="cpu",
        dp_update="sharded",
    )
    t_ref = Trainer(get_model("gpt2_tiny", vocab_size=256),
                    model_dir=str(tmp_path / "ref"), fused_adam=False,
                    **common)
    assert not t_ref.fused_adam
    t_ref.fit()
    t_fused = Trainer(get_model("gpt2_tiny", vocab_size=256),
                      model_dir=str(tmp_path / "fused"), **common)
    assert t_fused.fused_adam  # None -> auto: eligible config
    t_fused.fit()
    assert t_fused._train_step._cache_size() == 1
    assert t_ref.train_losses == t_fused.train_losses
    assert _bits_equal(t_ref.state.params, t_fused.state.params)
    assert _bits_equal(t_ref.state.opt_state, t_fused.state.opt_state)

    path = ckpt.save_checkpoint(
        str(tmp_path / "ckpt"), t_fused.state, {"train_loss": []}, epoch=2
    )
    restored, _, _ = ckpt.restore_checkpoint(path, t_ref.state)
    assert _bits_equal(t_fused.state.params, restored.params)
    assert _bits_equal(t_fused.state.opt_state, restored.opt_state)


def test_int8_quality_gate(tmp_path):
    """Argmax agreement >= 99.5% with bounded relative logit error on a
    model with real top-1 margins: gpt2_tiny memorizes a deterministic
    successor map in 4 epochs (random next-token targets leave logits
    near-tied, which measures tie-breaking, not the kernel)."""
    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.data.datasets import ArrayDataset

    rng = np.random.default_rng(0)
    V, S, N = 64, 32, 64
    succ = rng.permutation(V)
    data = np.zeros((N, S), np.int32)
    data[:, 0] = rng.integers(0, V, N)
    for t in range(1, S):
        data[:, t] = succ[data[:, t - 1]]
    model = get_model("gpt2_tiny", vocab_size=V)
    trainer = Trainer(
        model,
        datasets=(ArrayDataset(data, np.roll(data, -1, axis=1), None),) * 2,
        model_dir=str(tmp_path / "q"), epochs=4, batch_size=16, seed=3,
        lr=0.01, optimizer="adamw", metric=None, backend="cpu",
    )
    trainer.fit()
    params = trainer.state.params
    toks = jnp.asarray(data[:8])
    lf = model.apply({"params": params}, toks, train=False)
    lq = model.clone(quant_int8=True).apply(
        {"params": params, "quant": quantize_tree(params)}, toks,
        train=False,
    )
    agreement = float((jnp.argmax(lf, -1) == jnp.argmax(lq, -1)).mean())
    rel_err = float(jnp.max(jnp.abs(lf - lq)) / jnp.max(jnp.abs(lf)))
    assert agreement >= 0.995, f"argmax agreement {agreement}"
    assert rel_err <= 0.02, f"relative logit error {rel_err}"
