"""Compressed (JPEG) shard pipeline: round trip and decoder parity.

The ADVICE-requested coverage for csrc/jpeg_decoder.cpp and its Python
surface: ``write_sharded_jpeg_dataset`` -> ``ShardedJpegDataset`` ->
``NativeLoader`` must hand back the SAME pixels the Python decode path
produces (both run csrc/jpeg_decoder.cpp — bit-equal), the native
decoder must match PIL/libjpeg to IDCT rounding (±3) including the
4:2:0 triangular-upsampling path, and corrupt streams must be reported
per epoch, not deferred into a later one.
"""

import io

import numpy as np
import pytest

from ml_trainer_tpu.data.native import (
    NativeLoader,
    jpeg_decode_np,
    native_available,
)
from ml_trainer_tpu.data.sharded import (
    ShardedImageDataset,
    ShardedJpegDataset,
    encode_jpeg_samples,
    write_sharded_jpeg_dataset,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ / native library unavailable"
)

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


def _images(n, h=32, w=32, seed=0):
    """Structured uint8 RGB images (gradients + texture + noise) — JPEG
    behaves realistically on these, unlike pure uniform noise."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    base = np.stack([
        xx * 255.0 / w,
        yy * 255.0 / h,
        128 + 96 * np.sin(xx / 5.0) * np.cos(yy / 7.0),
    ], -1)
    out = np.empty((n, h, w, 3), np.uint8)
    for i in range(n):
        out[i] = np.clip(
            np.roll(base, i * 3, axis=1) + rng.normal(0, 10, base.shape),
            0, 255,
        ).astype(np.uint8)
    return out


def _write(tmp_path, images, labels, subsampling=0, **kw):
    return write_sharded_jpeg_dataset(
        str(tmp_path / "jds"),
        encode_jpeg_samples(
            [(images, labels)], quality=88, subsampling=subsampling
        ),
        shape=images.shape[1:],
        **kw,
    )


def test_roundtrip_write_then_native_loader(tmp_path):
    """write_sharded_jpeg_dataset -> NativeLoader round trip: the C++
    worker's decoded pixels are bit-equal to the Python decode path
    (ShardedJpegDataset.batch), labels ride along, order preserved."""
    images = _images(40)
    labels = np.arange(40, dtype=np.int32) % 10
    root = _write(tmp_path, images, labels, samples_per_shard=16)  # 3 shards
    ds = ShardedJpegDataset(root)
    assert len(ds) == 40

    ref_px, ref_y = ds.batch(np.arange(40))  # python-side native decode
    loader = NativeLoader(
        ds, batch_size=8, shuffle=False, pad=0, flip=False,
        normalize=((0.0, 0.0, 0.0), (1.0, 1.0, 1.0)),
    )
    got_px, got_y = [], []
    for x, y in loader:
        # identity normalize: float = uint8 / 255, exactly invertible
        got_px.append(np.round(x * 255.0).astype(np.uint8))
        got_y.append(y)
    np.testing.assert_array_equal(np.concatenate(got_px), ref_px)
    np.testing.assert_array_equal(np.concatenate(got_y), ref_y)
    loader.stop()


@pytest.mark.parametrize("subsampling", [0, 1, 2],
                         ids=["444", "422", "420"])
def test_native_decoder_matches_pil(subsampling):
    """csrc/jpeg_decoder.cpp vs PIL/libjpeg on the same streams: equal to
    ±3 (IDCT rounding); subsampling=2 exercises the 2x triangular
    chroma-upsampling path."""
    images = _images(4, h=48, w=40, seed=subsampling)
    worst = 0
    for img in images:
        buf = io.BytesIO()
        Image.fromarray(img).save(
            buf, "JPEG", quality=88, subsampling=subsampling
        )
        data = np.frombuffer(buf.getvalue(), np.uint8)
        mine = jpeg_decode_np(data, img.shape)
        assert mine is not None and mine.shape == img.shape
        pil = np.asarray(Image.open(io.BytesIO(buf.getvalue())).convert("RGB"))
        d = np.abs(mine.astype(np.int32) - pil.astype(np.int32))
        worst = max(worst, int(d.max()))
        assert d.mean() < 0.5
    assert worst <= 3


def test_sharded_image_dataset_rejects_jpeg_shards(tmp_path):
    """The ADVICE high: a jpeg-codec root opened with the raw-pixel
    dataset must say 'use ShardedJpegDataset', not KeyError: 'x'."""
    images = _images(4)
    root = _write(tmp_path, images, np.zeros(4, np.int32))
    with pytest.raises(ValueError, match="ShardedJpegDataset"):
        ShardedImageDataset(root)


def _corrupt_sample(root, ds, idx=0):
    """Scramble sample ``idx``'s byte stream in shard 0 on disk."""
    o = ds.offset_tables[0]
    import json
    import os

    with open(os.path.join(root, "index.json")) as fp:
        shard0 = json.load(fp)["shards"][0]["j"]
    path = os.path.join(root, shard0)
    with open(path, "r+b") as fp:
        fp.seek(int(o[idx]))
        fp.write(b"\x00" * min(64, int(o[idx + 1] - o[idx])))


def test_corrupt_stream_raises_at_epoch_end(tmp_path):
    images = _images(16)
    root = _write(tmp_path, images, np.zeros(16, np.int32))
    ds = ShardedJpegDataset(root)
    _corrupt_sample(root, ds)
    ds = ShardedJpegDataset(root)  # re-map the corrupted bytes
    loader = NativeLoader(ds, batch_size=8, shuffle=False, pad=0,
                          flip=False)
    with pytest.raises(RuntimeError, match="JPEG decode"):
        for _ in loader:
            pass


def test_corrupt_stream_surfaces_on_stop_after_early_break(tmp_path):
    """An early ``break`` skips the epoch-end check; stop() must still
    report the corrupt samples the broken epoch consumed — and a loader
    over CLEAN data must stop() silently."""
    images = _images(16)
    root = _write(tmp_path, images, np.zeros(16, np.int32))
    ds = ShardedJpegDataset(root)
    _corrupt_sample(root, ds)
    ds = ShardedJpegDataset(root)
    loader = NativeLoader(ds, batch_size=4, shuffle=False, pad=0,
                          flip=False, queue_cap=1, num_threads=1)
    it = iter(loader)
    next(it)  # batch 0 holds the corrupt sample; break before epoch end
    del it
    with pytest.raises(RuntimeError, match="failed JPEG decode"):
        loader.stop()
    loader.stop()  # idempotent after the error was consumed

    clean_root = _write(tmp_path / "clean", _images(8),
                        np.zeros(8, np.int32))
    clean = NativeLoader(ShardedJpegDataset(clean_root), batch_size=4,
                         shuffle=False, pad=0, flip=False)
    for _ in clean:
        pass
    clean.stop()  # no decode errors -> no raise
