"""Native (C++) batch worker vs the Python pipeline — semantics parity for
the reference augmentation (crop pad-4 / flip / normalize,
ref: src/utils/functions.py:5-12)."""

import numpy as np
import pytest

from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.data.native import NativeLoader, native_available
from ml_trainer_tpu.data.sampler import ShardedSampler
from ml_trainer_tpu.utils.functions import CIFAR10_MEAN, CIFAR10_STD

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ / native library unavailable"
)


def test_native_loader_shapes_and_determinism():
    ds = SyntheticCIFAR10(size=64)
    loader = NativeLoader(ds, batch_size=16, seed=5)
    a = list(loader)
    assert len(a) == 4
    x, y = a[0]
    assert x.shape == (16, 32, 32, 3) and x.dtype == np.float32
    assert y.shape == (16,) and y.dtype == np.int32
    b = list(loader)  # same epoch -> identical batches
    for (x1, y1), (x2, y2) in zip(a, b):
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)
    loader.set_epoch(1)
    c = list(loader)
    assert not np.array_equal(a[0][1], c[0][1])


def test_native_values_match_python_pipeline_statistics():
    """No aug (pad=0, no flip): native output must exactly equal the Python
    ToFloat+Normalize path."""
    ds = SyntheticCIFAR10(size=32)
    loader = NativeLoader(
        ds, batch_size=32, shuffle=False, pad=0, flip=False, seed=0
    )
    x, y = next(iter(loader))
    expected = (
        ds.data.astype(np.float32) / 255.0 - np.asarray(CIFAR10_MEAN)
    ) / np.asarray(CIFAR10_STD)
    np.testing.assert_allclose(x, expected, atol=1e-5)
    np.testing.assert_array_equal(y, ds.targets)


def test_native_crop_produces_zero_padding_rows():
    """With pad=4, some crops must include the zero-padding border, whose
    normalized value is (0 - mean) / std."""
    ds = SyntheticCIFAR10(size=64)
    loader = NativeLoader(ds, batch_size=64, pad=4, flip=False, seed=1)
    x, _ = next(iter(loader))
    border_val = (0.0 - np.asarray(CIFAR10_MEAN)) / np.asarray(CIFAR10_STD)
    hits = np.isclose(x[:, 0, 0], border_val, atol=1e-5).all(axis=-1)
    assert hits.any()  # at least one sample cropped into the padding
    assert not hits.all()  # and not all of them


def test_native_loader_ragged_tail_drop_last_false():
    """drop_last=False + size % batch != 0: the index buffer is padded by
    wrapping (the C++ side always reads n_batches*batch_size indices), so
    the final batch repeats leading samples instead of reading out of
    bounds."""
    ds = SyntheticCIFAR10(size=50)
    loader = NativeLoader(
        ds, batch_size=16, shuffle=False, pad=0, flip=False,
        drop_last=False, seed=0,
    )
    batches = list(loader)
    assert len(loader) == 4 and len(batches) == 4
    # All batches full-size; the tail wraps to the start of the index order.
    for x, y in batches:
        assert x.shape == (16, 32, 32, 3) and y.shape == (16,)
    tail_labels = batches[-1][1]
    np.testing.assert_array_equal(tail_labels[:2], ds.targets[48:50])
    np.testing.assert_array_equal(tail_labels[2:], ds.targets[:14])


def test_native_loader_with_sharded_sampler():
    ds = SyntheticCIFAR10(size=64)
    sampler = ShardedSampler(64, num_replicas=2, rank=0, shuffle=True, seed=3)
    loader = NativeLoader(ds, batch_size=8, sampler=sampler)
    batches = list(loader)
    assert len(batches) == 4  # 32 shard samples / 8


def test_native_loader_trains_with_trainer(tmp_path):
    """NativeLoader feeds the real trainer step through prefetch."""
    import jax
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import prefetch_to_device

    ds = SyntheticCIFAR10(size=64)
    trainer = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
        model_dir=str(tmp_path),
    )
    loader = NativeLoader(ds, batch_size=16, seed=2)
    lr_scale = jax.numpy.asarray(1.0)
    state = trainer.state
    for x, y in prefetch_to_device(loader, size=2,
                                   sharding=trainer._batch_sharding):
        state, loss, metric = trainer._train_step(state, x, y, lr_scale)
    assert np.isfinite(float(loss))


def test_trainer_auto_selects_native_pipeline(tmp_path):
    """VERDICT r1 #4: the Trainer itself constructs the native loader when
    the dataset carries the reference augmentation pipeline."""
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    ds = SyntheticCIFAR10(size=64, transform=custom_pre_process_function())
    t = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
        model_dir=str(tmp_path), metric="accuracy",
    )
    assert isinstance(t.train_loader, NativeLoader)
    assert isinstance(t.val_loader, NativeLoader)
    t.fit()
    assert np.isfinite(t.train_losses[0])
    # Explicit opt-out:
    t2 = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
        model_dir=str(tmp_path / "py"), loader="python",
    )
    assert not isinstance(t2.train_loader, NativeLoader)


def test_trainer_loader_native_rejects_unsupported(tmp_path):
    from ml_trainer_tpu import Trainer, MLModel
    import pytest as _pytest

    ds = SyntheticCIFAR10(size=64)  # no transform -> python semantics
    with _pytest.raises(ValueError, match="native"):
        Trainer(
            MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
            model_dir=str(tmp_path), loader="native",
        )
    # auto falls back silently
    t = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
        model_dir=str(tmp_path), loader="auto",
    )
    assert not isinstance(t.train_loader, NativeLoader)
