"""Beyond-RAM sharded streaming dataset (VERDICT r3 #3).

``ShardedImageDataset`` memory-maps per-shard ``.npy`` files, so the
ImageNet-class input pipeline (BASELINE.json configs[1]) never copies the
dataset into process RAM; both the Python Loader and the C++ NativeLoader
(segment-table gather, csrc/batch_worker.cpp) must produce EXACTLY the
batches the in-memory ``ArrayDataset`` path produces — streaming is a
residency decision, not a semantics change.
"""

import numpy as np
import pytest

from ml_trainer_tpu import MLModel, Trainer
from ml_trainer_tpu.data import (
    ArrayDataset,
    Loader,
    ShardedImageDataset,
    write_sharded_dataset,
)
from ml_trainer_tpu.utils.functions import custom_pre_process_function


def _make(root, n=100, seed=0, hw=8, shard=32):
    """Write a small sharded dataset in ragged chunks; return (dir, x, y)."""
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, size=(n, hw, hw, 3), dtype=np.uint8)
    y = rng.integers(0, 10, size=(n,)).astype(np.int32)
    # Deliberately ragged chunk sizes: the writer re-chunks to `shard`.
    cuts = [0, 7, 40, 41, 90, n]
    chunks = [(x[a:b], y[a:b]) for a, b in zip(cuts, cuts[1:])]
    write_sharded_dataset(str(root), chunks, samples_per_shard=shard)
    return str(root), x, y


def test_write_and_read_back(tmp_path):
    root, x, y = _make(tmp_path / "ds")
    ds = ShardedImageDataset(root)
    assert len(ds) == 100
    assert len(ds.shard_maps) == 4  # 32+32+32+4
    assert all(isinstance(m, np.memmap) for m in ds.shard_maps)
    # Random single-item and cross-shard batched gathers match the source.
    for i in (0, 31, 32, 99):
        xi, yi = ds[i]
        np.testing.assert_array_equal(xi, x[i])
        assert yi == y[i]
    sel = np.asarray([5, 33, 64, 99, 0, 32])  # touches every shard
    bx, by = ds.batch(sel)
    np.testing.assert_array_equal(bx, x[sel])
    np.testing.assert_array_equal(by, y[sel])
    # Python indexing semantics match ArrayDataset.
    xi, yi = ds[-1]
    np.testing.assert_array_equal(xi, x[-1])
    assert yi == y[-1]
    with pytest.raises(IndexError):
        ds[100]
    with pytest.raises(IndexError):
        ds[-101]


def test_python_loader_streaming_equals_in_memory(tmp_path):
    root, x, y = _make(tmp_path / "ds")
    transform = custom_pre_process_function()
    # Same transform OBJECT semantics, same seeds -> identical batches.
    lt_mem = Loader(ArrayDataset(x, y, None), batch_size=16, shuffle=True,
                    seed=3)
    lt_str = Loader(ShardedImageDataset(root), batch_size=16, shuffle=True,
                    seed=3)
    for (ax, ay), (bx, by) in zip(lt_mem, lt_str):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)
    assert transform is not None  # (transform path exercised in fit below)


def test_native_loader_streaming_equals_in_memory(tmp_path):
    pytest.importorskip("ctypes")
    from ml_trainer_tpu.data.native import NativeLoader, native_available

    if not native_available():
        pytest.skip("native worker unavailable (no g++)")
    root, x, y = _make(tmp_path / "ds", n=96, hw=32)
    mem = NativeLoader(ArrayDataset(x, y, None), batch_size=16, shuffle=True,
                       seed=3)
    streaming = NativeLoader(ShardedImageDataset(root), batch_size=16,
                             shuffle=True, seed=3)
    mem.set_epoch(1)
    streaming.set_epoch(1)
    batches_mem, batches_str = list(mem), list(streaming)
    assert len(batches_mem) == len(batches_str) == 6
    for (ax, ay), (bx, by) in zip(batches_mem, batches_str):
        np.testing.assert_array_equal(ax, bx)  # identical augmentation draws
        np.testing.assert_array_equal(ay, by)


def test_no_full_copy_in_ram(tmp_path):
    """The dataset object holds only maps + labels: nothing the size of
    the images lives in process-owned memory."""
    root, x, y = _make(tmp_path / "ds", n=100)
    ds = ShardedImageDataset(root)
    owned = ds.targets.nbytes + ds.shard_starts.nbytes
    assert owned < x.nbytes / 10
    # NativeLoader over it must not copy the segments either.
    from ml_trainer_tpu.data.native import NativeLoader, native_available

    if native_available():
        nl = NativeLoader(ds, batch_size=10)
        for seg, m in zip(nl._segments, ds.shard_maps):
            assert seg.base is m or isinstance(seg, np.memmap), (
                "segment was copied out of the mapping"
            )


def test_ingest_image_folder(tmp_path):
    """ImageFolder-layout JPEG/PNG trees decode + resize into the sharded
    format with sorted-name class labels (the ImageNet ingestion path)."""
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    from ml_trainer_tpu.data.sharded import ingest_image_folder

    rng = np.random.default_rng(0)
    src = tmp_path / "raw"
    for cls in ("dog", "cat"):  # sorted -> cat=0, dog=1
        (src / cls).mkdir(parents=True)
    for i in range(5):
        Image.fromarray(
            rng.integers(0, 256, (37, 53, 3), dtype=np.uint8)
        ).save(src / "dog" / f"d{i}.png")
    for i in range(3):
        Image.fromarray(
            rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
        ).save(src / "cat" / f"c{i}.jpg")
    dst = ingest_image_folder(
        str(src), str(tmp_path / "sharded"), size=(16, 16),
        samples_per_shard=4, decode_batch=3,
    )
    ds = ShardedImageDataset(dst)
    assert len(ds) == 8 and ds.shape == (16, 16, 3)
    assert len(ds.shard_maps) == 2  # 4 + 4
    # cat files come first (sorted class names), labeled 0.
    np.testing.assert_array_equal(ds.targets[:3], 0)
    np.testing.assert_array_equal(ds.targets[3:], 1)
    import json as _json
    import os as _os

    index = _json.load(open(_os.path.join(dst, "index.json")))
    assert index["classes"] == ["cat", "dog"]
    assert PIL is not None


@pytest.mark.slow
def test_fit_streams_sharded_dataset(tmp_path):
    """End-to-end: fit() over a sharded on-disk dataset with the reference
    augmentation — through loader='auto' (native path when available) —
    matches the identical in-memory run batch for batch."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(128, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, size=(128,)).astype(np.int32)
    write_sharded_dataset(str(tmp_path / "train"), [(x, y)],
                          samples_per_shard=50)
    xv = rng.integers(0, 256, size=(32, 32, 32, 3), dtype=np.uint8)
    yv = rng.integers(0, 10, size=(32,)).astype(np.int32)
    write_sharded_dataset(str(tmp_path / "val"), [(xv, yv)],
                          samples_per_shard=50)
    transform = custom_pre_process_function()

    def run(train_ds, val_ds, workdir):
        t = Trainer(
            MLModel(), datasets=(train_ds, val_ds), epochs=2, batch_size=16,
            model_dir=str(workdir), seed=9, lr=0.01, optimizer="adam",
            metric=None,
        )
        t.fit()
        return t.train_losses

    train_s = ShardedImageDataset(str(tmp_path / "train"), transform)
    val_s = ShardedImageDataset(str(tmp_path / "val"), transform)
    losses_stream = run(train_s, val_s, tmp_path / "m1")
    losses_mem = run(
        ArrayDataset(x, y, transform), ArrayDataset(xv, yv, transform),
        tmp_path / "m2",
    )
    assert losses_stream == pytest.approx(losses_mem, rel=1e-6)
