"""Elastic resume onto a DIFFERENT mesh (VERDICT r3 #5, ROADMAP #1).

Train on one device count, resume on another, and the trajectory must
continue exactly where an uninterrupted run would have gone — shrink
(8 -> 4, the preemption case) and scale-up (4 -> 8), for every
checkpoint flavor the repo writes:

* v2 full host-array trees, re-placed onto the new mesh;
* v3 per-host shards (ZeRO-1 moments) stitched onto the new shard grid;
* **fsdp** — rule-sharded MODEL kernels over a ``data x fsdp`` mesh: the
  reshard stitches model shards across DIFFERENT fsdp grids (the
  non-pure-DP case ROADMAP #1 called out as impossible before
  resilience/elastic.py).

The mid-epoch case: a preemption fault lands between step checkpoints,
the emergency checkpoint carries the batch cursor, and the resume at a
DIFFERENT topology still reproduces the uninterrupted trajectory.
"""

import os
import subprocess
import sys

import pytest

_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "elastic_worker.py"
)


def _run(ndev, phase, workdir, flavor, fault=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device topology
    env.pop("ML_TRAINER_TPU_FAULTS", None)
    cmd = [sys.executable, _WORKER, str(ndev), phase, str(workdir), flavor]
    if fault:
        cmd.append(fault)
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=420, env=env,
    )
    assert proc.returncode == 0, (
        f"{phase}@{ndev}dev failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "WORKER_DONE" in proc.stdout
    line = next(
        ln for ln in proc.stdout.splitlines() if ln.startswith("LOSSES ")
    )
    return eval(line[len("LOSSES "):])  # list literal printed by the worker


@pytest.mark.slow
@pytest.mark.parametrize(
    "flavor,first_ndev,resume_ndev",
    [
        ("v2", 8, 4),    # v2, preempted onto a smaller slice
        ("v3", 8, 4),    # v3, smaller slice
        ("v3", 4, 8),    # v3, resumed onto MORE devices (scale-up)
        ("fsdp", 8, 4),  # model-sharded kernels: fsdp grid 4 -> 2
        ("fsdp", 4, 8),  # model-sharded kernels: fsdp grid 2 -> 4
    ],
    ids=["v2-shrink", "v3-shrink", "v3-grow", "fsdp-shrink", "fsdp-grow"],
)
def test_resume_on_different_mesh(tmp_path, flavor, first_ndev, resume_ndev):
    ref = _run(first_ndev, "full", tmp_path / "ref", flavor)
    first = _run(first_ndev, "first", tmp_path / "elastic", flavor)
    resumed = _run(resume_ndev, "resume", tmp_path / "elastic", flavor)
    assert len(ref) == 4 and len(first) == 2 and len(resumed) == 4
    # The resumed run re-reports the first two epochs from the checkpoint
    # history, then continues them on the new mesh.
    assert resumed[:2] == pytest.approx(first, abs=1e-7)
    # Device count changes the reduction tree, not the math.
    assert resumed == pytest.approx(ref, rel=2e-4)


@pytest.mark.slow
def test_mid_epoch_emergency_resume_at_different_topology(tmp_path):
    """A preemption fault mid-epoch-2 on 8 devices; the emergency
    checkpoint (batch cursor + epoch accumulators) resumes on 4 devices
    — non-pure-DP (fsdp kernels) — and the full trajectory equals the
    uninterrupted 8-device run's."""
    ref = _run(8, "full", tmp_path / "ref", "fsdp")
    first = _run(
        8, "first_mid", tmp_path / "elastic", "fsdp",
        fault="preempt@step=6",
    )
    resumed = _run(4, "resume", tmp_path / "elastic", "fsdp")
    # The interrupted run completed only epoch 1 (preempted inside 2).
    assert len(first) == 1 and len(ref) == 4 and len(resumed) == 4
    assert resumed[0] == pytest.approx(first[0], abs=1e-7)
    assert resumed == pytest.approx(ref, rel=2e-4)
