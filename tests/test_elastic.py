"""Elastic resume onto a DIFFERENT mesh (VERDICT r3 #5).

Train 2 epochs on one device count, resume on another, and the
trajectory must continue exactly where an uninterrupted run would have
gone — shrink (8 -> 4, the preemption case) for both checkpoint formats
(v2 full host arrays re-placed; v3 per-host shards stitched onto the
new shard grid), and scale-UP (4 -> 8) for v3.  This is the
preemption-recovery capability the reference lacks entirely
(SURVEY.md §5): a TPU job that comes back on a different slice shape
keeps training.
"""

import os
import subprocess
import sys

import pytest

_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "elastic_worker.py"
)


def _run(ndev, phase, workdir, sharded):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device topology
    proc = subprocess.run(
        [sys.executable, _WORKER, str(ndev), phase, str(workdir),
         "1" if sharded else "0"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert proc.returncode == 0, (
        f"{phase}@{ndev}dev failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "WORKER_DONE" in proc.stdout
    line = next(
        ln for ln in proc.stdout.splitlines() if ln.startswith("LOSSES ")
    )
    return eval(line[len("LOSSES "):])  # list literal printed by the worker


@pytest.mark.slow
@pytest.mark.parametrize(
    "sharded,first_ndev,resume_ndev",
    [
        (False, 8, 4),  # v2, preempted onto a smaller slice
        (True, 8, 4),   # v3, smaller slice
        (True, 4, 8),   # v3, resumed onto MORE devices (scale-up)
    ],
    ids=["v2-shrink", "v3-shrink", "v3-grow"],
)
def test_resume_on_different_mesh(tmp_path, sharded, first_ndev, resume_ndev):
    ref = _run(first_ndev, "full", tmp_path / "ref", sharded)
    first = _run(first_ndev, "first", tmp_path / "elastic", sharded)
    resumed = _run(resume_ndev, "resume", tmp_path / "elastic", sharded)
    assert len(ref) == 4 and len(first) == 2 and len(resumed) == 4
    # The resumed run re-reports the first two epochs from the checkpoint
    # history, then continues them on the new mesh.
    assert resumed[:2] == pytest.approx(first, abs=1e-7)
    # Device count changes the reduction tree, not the math.
    assert resumed == pytest.approx(ref, rel=2e-4)
