"""Worker for tests/test_elastic.py — one phase of an elastic-resume run.

Each invocation is a fresh process so the virtual device count can differ
between phases: a checkpoint written on an 8-device mesh is resumed on a
4-device mesh (the TPU-preemption reality: the replacement slice need not
match the one that died).  Global-batch semantics make the trajectory
device-count-invariant, so the resumed run must continue the
uninterrupted reference's losses.

Usage: python elastic_worker.py <ndev> <phase> <workdir> <sharded01>
  phase: full   — train 4 epochs from scratch
         first  — train 2 epochs (leaves checkpoints behind)
         resume — train to epoch 4 with fit(resume=True)
"""

import os
import sys

ndev, phase, workdir, sharded = (
    int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4] == "1"
)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={ndev}"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == ndev, jax.device_count()

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ml_trainer_tpu import MLModel, Trainer  # noqa: E402
from ml_trainer_tpu.data import SyntheticCIFAR10  # noqa: E402

datasets = (
    SyntheticCIFAR10(size=64, seed=0),
    SyntheticCIFAR10(size=32, seed=1),
)
epochs = 2 if phase == "first" else 4
t = Trainer(
    MLModel(), datasets=datasets, epochs=epochs, batch_size=16,
    model_dir=workdir, is_parallel=True, backend="cpu", seed=11, lr=0.01,
    optimizer="adam", metric=None,
    shard_opt_state=sharded, sharded_checkpoint=sharded,
)
t.fit(resume=(phase == "resume"))
assert all(np.isfinite(v) for v in t.train_losses)
print(f"LOSSES {t.train_losses}", flush=True)
print("WORKER_DONE", flush=True)
