"""Worker for tests/test_elastic.py — one phase of an elastic-resume run.

Each invocation is a fresh process so the virtual device count can differ
between phases: a checkpoint written on an 8-device mesh is resumed on a
4-device mesh (the TPU-preemption reality: the replacement slice need not
match the one that died).  Global-batch semantics make the trajectory
device-count-invariant, so the resumed run must continue the
uninterrupted reference's losses.

Usage: python elastic_worker.py <ndev> <phase> <workdir> <flavor> [fault]
  phase:  full      — train 4 epochs from scratch
          first     — train 2 epochs (leaves checkpoints behind)
          first_mid — train with ``fault`` injected (a mid-epoch preempt:
                      emergency checkpoint + clean exit, asserted)
          resume    — train to epoch 4 with fit(resume=True)
  flavor: v2   — pure DP, host-0 full-tree checkpoints
          v3   — pure DP + ZeRO-1 moments, per-host sharded checkpoints
          fsdp — data×fsdp mesh with rule-sharded dense kernels
                 (non-pure-DP: the reshard must stitch MODEL shards
                 across different fsdp grids), v3 checkpoints
  fault:  optional ``ML_TRAINER_TPU_FAULTS`` spec (first_mid phases);
          implies step-granular checkpoints (save_every_steps=2)
"""

import os
import sys

ndev, phase, workdir, flavor = (
    int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4]
)
fault = sys.argv[5] if len(sys.argv) > 5 else None
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={ndev}"
).strip()
if fault:
    os.environ["ML_TRAINER_TPU_FAULTS"] = fault

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == ndev, jax.device_count()

import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ml_trainer_tpu import MLModel, Trainer  # noqa: E402
from ml_trainer_tpu.data import SyntheticCIFAR10  # noqa: E402

datasets = (
    SyntheticCIFAR10(size=64, seed=0),
    SyntheticCIFAR10(size=32, seed=1),
)
kw = {}
if flavor == "v3":
    kw.update(shard_opt_state=True, sharded_checkpoint=True)
elif flavor == "fsdp":
    # Rule-sharded dense kernels over a genuine model axis: the elastic
    # restore must re-stitch MODEL shards (not just replicas) onto a
    # DIFFERENT fsdp grid (8 devices: fsdp=4; 4 devices: fsdp=2).
    kw.update(
        mesh_shape={"data": 2, "fsdp": ndev // 2},
        sharding_rules=[(r"fc\d/kernel", P("fsdp"))],
        sharded_checkpoint=True,
    )
elif flavor != "v2":
    raise SystemExit(f"unknown flavor {flavor!r}")
if fault:
    kw.update(save_every_steps=2)
epochs = 2 if phase == "first" else 4
t = Trainer(
    MLModel(), datasets=datasets, epochs=epochs, batch_size=16,
    model_dir=workdir, is_parallel=True, backend="cpu", seed=11, lr=0.01,
    optimizer="adam", metric=None, **kw,
)
t.fit(resume=(phase == "resume"))
if phase == "first_mid":
    assert t.preempted, "injected preempt fault did not trip fit()"
    marker = os.path.join(workdir, "checkpoints", "PREEMPTED.json")
    assert os.path.exists(marker), "no clean-exit marker after preemption"
assert all(np.isfinite(v) for v in t.train_losses)
print(f"LOSSES {t.train_losses}", flush=True)
print("WORKER_DONE", flush=True)
