"""Data layer tests: transforms, datasets, sampler, loader, prefetch
(the DataLoader/DistributedSampler analog, ref: src/trainer.py:60-64, 77-79;
src/utils/functions.py:5-12)."""

import jax
import numpy as np
import pytest

from ml_trainer_tpu.data import (
    ArrayDataset,
    Compose,
    Loader,
    Normalize,
    RandomCrop,
    RandomHorizontalFlip,
    ShardedSampler,
    SyntheticCIFAR10,
    ToFloat,
    prefetch_to_device,
)
from ml_trainer_tpu.utils.functions import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    custom_pre_process_function,
)


def test_random_crop_shape_and_determinism():
    batch = np.arange(2 * 32 * 32 * 3, dtype=np.uint8).reshape(2, 32, 32, 3)
    crop = RandomCrop(32, padding=4)
    out1 = crop(batch, np.random.default_rng(0))
    out2 = crop(batch, np.random.default_rng(0))
    assert out1.shape == (2, 32, 32, 3)
    np.testing.assert_array_equal(out1, out2)


def test_random_flip_flips_some_not_all():
    batch = np.random.default_rng(0).integers(0, 255, (64, 8, 8, 3)).astype(np.uint8)
    out = RandomHorizontalFlip()(batch, np.random.default_rng(1))
    flipped = (out != batch).any(axis=(1, 2, 3))
    assert 0 < flipped.sum() < 64
    # flipped samples are exact mirrors
    idx = int(np.argmax(flipped))
    np.testing.assert_array_equal(out[idx], batch[idx, :, ::-1])


def test_normalize_constants_match_reference():
    """Mean/std are the reference's CIFAR-10 constants
    (ref: src/utils/functions.py:10)."""
    assert CIFAR10_MEAN == (0.4914, 0.4822, 0.4465)
    assert CIFAR10_STD == (0.2023, 0.1994, 0.2010)
    pipeline = custom_pre_process_function()
    batch = np.full((2, 32, 32, 3), 128, dtype=np.uint8)
    out = pipeline(batch, np.random.default_rng(0))
    assert out.dtype == np.float32
    expected = (128 / 255.0 - np.array(CIFAR10_MEAN)) / np.array(CIFAR10_STD)
    assert np.allclose(out[0, 16, 16], expected, atol=1e-5)


def test_tofloat_scales_uint8():
    batch = np.array([[[[255, 0, 128]]]], dtype=np.uint8)
    out = ToFloat()(batch, np.random.default_rng(0))
    assert np.allclose(out.ravel(), [1.0, 0.0, 128 / 255.0])


def test_sharded_sampler_partitions_disjointly():
    """DistributedSampler semantics (ref: src/trainer.py:60-61): shards are
    disjoint, equally sized, together cover the dataset."""
    n = 103
    shards = [
        ShardedSampler(n, num_replicas=4, rank=r, shuffle=True, seed=7).indices()
        for r in range(4)
    ]
    sizes = {len(s) for s in shards}
    assert sizes == {26}  # ceil(103/4)
    all_idx = np.concatenate(shards)
    assert len(np.unique(all_idx)) == n  # full coverage (with wrap padding)


def test_sharded_sampler_reshuffles_per_epoch():
    s = ShardedSampler(50, num_replicas=2, rank=0, shuffle=True, seed=0)
    a = s.indices().copy()
    s.set_epoch(1)
    b = s.indices()
    assert not np.array_equal(a, b)


def test_loader_batching_and_len():
    ds = ArrayDataset(np.arange(10)[:, None].astype(np.float32), np.arange(10))
    loader = Loader(ds, batch_size=3)
    assert len(loader) == 4
    batches = list(loader)
    assert [len(x) for x, _ in batches] == [3, 3, 3, 1]
    loader_drop = Loader(ds, batch_size=3, drop_last=True)
    assert len(loader_drop) == 3


def test_loader_shuffle_is_epoch_deterministic():
    ds = SyntheticCIFAR10(size=32)
    loader = Loader(ds, batch_size=8, shuffle=True, seed=3)
    a = [y.copy() for _, y in loader]
    b = [y.copy() for _, y in loader]
    np.testing.assert_array_equal(np.concatenate(a), np.concatenate(b))
    loader.set_epoch(1)
    c = [y.copy() for _, y in loader]
    assert not np.array_equal(np.concatenate(a), np.concatenate(c))


def test_loader_applies_batched_transform():
    ds = SyntheticCIFAR10(size=16, transform=custom_pre_process_function())
    x, y = next(iter(Loader(ds, batch_size=16)))
    assert x.dtype == np.float32 and x.shape == (16, 32, 32, 3)
    assert y.shape == (16,)


def test_prefetch_to_device_yields_device_arrays():
    ds = SyntheticCIFAR10(size=16)
    loader = Loader(ds, batch_size=8)
    out = list(prefetch_to_device(loader, size=2))
    assert len(out) == 2
    assert isinstance(out[0][0], jax.Array)


def test_prefetch_with_mesh_sharding_splits_batch():
    from ml_trainer_tpu.parallel import batch_sharding, create_mesh

    mesh = create_mesh()  # 8 simulated devices
    ds = SyntheticCIFAR10(size=32)
    loader = Loader(ds, batch_size=16)
    x, y = next(iter(prefetch_to_device(loader, sharding=batch_sharding(mesh))))
    assert len(x.sharding.device_set) == 8
    assert x.shape == (16, 32, 32, 3)


def test_as_dataset_adapts_foreign_per_sample_transform():
    """A reference-style dataset carrying a torch-style per-sample transform
    (one argument, returns a CHW torch tensor — the torchvision ToTensor
    shape, ref: main.py:14-18) must keep working through the batched
    Loader."""
    import torch

    def torchvision_style(img):  # PIL Image or HWC ndarray in, CHW tensor out
        arr = np.asarray(img, dtype=np.float32) / 255.0
        return torch.from_numpy(arr.transpose(2, 0, 1))

    class FakeTorchvisionDataset:
        def __init__(self):
            rng = np.random.default_rng(0)
            self.data = rng.integers(0, 255, (8, 32, 32, 3)).astype(np.uint8)
            self.targets = list(rng.integers(0, 10, 8))
            self.transform = torchvision_style

        def __len__(self):
            return len(self.data)

        def __getitem__(self, i):
            return self.data[i], self.targets[i]

    loader = Loader(FakeTorchvisionDataset(), batch_size=4)
    x, y = next(iter(loader))
    assert x.shape == (4, 32, 32, 3)  # back to NHWC float
    assert x.dtype == np.float32
    assert x.max() <= 1.0


def test_loader_ignores_non_callable_batch_attribute():
    """A user dataset whose ``batch`` attribute is data (say an int)
    must take the per-item path, not the vectorized-gather fast path
    (ADVICE r4)."""

    class WithBatchField:
        batch = 64  # unrelated to the batch(indices) protocol

        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.full((2, 2, 3), i, np.float32), i % 2

    loader = Loader(WithBatchField(), batch_size=4, shuffle=False)
    xb, yb = next(iter(loader))
    assert xb.shape == (4, 2, 2, 3) and list(yb) == [0, 1, 0, 1]
